"""Prometheus-style metrics registry (reference: scheduler/metrics/,
trainer/metrics/, grpc_prometheus interceptors).

Counters/gauges/histograms with label support and text exposition
(Prometheus format), dependency-free.  Services define their metric sets
at module scope the way the reference does (metrics.go:44-180).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline (exposition-format spec).  Without this a hostile
    label value (a URL with a quote, a multi-line error string) splits
    the sample line and corrupts every series after it in the scrape."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(v: str) -> str:
    """# HELP text escaping: backslash and newline only (quotes are legal
    in help text per the exposition format)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _current_trace_id() -> Optional[str]:
    """Active trace id on this thread (exemplar hook): one thread-local
    read through the tracer — cheap enough for per-observe use."""
    from .tracing import current_trace_id

    return current_trace_id()


class _Metric:
    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._mu = threading.Lock()

    def state(self) -> Optional[Dict[str, Any]]:
        """Serializable snapshot for the metric journal (DESIGN.md §23);
        None = this metric kind is not journaled."""
        return None

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        # Hot path (per-observe): equal length + every name present is
        # equivalent to set equality without building two sets per call.
        names = self.label_names
        if len(labels) == len(names):
            try:
                return tuple([labels[n] for n in names])
            except KeyError:
                pass
        raise ValueError(
            f"{self.name}: labels {sorted(labels)} != {sorted(self.label_names)}"
        )

    def _fmt_labels(self, key: Tuple[str, ...]) -> str:
        if not key:
            return ""
        inner = ",".join(
            f'{n}="{_escape_label_value(v)}"'
            for n, v in zip(self.label_names, key)
        )
        return "{" + inner + "}"


class _CounterChild:
    """Label-bound counter handle: the per-call kwargs-dict build and
    label validation are paid ONCE at bind time — serving hot paths
    (scheduler featcache/evaluator) observe through these."""

    __slots__ = ("_metric", "_key_t")

    def __init__(self, metric: "Counter", key: Tuple[str, ...]) -> None:
        self._metric = metric
        self._key_t = key

    def inc(self, amount: float = 1.0) -> None:
        m = self._metric
        with m._mu:
            m._values[self._key_t] = m._values.get(self._key_t, 0.0) + amount


class Counter(_Metric):
    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()) -> None:
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._mu:
            self._values[key] = self._values.get(key, 0.0) + amount

    def labels(self, **labels: str) -> _CounterChild:
        return _CounterChild(self, self._key(labels))

    def value(self, **labels: str) -> float:
        with self._mu:
            return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}", f"# TYPE {self.name} counter"]
        with self._mu:
            for key, v in sorted(self._values.items()):
                out.append(f"{self.name}{self._fmt_labels(key)} {v}")
        return out

    def state(self) -> Dict[str, Any]:
        with self._mu:
            series = [[list(k), v] for k, v in sorted(self._values.items())]
        return {"type": "counter", "labels": list(self.label_names), "series": series}


class Gauge(_Metric):
    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()) -> None:
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        with self._mu:
            self._values[self._key(labels)] = value

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._mu:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._mu:
            return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}", f"# TYPE {self.name} gauge"]
        with self._mu:
            for key, v in sorted(self._values.items()):
                out.append(f"{self.name}{self._fmt_labels(key)} {v}")
        return out

    def state(self) -> Dict[str, Any]:
        with self._mu:
            series = [[list(k), v] for k, v in sorted(self._values.items())]
        return {"type": "gauge", "labels": list(self.label_names), "series": series}


DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class _HistogramChild:
    """Label-bound histogram handle (see _CounterChild).  Caches the
    per-key bucket-count list so a hot-path observe is one bisect + one
    locked region of three list/dict ops."""

    __slots__ = ("_metric", "_key_t", "_counts")

    def __init__(self, metric: "Histogram", key: Tuple[str, ...]) -> None:
        self._metric = metric
        self._key_t = key
        self._counts = None

    def observe(self, value: float) -> None:
        m = self._metric
        idx = bisect.bisect_left(m.buckets, value)
        key = self._key_t
        tid = _current_trace_id()
        with m._mu:
            counts = self._counts
            if counts is None:
                counts = m._counts.get(key)
                if counts is None:
                    counts = m._counts[key] = [0] * len(m.buckets)
                self._counts = counts
            if idx < len(counts):
                counts[idx] += 1
            m._sums[key] = m._sums.get(key, 0.0) + value
            m._totals[key] = m._totals.get(key, 0) + 1
            if tid is not None:
                m._exemplars.setdefault(key, {})[idx] = tid


class Histogram(_Metric):
    def __init__(
        self,
        name: str,
        help: str,
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> None:
        super().__init__(name, help, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}
        # Exemplars: last trace id observed per (key, bucket) — recorded
        # under the existing metric lock (one dict store when a span is
        # active, nothing otherwise), exposed as /debug/exemplars JSON so
        # a slow-bucket latency joins to its flight-recorder trace.
        self._exemplars: Dict[Tuple[str, ...], Dict[int, str]] = {}

    def observe(self, value: float, **labels: str) -> None:
        # Counts are stored PER-BUCKET (one increment per observe) and
        # cumulated at expose time — the cumulative-update loop over the
        # bucket ladder showed up on the scheduler's per-announce path.
        self._observe_key(self._key(labels), value)

    def _observe_key(self, key: Tuple[str, ...], value: float) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        tid = _current_trace_id()
        with self._mu:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * len(self.buckets)
            if idx < len(counts):
                counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1
            if tid is not None:
                self._exemplars.setdefault(key, {})[idx] = tid

    def labels(self, **labels: str) -> "_HistogramChild":
        return _HistogramChild(self, self._key(labels))

    def exemplars(self) -> Dict[str, Dict[str, str]]:
        """``{label-set: {le: trace_id}}`` — the last trace id observed
        per bucket (``le`` is the bucket's upper bound, ``+Inf`` for the
        overflow bucket)."""
        with self._mu:
            snap = {k: dict(v) for k, v in self._exemplars.items()}
        out: Dict[str, Dict[str, str]] = {}
        for key, per_bucket in snap.items():
            label_str = self._fmt_labels(key) or "{}"
            out[label_str] = {
                (str(self.buckets[i]) if i < len(self.buckets) else "+Inf"): tid
                for i, tid in sorted(per_bucket.items())
            }
        return out

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}", f"# TYPE {self.name} histogram"]
        with self._mu:
            for key, counts in sorted(self._counts.items()):
                base = self._fmt_labels(key)[1:-1] if key else ""
                running = 0
                for le, c in zip(self.buckets, counts):
                    running += c
                    sep = "," if base else ""
                    out.append(f'{self.name}_bucket{{{base}{sep}le="{le}"}} {running}')
                sep = "," if base else ""
                out.append(f'{self.name}_bucket{{{base}{sep}le="+Inf"}} {self._totals[key]}')
                lbl = "{" + base + "}" if base else ""
                out.append(f"{self.name}_sum{lbl} {self._sums[key]}")
                out.append(f"{self.name}_count{lbl} {self._totals[key]}")
        return out


# ---------------------------------------------------------------------------
# Mergeable percentile sketch (DESIGN.md §23)
# ---------------------------------------------------------------------------

# Process-wide sketch-recording toggle: the telemetry-overhead bench arm
# (tools/bench_sched.py) and operators who want fixed-bucket histograms
# only.  Mirrors tracing.set_enabled — disabled, observe() returns
# before touching the lock.
_SKETCHES_ENABLED = True


def set_sketches_enabled(on: bool) -> None:
    global _SKETCHES_ENABLED
    _SKETCHES_ENABLED = bool(on)


def sketches_enabled() -> bool:
    return _SKETCHES_ENABLED


# Values at or below this land in the zero bucket: latencies and sizes
# are non-negative, and log() needs a floor.
MIN_TRACKABLE = 1e-12


def sketch_state_quantile(st: Dict[str, Any], q: float) -> Optional[float]:
    """q-quantile estimate from a serialized sketch state, relative
    error ≤ alpha for positive values (the DDSketch midpoint bound:
    bucket i covers (γ^(i-1), γ^i]; 2γ^i/(γ+1) is within α of every
    value in it).  None on an empty sketch."""
    total = st["total"]
    if total <= 0:
        return None
    q = min(max(q, 0.0), 1.0)
    target = max(int(math.ceil(q * total)), 1)
    cum = st["zero"]
    if cum >= target:
        return 0.0
    gamma = (1.0 + st["alpha"]) / (1.0 - st["alpha"])
    value = 0.0
    for idx, c in sorted(st["counts"]):
        cum += c
        if cum >= target:
            value = 2.0 * gamma ** idx / (gamma + 1.0)
            break
    # The recorded extremes are exact; clamping costs nothing and keeps
    # p0/p100 honest.
    return min(max(value, st["min"]), st["max"])


def sketch_state_count_below(st: Dict[str, Any], threshold: float) -> float:
    """Samples ≤ threshold (resolved at sketch resolution: whole buckets
    whose upper bound γ^i does not exceed threshold·(1+α) count, so the
    answer is exact to within the declared relative error — the SLO
    engine's good-event source)."""
    if threshold <= MIN_TRACKABLE:
        return float(st["zero"])
    gamma = (1.0 + st["alpha"]) / (1.0 - st["alpha"])
    # Bucket of `threshold` itself: every bucket up to and including it
    # holds values ≤ threshold·(1+α).
    i_max = int(math.ceil(math.log(threshold) / math.log(gamma) - 1e-9))
    return float(st["zero"] + sum(c for idx, c in st["counts"] if idx <= i_max))


def merge_sketch_states(states: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Lossless merge of serialized sketch states (same alpha): bucket
    counts add exactly, so merging per-process sketches equals having
    observed every sample in one sketch — the fleet-assembly primitive."""
    if not states:
        return {"alpha": 0.01, "zero": 0, "counts": [], "total": 0,
                "sum": 0.0, "min": 0.0, "max": 0.0}
    alpha = states[0]["alpha"]
    counts: Dict[int, int] = {}
    zero = total = 0
    total_sum = 0.0
    mn, mx = math.inf, -math.inf
    for st in states:
        if abs(st["alpha"] - alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different alpha "
                f"({st['alpha']} != {alpha})"
            )
        zero += st["zero"]
        total += st["total"]
        total_sum += st["sum"]
        if st["total"] > 0:
            mn = min(mn, st["min"])
            mx = max(mx, st["max"])
        for idx, c in st["counts"]:
            counts[idx] = counts.get(idx, 0) + c
    return {
        "alpha": alpha,
        "zero": zero,
        "counts": sorted(counts.items()),
        "total": total,
        "sum": total_sum,
        "min": mn if total > 0 else 0.0,
        "max": mx if total > 0 else 0.0,
    }


class _SketchSeries:
    """One label-set's bucket state (int bucket index → count)."""

    __slots__ = ("zero", "counts", "total", "sum", "mn", "mx")

    def __init__(self) -> None:
        self.zero = 0
        self.counts: Dict[int, int] = {}
        self.total = 0
        self.sum = 0.0
        self.mn = math.inf
        self.mx = -math.inf


class _SketchChild:
    """Label-bound sketch handle (see _CounterChild): label validation
    paid once at bind time — hot paths observe through these."""

    __slots__ = ("_metric", "_key_t")

    def __init__(self, metric: "Sketch", key: Tuple[str, ...]) -> None:
        self._metric = metric
        self._key_t = key

    def observe(self, value: float) -> None:
        if not _SKETCHES_ENABLED:
            return
        self._metric._observe_key(self._key_t, value)


class Sketch(_Metric):
    """DDSketch-style mergeable quantile sketch (relative-error bound).

    Buckets are logarithmic with ratio γ=(1+α)/(1−α): bucket i covers
    (γ^(i-1), γ^i], so any value's bucket-midpoint estimate is within α
    relative error.  The bucket index of a sample is a deterministic
    function of the value alone — two processes observing the same
    stream build byte-identical states, and ``merge_sketch_states`` adds
    counts exactly (lossless merge).  State is bounded: past ``max_bins``
    distinct buckets the lowest indices collapse into one (tail accuracy
    — the p99 the fleet cares about — is never what collapses).

    Exposed in the Prometheus text format as a ``summary`` (quantile
    label per series + _sum/_count), journaled exactly via ``state()``.
    """

    QUANTILES = (0.5, 0.9, 0.99)

    def __init__(
        self,
        name: str,
        help: str,
        label_names: Sequence[str] = (),
        alpha: float = 0.01,
        max_bins: int = 2048,
    ) -> None:
        super().__init__(name, help, label_names)
        if not (0.0 < alpha < 1.0):
            raise ValueError(f"sketch alpha {alpha} out of (0, 1)")
        self.alpha = alpha
        self.max_bins = max(16, max_bins)
        self._gamma = (1.0 + alpha) / (1.0 - alpha)
        self._lg = math.log(self._gamma)
        self._series: Dict[Tuple[str, ...], _SketchSeries] = {}

    # -- recording -----------------------------------------------------------

    def observe(self, value: float, **labels: str) -> None:
        if not _SKETCHES_ENABLED:
            return
        self._observe_key(self._key(labels), value)

    def _observe_key(self, key: Tuple[str, ...], value: float) -> None:
        v = float(value)
        idx = (
            None if v <= MIN_TRACKABLE
            else int(math.ceil(math.log(v) / self._lg))
        )
        with self._mu:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _SketchSeries()
            if idx is None:
                s.zero += 1
            else:
                s.counts[idx] = s.counts.get(idx, 0) + 1
                if len(s.counts) > self.max_bins:
                    self._collapse_locked(s)
            s.total += 1
            s.sum += v
            if v < s.mn:
                s.mn = v
            if v > s.mx:
                s.mx = v

    def _collapse_locked(self, s: _SketchSeries) -> None:
        """Fold the lowest bucket indices together until the bin bound
        holds (DDSketch collapsing): the fine-grained tail — the high
        quantiles — keeps full resolution; only the smallest values get
        coarser."""
        keys = sorted(s.counts)
        floor_idx = keys[len(keys) - self.max_bins]
        folded = 0
        for k in keys:
            if k >= floor_idx:
                break
            folded += s.counts.pop(k)
        s.counts[floor_idx] = s.counts.get(floor_idx, 0) + folded

    def labels(self, **labels: str) -> _SketchChild:
        return _SketchChild(self, self._key(labels))

    # -- reading -------------------------------------------------------------

    def _state_of_locked(self, s: _SketchSeries) -> Dict[str, Any]:
        return {
            "alpha": self.alpha,
            "zero": s.zero,
            "counts": sorted(s.counts.items()),
            "total": s.total,
            "sum": s.sum,
            "min": s.mn if s.total else 0.0,
            "max": s.mx if s.total else 0.0,
        }

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        key = self._key(labels)
        with self._mu:
            s = self._series.get(key)
            if s is None:
                return None
            st = self._state_of_locked(s)
        return sketch_state_quantile(st, q)

    def count_below(self, threshold: float, **labels: str) -> float:
        key = self._key(labels)
        with self._mu:
            s = self._series.get(key)
            if s is None:
                return 0.0
            st = self._state_of_locked(s)
        return sketch_state_count_below(st, threshold)

    def total_count(self, **labels: str) -> int:
        key = self._key(labels)
        with self._mu:
            s = self._series.get(key)
            return s.total if s is not None else 0

    def aggregate_state(self) -> Dict[str, Any]:
        """All label series merged into one state — what an SLO over the
        whole metric (every parent, every task) evaluates against."""
        with self._mu:
            states = [self._state_of_locked(s) for s in self._series.values()]
        return merge_sketch_states(states)

    def state(self) -> Dict[str, Any]:
        with self._mu:
            series = [
                [list(k), self._state_of_locked(s)]
                for k, s in sorted(self._series.items())
            ]
        return {
            "type": "sketch",
            "labels": list(self.label_names),
            "alpha": self.alpha,
            "series": series,
        }

    def merge_state(self, st: Dict[str, Any], **labels: str) -> None:
        """Fold a serialized state into this sketch (tests / fleet
        tooling; not a hot path)."""
        key = self._key(labels)
        with self._mu:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _SketchSeries()
            own = self._state_of_locked(s)
        merged = merge_sketch_states([own, st])
        with self._mu:
            s.zero = merged["zero"]
            s.counts = dict(merged["counts"])
            s.total = merged["total"]
            s.sum = merged["sum"]
            s.mn = merged["min"] if merged["total"] else math.inf
            s.mx = merged["max"] if merged["total"] else -math.inf

    def expose(self) -> List[str]:
        out = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} summary",
        ]
        with self._mu:
            snap = [
                (k, self._state_of_locked(s))
                for k, s in sorted(self._series.items())
            ]
        for key, st in snap:
            base = self._fmt_labels(key)[1:-1] if key else ""
            sep = "," if base else ""
            for q in self.QUANTILES:
                v = sketch_state_quantile(st, q)
                if v is None:
                    continue
                out.append(
                    f'{self.name}{{{base}{sep}quantile="{q}"}} {v:.9g}'
                )
            lbl = "{" + base + "}" if base else ""
            out.append(f"{self.name}_sum{lbl} {st['sum']}")
            out.append(f"{self.name}_count{lbl} {st['total']}")
        return out


class Registry:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def counter(self, name: str, help: str = "", label_names: Sequence[str] = ()) -> Counter:
        return self._register(Counter(name, help, label_names))

    def gauge(self, name: str, help: str = "", label_names: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(name, help, label_names))

    def histogram(
        self,
        name: str,
        help: str = "",
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(Histogram(name, help, label_names, buckets))

    def sketch(
        self,
        name: str,
        help: str = "",
        label_names: Sequence[str] = (),
        alpha: float = 0.01,
        max_bins: int = 2048,
    ) -> Sketch:
        return self._register(Sketch(name, help, label_names, alpha, max_bins))

    def get(self, name: str) -> Optional[_Metric]:
        with self._mu:
            return self._metrics.get(name)

    def _register(self, metric):
        with self._mu:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric):
                    raise ValueError(f"metric {metric.name} re-registered as different type")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def expose_text(self) -> str:
        with self._mu:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """Serializable snapshot of every journaled metric — the metric
        journal's frame payload (utils/metric_journal.py, DESIGN.md §23):
        counters and gauges as (labels, value) series, sketches as exact
        bucket states.  Histograms are served by /metrics but not
        journaled (the sketch is the durable latency carrier).  Metric
        locks are taken one at a time, never nested under the registry
        lock (the expose_text discipline)."""
        with self._mu:
            metrics = list(self._metrics.values())
        out: Dict[str, Any] = {}
        for m in metrics:
            state = m.state()
            if state is not None:
                out[m.name] = state
        return out

    def exemplars(self) -> Dict[str, Dict[str, Dict[str, str]]]:
        """Every histogram's per-bucket exemplars (``/debug/exemplars``):
        {metric: {label-set: {le: trace_id}}}, empty sets omitted."""
        with self._mu:
            metrics = list(self._metrics.values())
        out: Dict[str, Dict[str, Dict[str, str]]] = {}
        for m in metrics:
            if isinstance(m, Histogram):
                ex = m.exemplars()
                if ex:
                    out[m.name] = ex
        return out


# Process-default registry (services may create their own for isolation).
default_registry = Registry()
