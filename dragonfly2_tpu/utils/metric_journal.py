"""Crash-safe metric journal: the flight recorder's discipline (§21)
applied to metrics (DESIGN.md §23).

Every process appends periodic snapshots of its counter/gauge/sketch
state — plus its process/run identity — to an append-only journal of
length-prefixed, crc32-digest-checked frames:

    b"DFMJ1 <payload_len> <crc32 payload, 8 hex>\n" + payload + b"\n"

Each frame is ONE ``os.write`` on an O_APPEND fd (the kernel serializes
appends), so a SIGKILL costs at most the in-flight frame at the tail.
The replayer follows the DFTL1 rules (utils/tracing.replay_trace_log):
tolerate the torn tail, resync past mid-file truncation, and NEVER
admit a digest-bad frame.

Snapshots are CUMULATIVE (the full registry state, not deltas): the
last admitted frame of a run is that run's final word, so a dead
process's journal is exactly as useful as a live one's ``/metrics``
scrape was.  ``run_id`` gives restart/reset detection its identity —
``tools/fleet_assemble.py`` sums counters per run and merges sketches
losslessly across every run of every process.

Wired into all four binaries next to ``--trace-log``
(``--metric-journal`` / config ``telemetry.journal_path``).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
import zlib
from typing import Any, Dict, List, Optional, Tuple

from .metrics import Registry, default_registry
from .tracing import _raw_lock

FRAME_MAGIC = b"DFMJ1 "

SNAPSHOT_VERSION = 1


def encode_frame(snapshot: Dict[str, Any]) -> bytes:
    """One canonical DFMJ1 frame for ``snapshot`` — the declared DFMJ1
    artifact writer of DESIGN.md §27.  ``sort_keys=True`` is
    load-bearing: replay byte-identity (and the different-PYTHONHASHSEED
    dual-run drill) holds only while equal snapshots serialize to equal
    bytes regardless of dict insertion/hash order."""
    payload = json.dumps(snapshot, sort_keys=True).encode()
    return (
        FRAME_MAGIC
        + f"{len(payload)} {zlib.crc32(payload) & 0xFFFFFFFF:08x}\n".encode()
        + payload
        + b"\n"
    )


class MetricJournal:
    """Per-process append-only metric journal.

    ``start()`` runs a background snapshot thread every ``interval_s``;
    ``write_snapshot()`` appends one immediately (shutdown hooks, tests,
    drills).  Write failures are counted in ``dropped``, never raised —
    observability must not crash the plane.  The bookkeeping lock comes
    from dflock's REAL factory (the exporter precedent): diagnostics
    must not instrument diagnostics.
    """

    def __init__(
        self,
        path: str,
        *,
        registry: Optional[Registry] = None,
        service: str = "dragonfly",
        interval_s: float = 10.0,
        run_id: Optional[str] = None,
        fsync: bool = False,
    ) -> None:
        import atexit

        self.path = path
        self.registry = registry if registry is not None else default_registry
        self.service = service
        self.interval_s = max(0.05, float(interval_s))
        self.run_id = run_id or uuid.uuid4().hex
        self.fsync = fsync
        self.written = 0
        self.dropped = 0
        # Payload of the most recent write_snapshot (None before the
        # first) — the autopilot's live ingest source.
        self.last_snapshot = None
        # Optional callable(snapshot_dict) invoked after each cadence
        # write (qos/autopilot.py rides the journal's clock: its live
        # input IS the frame replay will read back).  Exceptions are
        # swallowed with a log — a consumer bug must not stop journaling.
        self.on_snapshot = None
        self._seq = 0
        self._closed = False
        self._mu = _raw_lock()
        self._fd: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        atexit.register(self.close)

    # -- writing -------------------------------------------------------------

    def _payload(self) -> Dict[str, Any]:
        with self._mu:
            self._seq += 1
            seq = self._seq
        return {
            "v": SNAPSHOT_VERSION,
            "service": self.service,
            "run_id": self.run_id,
            "pid": os.getpid(),
            "seq": seq,
            "ts": time.time(),
            "metrics": self.registry.snapshot(),
        }

    def write_snapshot(self) -> bool:
        """Append one cumulative snapshot frame; False = write failed
        (counted in ``dropped``).  The frame's payload stays readable on
        ``last_snapshot`` — the SLO autopilot's live loop ingests the
        SAME dict replay will read back off disk (qos/autopilot.py), so
        live decisions and journal replay are identical by construction.
        """
        from . import faultinject

        snapshot = self._payload()
        self.last_snapshot = snapshot
        frame = encode_frame(snapshot)
        # Chaos seam: a ``crash`` fault here SIGKILLs the process at a
        # deterministic journal write — the telemetry kill drill's
        # "mid-storm, mid-journal" point (sim/telemetry.py).
        faultinject.fire("metrics.journal.write")
        with self._mu:
            try:
                if self._fd is None:
                    self._fd = os.open(
                        self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
                    )
                os.write(self._fd, frame)
                if self.fsync:
                    os.fsync(self._fd)
                self.written += 1
                return True
            except OSError:
                self.dropped += 1
                return False

    # -- background cadence --------------------------------------------------

    def start(self) -> "MetricJournal":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="metric-journal", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        # Bounded waits (DF008 timeout sweep): the stop event doubles as
        # the cadence clock, so close() never waits out a full interval.
        while not self._stop.wait(self.interval_s):
            if self.write_snapshot():
                sink = self.on_snapshot
                if sink is not None:
                    try:
                        sink(self.last_snapshot)
                    except Exception:  # noqa: BLE001 — consumer bug ≠ journal outage
                        import logging

                        logging.getLogger(__name__).exception(
                            "metric-journal snapshot consumer failed"
                        )

    def close(self) -> None:
        """Stop the cadence thread, write the final snapshot, close the
        fd.  Idempotent (atexit + explicit shutdown both call it)."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            while t.is_alive():
                t.join(5.0)
                break
        with self._mu:
            if self._closed:
                return
            self._closed = True
        self.write_snapshot()
        with self._mu:
            fd, self._fd = self._fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Replay (DFTL1 rules: torn tail tolerated, digest-bad never admitted)
# ---------------------------------------------------------------------------


def replay_metric_journal(
    path: str,
) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Replay a metric journal → (snapshots, stats).

    Stats: ``frames`` admitted, ``corrupt`` frames rejected by digest or
    JSON decode (NEVER admitted), ``torn_tail`` True when the file ends
    inside a frame — the expected SIGKILL signature, tolerated."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return [], {"frames": 0, "corrupt": 0, "torn_tail": False}
    snapshots: List[Dict[str, Any]] = []
    corrupt = 0
    torn = False
    pos = 0
    while True:
        idx = data.find(FRAME_MAGIC, pos)
        if idx < 0:
            break
        nl = data.find(b"\n", idx)
        if nl < 0:
            torn = True  # header itself torn at the tail
            break
        header = data[idx + len(FRAME_MAGIC) : nl]
        try:
            len_s, crc_s = header.split()
            length, crc = int(len_s), int(crc_s, 16)
        except ValueError:
            corrupt += 1
            pos = idx + 1  # garbage where a header should be: resync
            continue
        payload = data[nl + 1 : nl + 1 + length]
        if len(payload) < length:
            # Frame cut mid-payload.  At EOF that's the torn tail a
            # SIGKILL leaves (tolerated); mid-file it's a corrupt frame
            # — reject and resync at the next magic.
            nxt = data.find(FRAME_MAGIC, idx + 1)
            if nxt < 0:
                torn = True
                break
            corrupt += 1
            pos = nxt
            continue
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            corrupt += 1
            pos = idx + 1  # digest mismatch: frame not admitted; resync
            continue
        try:
            snapshots.append(json.loads(payload))
        except ValueError:
            corrupt += 1
            pos = idx + 1
            continue
        pos = nl + 1 + length
    return snapshots, {
        "frames": len(snapshots),
        "corrupt": corrupt,
        "torn_tail": torn,
    }


def final_snapshots_by_run(
    snapshots: List[Dict[str, Any]],
) -> Dict[Tuple[str, str], Dict[str, Any]]:
    """``{(service, run_id): last snapshot}`` — snapshots are cumulative,
    so the highest-seq admitted frame is a run's final state.  Run
    identity IS the restart/reset detector: a restarted process draws a
    fresh run_id, so its counters start a new summand instead of being
    mistaken for a reset of the old series."""
    out: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for snap in snapshots:
        key = (str(snap.get("service", "")), str(snap.get("run_id", "")))
        prev = out.get(key)
        if prev is None or snap.get("seq", 0) >= prev.get("seq", 0):
            out[key] = snap
    return out
