"""Live host statistics (reference: gopsutil usage in client/daemon/announcer
announcer.go:158-303 and scheduler/resource/host.go:133-347).

These stats ride every peer announce, land in Download training records
(scheduler/storage/types.go Host :59-126) and become the node features of
the trainer's peer graph — so the field set here defines the model's host
feature vector.
"""

from __future__ import annotations

import os
import platform
import socket
from dataclasses import dataclass, field, asdict
from typing import Optional


@dataclass
class CPUTimes:
    user: float = 0.0
    system: float = 0.0
    idle: float = 0.0
    nice: float = 0.0
    iowait: float = 0.0
    irq: float = 0.0
    softirq: float = 0.0
    steal: float = 0.0
    guest: float = 0.0


@dataclass
class CPUStat:
    logical_count: int = 0
    physical_count: int = 0
    percent: float = 0.0
    process_percent: float = 0.0
    times: CPUTimes = field(default_factory=CPUTimes)


@dataclass
class MemoryStat:
    total: int = 0
    available: int = 0
    used: int = 0
    used_percent: float = 0.0
    process_used_percent: float = 0.0
    free: int = 0


@dataclass
class NetworkStat:
    tcp_connection_count: int = 0
    upload_tcp_connection_count: int = 0
    location: str = ""
    idc: str = ""
    download_rate: float = 0.0
    download_rate_limit: float = 0.0
    upload_rate: float = 0.0
    upload_rate_limit: float = 0.0


@dataclass
class DiskStat:
    total: int = 0
    free: int = 0
    used: int = 0
    used_percent: float = 0.0
    inodes_total: int = 0
    inodes_used: int = 0
    inodes_free: int = 0
    inodes_used_percent: float = 0.0


@dataclass
class BuildInfo:
    git_version: str = ""
    git_commit: str = ""
    go_version: str = ""  # kept for record-schema parity; carries runtime version
    platform: str = ""


@dataclass
class HostInfo:
    ip: str = ""
    hostname: str = ""
    os: str = ""
    platform: str = ""
    platform_family: str = ""
    platform_version: str = ""
    kernel_version: str = ""
    cpu: CPUStat = field(default_factory=CPUStat)
    memory: MemoryStat = field(default_factory=MemoryStat)
    network: NetworkStat = field(default_factory=NetworkStat)
    disk: DiskStat = field(default_factory=DiskStat)
    build: BuildInfo = field(default_factory=BuildInfo)
    scheduler_cluster_id: int = 0
    announce_interval: float = 0.0

    def to_dict(self) -> dict:
        return asdict(self)


def _read_meminfo() -> MemoryStat:
    stat = MemoryStat()
    try:
        with open("/proc/meminfo") as f:
            info = {}
            for line in f:
                key, _, rest = line.partition(":")
                info[key.strip()] = int(rest.strip().split()[0]) * 1024
        stat.total = info.get("MemTotal", 0)
        stat.free = info.get("MemFree", 0)
        stat.available = info.get("MemAvailable", stat.free)
        stat.used = max(stat.total - stat.available, 0)
        if stat.total:
            stat.used_percent = 100.0 * stat.used / stat.total
    except OSError:
        pass
    return stat


def _read_disk(path: str = "/") -> DiskStat:
    stat = DiskStat()
    try:
        st = os.statvfs(path)
        stat.total = st.f_blocks * st.f_frsize
        stat.free = st.f_bavail * st.f_frsize
        stat.used = stat.total - st.f_bfree * st.f_frsize
        if stat.total:
            stat.used_percent = 100.0 * stat.used / stat.total
        stat.inodes_total = st.f_files
        stat.inodes_free = st.f_favail
        stat.inodes_used = st.f_files - st.f_ffree
        if st.f_files:
            stat.inodes_used_percent = 100.0 * stat.inodes_used / st.f_files
    except OSError:
        pass
    return stat


class CPUSampler:
    """Delta-window CPU utilization (gopsutil-style): percent over the
    interval since THIS sampler's previous read, not the since-boot average.

    Each periodic caller owns a sampler so concurrent loops don't steal each
    other's windows; reads under a lock; a re-read before the jiffy counter
    advances returns the last computed percent instead of degrading to the
    since-boot average.
    """

    def __init__(self) -> None:
        import threading

        self._mu = threading.Lock()
        self._prev: Optional[tuple] = None
        self._last_percent: Optional[float] = None

    def read(self) -> CPUStat:
        stat = CPUStat(
            logical_count=os.cpu_count() or 0, physical_count=os.cpu_count() or 0
        )
        try:
            with open("/proc/stat") as f:
                first = f.readline().split()
        except OSError:
            return stat
        if not first or first[0] != "cpu":
            return stat
        vals = [float(v) for v in first[1:]]
        names = ["user", "nice", "system", "idle", "iowait", "irq", "softirq", "steal", "guest"]
        for name, v in zip(names, vals):
            setattr(stat.times, name, v)
        busy = sum(vals) - stat.times.idle - stat.times.iowait
        total = sum(vals)
        with self._mu:
            prev = self._prev
            if prev is not None and total > prev[1]:
                self._prev = (busy, total)
                self._last_percent = 100.0 * (busy - prev[0]) / (total - prev[1])
                stat.percent = self._last_percent
            elif prev is not None:
                # Counter hasn't advanced — keep the last window's value.
                stat.percent = self._last_percent or 0.0
            else:
                self._prev = (busy, total)
                # First sample ever: since-boot average is all we have.
                stat.percent = 100.0 * busy / total if total else 0.0
                self._last_percent = stat.percent
        return stat


_default_cpu_sampler = CPUSampler()


def _read_cpu() -> CPUStat:
    return _default_cpu_sampler.read()


def local_ip() -> str:
    """Best-effort routable local IP (the address peers should dial)."""
    return _local_ip()


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))  # no packets sent; picks the default route
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def collect(location: str = "", idc: str = "") -> HostInfo:
    """Snapshot this machine's stats the way the daemon announcer does."""
    uname = platform.uname()
    return HostInfo(
        ip=_local_ip(),
        hostname=socket.gethostname(),
        os=uname.system.lower(),
        platform=uname.system.lower(),
        platform_family=uname.system.lower(),
        platform_version=uname.release,
        kernel_version=uname.release,
        cpu=_read_cpu(),
        memory=_read_meminfo(),
        network=NetworkStat(location=location, idc=idc),
        disk=_read_disk(),
        build=BuildInfo(platform=uname.machine, go_version=platform.python_version()),
    )
