"""Runtime span witness: dynamic validation of DF016's span inventory.

``tools/dflint/checkers/df016_spans.py`` pins each instrumented module
to the span names it must open (static AST extraction).  Static checks
can rot without failing anything: a span site the extractor cannot see
(opened through an alias it doesn't recognize) silently leaves the
inventory unenforced, and an inventoried span whose call path the suite
no longer reaches may be "present" in the AST while never actually
recording.  This module closes the loop in the lock/compile/crash
witness mould (utils/dflock.py, utils/dftrace.py, utils/dfcrash.py):

- installed by ``tests/conftest.py`` before any test runs, it wraps
  ``Tracer.span`` / ``Tracer.remote_span`` so every span OPENED from
  project code during the tier-1 run records
  ``(caller relpath, span name, kind)``;
- ``tests/test_zz_spanwitness.py`` then cross-validates: every
  inventoried site of every module the suite imported must have been
  observed at runtime (deleting a ``remote_span`` fails HERE as well as
  in the static rule), and every observed span must match a site the
  static extractor found in its module (an unmatched observation means
  the extractor has a blind spot — test failure, not silent rot).

Design constraints (mirroring dflock/dftrace/dfcrash):

- **foreign spans untouched** — only call sites whose frame lives under
  the package root record; tests and tools construct spans freely;
- **tracing.py's own frames are skipped** — ``remote_span`` delegates to
  ``span`` internally; recording that inner call would attribute every
  remote span to utils/tracing.py instead of its real opener;
- **recording failure never breaks tracing** — bookkeeping is wrapped
  defensively and the real contextmanager is always returned;
- the bookkeeping lock comes from dflock's REAL factory: diagnostics
  must not instrument diagnostics.

Set ``DF_SPAN_WITNESS=0`` to disable.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, Optional, Tuple

Site = Tuple[str, str, str]   # (caller relpath, span name, kind)


def _raw_lock():
    try:
        from .dflock import _REAL_LOCK

        return _REAL_LOCK()
    except ImportError:  # pragma: no cover — dflock always ships
        return threading.Lock()


class SpanWitness:
    """Global recorder shared by the patched tracer methods."""

    def __init__(self, package_dir: str) -> None:
        self.package_dir = os.path.abspath(package_dir)
        self.repo_root = os.path.dirname(self.package_dir)
        self._mu = _raw_lock()
        self.observed: Dict[Site, int] = {}

    def note(self, frame, name: str, kind: str) -> None:
        filename = os.path.abspath(frame.f_code.co_filename)
        if not filename.startswith(self.package_dir + os.sep):
            return
        rel = os.path.relpath(filename, self.repo_root).replace(os.sep, "/")
        if rel == "dragonfly2_tpu/utils/tracing.py":
            # remote_span's internal self.span() call — the outer
            # wrapper already recorded the real opener.
            return
        key = (rel, name, kind)
        with self._mu:
            self.observed[key] = self.observed.get(key, 0) + 1

    def snapshot(self) -> Dict[Site, int]:
        with self._mu:
            return dict(self.observed)

    def names_by_module(self) -> Dict[str, set]:
        out: Dict[str, set] = {}
        with self._mu:
            for (rel, name, _kind) in self.observed:
                out.setdefault(rel, set()).add(name)
        return out

    def reset(self) -> None:
        with self._mu:
            self.observed.clear()


_installed: Optional[SpanWitness] = None


def witness() -> Optional[SpanWitness]:
    return _installed


def _default_package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def install(package_dir: Optional[str] = None) -> SpanWitness:
    """Wrap ``Tracer.span``/``Tracer.remote_span`` with recording
    shims.  Idempotent; returns the active witness."""
    global _installed
    if _installed is not None:
        return _installed
    from .tracing import Tracer

    w = SpanWitness(package_dir or _default_package_dir())
    real_span = Tracer.span
    real_remote = Tracer.remote_span

    def span(self, name, **kwargs):
        try:
            w.note(sys._getframe(1), name, "span")
        except Exception:  # dflint: disable=DF001 — diagnostics-only bookkeeping; tracing itself must proceed
            pass
        return real_span(self, name, **kwargs)

    def remote_span(self, name, traceparent, **kwargs):
        try:
            w.note(sys._getframe(1), name, "remote_span")
        except Exception:  # dflint: disable=DF001 — diagnostics-only bookkeeping; tracing itself must proceed
            pass
        return real_remote(self, name, traceparent, **kwargs)

    Tracer.span = span
    Tracer.remote_span = remote_span
    _installed = w
    return w
