"""TTL in-memory cache with janitor and regex scan (reference: pkg/cache/cache.go).

Backs the read-through layer in front of the network-topology store (the
reference fronts Redis with this; we front the embedded KV store) and the
certificate cache.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

NO_EXPIRATION = -1.0


class TTLCache:
    def __init__(
        self,
        default_ttl: float = NO_EXPIRATION,
        janitor_interval: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._mu = threading.RLock()
        self._items: Dict[str, Tuple[Any, float]] = {}  # key -> (value, deadline)
        self._default_ttl = default_ttl
        self._clock = clock
        self._janitor: Optional[threading.Timer] = None
        self._janitor_interval = janitor_interval
        if janitor_interval > 0:
            self._schedule_janitor()

    def _deadline(self, ttl: Optional[float]) -> float:
        if ttl is None:
            ttl = self._default_ttl
        if ttl == NO_EXPIRATION or ttl < 0:
            return float("inf")
        return self._clock() + ttl

    def set(self, key: str, value: Any, ttl: Optional[float] = None) -> None:
        with self._mu:
            self._items[key] = (value, self._deadline(ttl))

    def add(self, key: str, value: Any, ttl: Optional[float] = None) -> bool:
        """Set only if absent (and not expired). Returns True if stored."""
        with self._mu:
            if self._get_locked(key) is not None:
                return False
            self._items[key] = (value, self._deadline(ttl))
            return True

    def _get_locked(self, key: str) -> Optional[Tuple[Any, float]]:
        item = self._items.get(key)
        if item is None:
            return None
        value, deadline = item
        if deadline < self._clock():
            del self._items[key]
            return None
        return item

    def get(self, key: str, default: Any = None) -> Any:
        with self._mu:
            item = self._get_locked(key)
            return default if item is None else item[0]

    def contains(self, key: str) -> bool:
        with self._mu:
            return self._get_locked(key) is not None

    def delete(self, key: str) -> None:
        with self._mu:
            self._items.pop(key, None)

    def keys(self) -> list[str]:
        with self._mu:
            now = self._clock()
            return [k for k, (_, d) in self._items.items() if d >= now]

    def scan(self, pattern: str) -> Iterator[Tuple[str, Any]]:
        """Yield (key, value) for keys matching the regex (reference: cache.Scan)."""
        rx = re.compile(pattern)
        with self._mu:
            now = self._clock()
            snapshot = [
                (k, v) for k, (v, d) in self._items.items() if d >= now and rx.search(k)
            ]
        yield from snapshot

    def purge_expired(self) -> int:
        with self._mu:
            now = self._clock()
            dead = [k for k, (_, d) in self._items.items() if d < now]
            for k in dead:
                del self._items[k]
            return len(dead)

    def clear(self) -> None:
        with self._mu:
            self._items.clear()

    def __len__(self) -> int:
        with self._mu:
            now = self._clock()
            return sum(1 for _, d in self._items.values() if d >= now)

    def _schedule_janitor(self) -> None:
        def run() -> None:
            self.purge_expired()
            with self._mu:
                if self._janitor_interval > 0:
                    self._schedule_janitor()

        self._janitor = threading.Timer(self._janitor_interval, run)
        self._janitor.daemon = True
        self._janitor.start()

    def close(self) -> None:
        with self._mu:
            self._janitor_interval = 0.0
            if self._janitor is not None:
                self._janitor.cancel()
                self._janitor = None
