"""Debug endpoint (reference: cmd/dependency --pprof-port starting
net/http/pprof on every binary).

Python analog over loopback HTTP:

  GET /debug/stacks   — current stack of every thread (goroutine dump)
  GET /debug/stats    — gc counters, thread/fd counts, rss
  GET /debug/profile?seconds=N — cProfile the process for N seconds,
                                 returns pstats text sorted by cumtime
"""

from __future__ import annotations

import gc
import io
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler
from typing import Tuple
from urllib.parse import parse_qsl, urlsplit

from ..rpc._server import ThreadedHTTPService


def thread_stacks() -> str:
    out = io.StringIO()
    frames = sys._current_frames()
    for thread in threading.enumerate():
        frame = frames.get(thread.ident)
        out.write(
            f"\n--- {thread.name} (daemon={thread.daemon}, "
            f"ident={thread.ident}) ---\n"
        )
        if frame is not None:
            traceback.print_stack(frame, file=out)
    return out.getvalue()


def process_stats() -> dict:
    stats = {
        "threads": threading.active_count(),
        "gc_counts": gc.get_count(),
        "gc_collections": [g["collections"] for g in gc.get_stats()],
    }
    try:
        import resource

        stats["max_rss_kb"] = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except ImportError:
        pass
    try:
        import os

        stats["open_fds"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    return stats


def profile_seconds(seconds: float, hz: float = 100.0) -> str:
    """Sampling profiler across ALL threads (pprof's CPU profile shape):
    sample sys._current_frames() at ``hz`` for ``seconds``, aggregate
    leaf frames and full stacks by count.  cProfile would only see the
    calling thread (i.e. this handler's own sleep) — useless for the
    worker threads an operator actually wants to see."""
    import time
    from collections import Counter

    seconds = min(max(seconds, 0.1), 60.0)
    interval = 1.0 / max(hz, 1.0)
    own = threading.get_ident()
    leaves: Counter = Counter()
    stacks: Counter = Counter()
    samples = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == own:
                continue
            samples += 1
            leaf = f"{frame.f_code.co_filename.rsplit('/', 1)[-1]}:" \
                   f"{frame.f_lineno} {frame.f_code.co_name}"
            leaves[leaf] += 1
            stack = []
            f = frame
            while f is not None and len(stack) < 30:
                stack.append(f.f_code.co_name)
                f = f.f_back
            stacks[" <- ".join(stack)] += 1
        time.sleep(interval)
    out = io.StringIO()
    out.write(f"sampled {samples} frames over {seconds:.1f}s at {hz:.0f} Hz\n")
    out.write("\n== hottest leaf frames (cumulative samples) ==\n")
    for leaf, n in leaves.most_common(25):
        out.write(f"{n:8d}  {leaf}\n")
    out.write("\n== hottest stacks ==\n")
    for stack, n in stacks.most_common(10):
        out.write(f"{n:8d}  {stack}\n")
    return out.getvalue()


class DebugServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _text(self, code: int, body: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                parsed = urlsplit(self.path)
                if parsed.path == "/debug/stacks":
                    self._text(200, thread_stacks())
                elif parsed.path == "/debug/stats":
                    import json

                    self._text(200, json.dumps(process_stats(), indent=2))
                elif parsed.path == "/debug/profile":
                    q = dict(parse_qsl(parsed.query))
                    self._text(200, profile_seconds(float(q.get("seconds", 2))))
                else:
                    self._text(404, "not found\n")

        self._svc = ThreadedHTTPService(Handler, host, port, "debug")
        self.address: Tuple[int, int] = self._svc.address

    @property
    def url(self) -> str:
        return self._svc.url

    def serve(self) -> None:
        self._svc.serve()

    def stop(self) -> None:
        self._svc.stop()
