"""Shared RFC-7233 byte-range parsing (DESIGN.md §25).

Three HTTP surfaces serve byte ranges — the upload piece server's
``/tasks/<id>`` endpoint, the dfdaemon forward proxy, and the object
gateway — and before this module each parsed ``Range:`` headers with its
own inline arithmetic, which is exactly how the three drift apart one
edge case at a time.  ``parse_range`` is the single owner of the RFC's
shapes, and the conformance sweep (tests/test_stream_tee.py) proves the
three surfaces byte-identical through it.

Contract (single-range ``bytes=`` specs, the shapes real clients send):

- ``bytes=S-E``  → ``(S, min(E, total-1))``; ``S > E`` is syntactically
  invalid → ``None`` (RFC 7233 §3.1: ignore the header, serve 200);
- ``bytes=S-``   → ``(S, total-1)`` (open-ended);
- ``bytes=-N``   → the final N bytes; ``N >= total`` clamps to the whole
  representation; ``N == 0`` is unsatisfiable → 416;
- ``S >= total`` → :class:`RangeNotSatisfiable` (416 with
  ``Content-Range: bytes */total``);
- a missing/foreign-unit/multi-range header → ``None`` (callers serve
  the full 200 body; multi-range responses are out of scope here, and
  ignoring is RFC-legal).

Callers that REQUIRE a range (the piece server's task endpoint has no
un-ranged read) map ``None`` to 416 themselves — that strictness is the
endpoint's contract, not the parser's.
"""

from __future__ import annotations

from typing import Optional, Tuple


class RangeNotSatisfiable(ValueError):
    """The range is syntactically valid but lies past EOF (HTTP 416).
    Carries ``total`` for the ``Content-Range: bytes */total`` answer."""

    def __init__(self, spec: str, total: int) -> None:
        super().__init__(f"range {spec!r} not satisfiable (total {total})")
        self.total = total


def parse_range(header: Optional[str], total: int) -> Optional[Tuple[int, int]]:
    """``Range`` header + representation length → inclusive
    ``(start, end)`` byte positions, ``None`` when the request is not a
    servable single byte range (serve the full body), or
    :class:`RangeNotSatisfiable` (answer 416).

    ``total`` must be the representation's byte length; ``total <= 0``
    has no satisfiable range at all.
    """
    if not header or not header.startswith("bytes="):
        return None
    spec = header[len("bytes="):].strip()
    if "," in spec or "-" not in spec:
        # Multi-range (or garbage): we only serve single ranges —
        # ignoring the header is the RFC-sanctioned degrade.
        return None
    start_s, _, end_s = spec.partition("-")
    start_s, end_s = start_s.strip(), end_s.strip()
    try:
        if start_s == "":
            if not end_s.isdigit():
                return None                # bytes=--5 etc.: malformed
            suffix = int(end_s)            # bytes=-N: the final N bytes
            if suffix <= 0 or total <= 0:
                # bytes=-0 is syntactically valid but has no bytes.
                raise RangeNotSatisfiable(header, max(total, 0))
            return (max(total - suffix, 0), total - 1)
        start = int(start_s)
        if start < 0:
            return None
        if total <= 0 or start >= total:
            raise RangeNotSatisfiable(header, max(total, 0))
        if end_s == "":
            return (start, total - 1)      # bytes=S-: open-ended
        end = int(end_s)
        if end < start:
            return None                    # invalid spec → ignore (200)
        return (start, min(end, total - 1))
    except ValueError as exc:
        if isinstance(exc, RangeNotSatisfiable):
            raise
        return None                        # non-numeric → ignore (200)


def content_range(start: int, end: int, total: int) -> str:
    """The 206 response's ``Content-Range`` value."""
    return f"bytes {start}-{end}/{total}"


def unsatisfiable_content_range(total: int) -> str:
    """The 416 response's ``Content-Range`` value."""
    return f"bytes */{total}"
