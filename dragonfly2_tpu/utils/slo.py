"""SLO evaluation: declared objectives + multi-window burn-rate alerts
(DESIGN.md §23).

An SLO declares what fraction of events must be *good* over a window —
``99% of piece fetches complete within 500 ms`` (latency objective over
a Sketch metric) or ``99.9% of flushes succeed`` (availability objective
over a good/total counter pair).  The engine tracks the cumulative
(good, total) signal and evaluates the **burn rate**: the observed bad
fraction divided by the error budget ``1 − target``.  Burn rate 1.0
means the budget is being consumed exactly at the sustainable pace;
burn rate 20 means a 30-day budget dies in ~36 hours.

Alerts follow the multi-window discipline (SRE workbook ch.5): breached
only while BOTH the fast window (default 5 m — catches the spike,
clears quickly on recovery) and the slow window (default 1 h — immune
to blips) burn above ``burn_threshold``.  The verdict is stateless in
the sample history, so replaying a metric journal through
``ingest_snapshot`` reconstructs exactly the state the live engine
served on ``/debug/slo`` — the telemetry drill's acceptance bar
(sim/telemetry.py).

Machine-readable output for the future SLO autopilot (ROADMAP):
``slo_burn_rate{slo}`` / ``slo_breached{slo}`` gauges on the default
registry, and the ``/debug/slo`` JSON on every DiagnosticsServer and
the manager REST surface.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .metrics import (
    Registry,
    Sketch,
    default_registry as _reg,
    merge_sketch_states,
    sketch_state_count_below,
)
from .tracing import _raw_lock

SLO_BURN_RATE = _reg.gauge(
    "slo_burn_rate",
    "Fast-window burn rate per SLO (bad fraction / error budget; "
    "1.0 = consuming budget exactly at the sustainable pace)",
    ["slo"],
)
SLO_BREACHED = _reg.gauge(
    "slo_breached",
    "1 while an SLO's fast AND slow windows both burn above its "
    "threshold (multi-window alert), else 0",
    ["slo"],
)

OBJECTIVES = ("latency", "availability")


@dataclass
class SLO:
    """One declared objective (config ``telemetry.slos`` entry)."""

    name: str
    objective: str                # "latency" | "availability"
    target: float                 # required good fraction, in (0, 1)
    metric: str = ""              # latency: Sketch metric name
    threshold_ms: float = 0.0     # latency: good iff ≤ threshold
    good_metric: str = ""         # availability: good-event counter
    total_metric: str = ""        # availability: total-event counter
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    burn_threshold: float = 2.0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SLO":
        unknown = set(d) - {f for f in cls.__dataclass_fields__}
        if unknown:
            raise ValueError(f"slo: unknown keys {sorted(unknown)}")
        slo = cls(**d)
        slo.validate()
        return slo

    def validate(self) -> None:
        if not self.name:
            raise ValueError("slo needs a name")
        if self.objective not in OBJECTIVES:
            raise ValueError(
                f"slo {self.name!r}: objective {self.objective!r} "
                f"not in {OBJECTIVES}"
            )
        if not (0.0 < self.target < 1.0):
            raise ValueError(
                f"slo {self.name!r}: target must be in (0, 1) — an SLO "
                "of exactly 1.0 has no error budget to burn"
            )
        if self.objective == "latency":
            if not self.metric or self.threshold_ms <= 0:
                raise ValueError(
                    f"slo {self.name!r}: latency objective needs metric "
                    "and threshold_ms > 0"
                )
        else:
            if not self.good_metric or not self.total_metric:
                raise ValueError(
                    f"slo {self.name!r}: availability objective needs "
                    "good_metric and total_metric"
                )
        if not (0 < self.fast_window_s < self.slow_window_s):
            raise ValueError(
                f"slo {self.name!r}: need 0 < fast_window_s < slow_window_s"
            )
        if self.burn_threshold <= 0:
            raise ValueError(f"slo {self.name!r}: burn_threshold must be > 0")


def parse_slos(raw: Sequence[Any]) -> List[SLO]:
    """Config entries → validated SLO list (ValueError on bad entries —
    surfaced by config validate())."""
    out: List[SLO] = []
    for entry in raw:
        out.append(entry if isinstance(entry, SLO) else SLO.from_dict(entry))
    names = [s.name for s in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate slo names: {names}")
    return out


def _sum_counter_state(state: Optional[Dict[str, Any]]) -> float:
    if not state:
        return 0.0
    return float(sum(v for _key, v in state.get("series", [])))


def _merged_sketch_state(state: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not state or state.get("type") != "sketch":
        return None
    return merge_sketch_states([st for _key, st in state.get("series", [])])


class SLOEngine:
    """Samples the cumulative (good, total) signal per SLO — live from a
    Registry via ``tick()``, or from replayed journal snapshots via
    ``ingest_snapshot()`` — and evaluates multi-window burn rates over
    the sample history.  Both paths share the same ingest/evaluate code,
    which is what makes live state and journal-replay state provably
    identical."""

    def __init__(
        self,
        slos: Sequence[Any],
        *,
        registry: Optional[Registry] = None,
    ) -> None:
        self.slos = parse_slos(slos)
        self.registry = registry if registry is not None else _reg
        self._mu = _raw_lock()
        # Per-SLO (t, good, total) cumulative samples, oldest first.
        self._samples: Dict[str, deque] = {s.name: deque() for s in self.slos}
        self._last: Dict[str, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signal extraction ---------------------------------------------------

    def _cumulative_live(self, slo: SLO) -> Tuple[float, float]:
        if slo.objective == "latency":
            m = self.registry.get(slo.metric)
            if not isinstance(m, Sketch):
                return 0.0, 0.0
            agg = m.aggregate_state()
            good = sketch_state_count_below(agg, slo.threshold_ms / 1e3)
            return good, float(agg["total"])
        good_m = self.registry.get(slo.good_metric)
        total_m = self.registry.get(slo.total_metric)
        good = _sum_counter_state(good_m.state()) if good_m is not None else 0.0
        total = _sum_counter_state(total_m.state()) if total_m is not None else 0.0
        return good, total

    @staticmethod
    def _cumulative_from_snapshot(
        slo: SLO, metrics: Dict[str, Any]
    ) -> Tuple[float, float]:
        if slo.objective == "latency":
            merged = _merged_sketch_state(metrics.get(slo.metric))
            if merged is None:
                return 0.0, 0.0
            good = sketch_state_count_below(merged, slo.threshold_ms / 1e3)
            return good, float(merged["total"])
        return (
            _sum_counter_state(metrics.get(slo.good_metric)),
            _sum_counter_state(metrics.get(slo.total_metric)),
        )

    # -- ingest + evaluate ---------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Dict[str, Dict[str, Any]]:
        """Sample the live registry and re-evaluate every SLO."""
        t = time.time() if now is None else now
        for slo in self.slos:
            good, total = self._cumulative_live(slo)
            self._ingest(slo, t, good, total)
        return self.evaluate(t)

    def ingest_snapshot(self, snapshot: Dict[str, Any]) -> None:
        """Feed one replayed journal snapshot (cumulative state at its
        ``ts``).  Feed snapshots of ONE process stream in ts order —
        fleet-level replay merges per-process streams first
        (tools/fleet_assemble.py)."""
        t = float(snapshot.get("ts", 0.0))
        metrics = snapshot.get("metrics", {})
        for slo in self.slos:
            good, total = self._cumulative_from_snapshot(slo, metrics)
            self._ingest(slo, t, good, total)

    def _ingest(self, slo: SLO, t: float, good: float, total: float) -> None:
        with self._mu:
            samples = self._samples[slo.name]
            samples.append((t, good, total))
            # Bound the history: one sample beyond the slow window is
            # enough to anchor the slow delta.
            horizon = t - slo.slow_window_s * 1.25
            while len(samples) > 2 and samples[1][0] <= horizon:
                samples.popleft()

    @staticmethod
    def _window_burn(
        samples: Sequence[Tuple[float, float, float]],
        t: float,
        window_s: float,
        budget: float,
    ) -> Tuple[float, float]:
        """(burn_rate, events_in_window) over [t−window, t].  Baseline =
        the newest sample at or before the window start (the oldest one
        during warm-up, so a fresh engine still answers)."""
        if not samples:
            return 0.0, 0.0
        start = t - window_s
        base = samples[0]
        for s in samples:
            if s[0] <= start:
                base = s
            else:
                break
        cur = samples[-1]
        d_total = cur[2] - base[2]
        if d_total <= 0:
            return 0.0, 0.0
        d_bad = (cur[2] - cur[1]) - (base[2] - base[1])
        bad_frac = min(max(d_bad / d_total, 0.0), 1.0)
        return bad_frac / budget, d_total

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Dict[str, Any]]:
        """Burn rates + breach verdicts from the current sample history;
        updates the ``slo_burn_rate``/``slo_breached`` gauges.

        ``evaluate`` is a declared replay root (DESIGN.md §27): its
        verdicts must be a pure function of the ingested samples and
        ``now``.  The live edge (``tick``) samples the wall clock
        OUTSIDE the replay path and passes it through the declared
        ``now`` injection seam; when ``now`` is omitted the engine
        anchors at the newest ingested sample instead of reading the
        ambient clock (DF018) — identical verdicts either way, since
        window ends are already clamped to the newest sample below."""
        if now is None:
            with self._mu:
                t = max(
                    (s[-1][0] for s in self._samples.values() if s),
                    default=0.0,
                )
        else:
            t = now
        out: Dict[str, Dict[str, Any]] = {}
        for slo in self.slos:
            with self._mu:
                samples = list(self._samples[slo.name])
            if samples:
                t_eval = max(t, samples[-1][0])
            else:
                t_eval = t
            budget = 1.0 - slo.target
            fast, fast_events = self._window_burn(
                samples, t_eval, slo.fast_window_s, budget
            )
            slow, slow_events = self._window_burn(
                samples, t_eval, slo.slow_window_s, budget
            )
            breached = (
                fast >= slo.burn_threshold and slow >= slo.burn_threshold
            )
            state = {
                "name": slo.name,
                "objective": slo.objective,
                "target": slo.target,
                "burn_threshold": slo.burn_threshold,
                "fast_window_s": slo.fast_window_s,
                "slow_window_s": slo.slow_window_s,
                "burn_rate_fast": round(fast, 6),
                "burn_rate_slow": round(slow, 6),
                "events_fast": fast_events,
                "events_slow": slow_events,
                "breached": breached,
                "samples": len(samples),
            }
            out[slo.name] = state
            with self._mu:
                self._last[slo.name] = state
            SLO_BURN_RATE.set(fast, slo=slo.name)
            SLO_BREACHED.set(1.0 if breached else 0.0, slo=slo.name)
        return out

    def state(self) -> Dict[str, Any]:
        """Last evaluated state (the ``/debug/slo`` payload) without
        re-sampling."""
        with self._mu:
            slos = [dict(self._last[s.name]) for s in self.slos
                    if s.name in self._last]
        return {"slos": slos}

    # -- background cadence --------------------------------------------------

    def start(self, interval_s: float = 5.0) -> "SLOEngine":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, args=(max(0.05, interval_s),),
                name="slo-engine", daemon=True,
            )
            self._thread.start()
        return self

    def _run(self, interval_s: float) -> None:
        # Bounded waits (DF008 timeout sweep): stop event doubles as the
        # cadence clock.
        while not self._stop.wait(interval_s):
            self.tick()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            while t.is_alive():
                t.join(5.0)
                break


# ---------------------------------------------------------------------------
# Journal replay (fleet_assemble + the drill's live-vs-replay parity bar)
# ---------------------------------------------------------------------------


def replay_fleet(
    snapshots: Sequence[Dict[str, Any]], slos: Sequence[Any]
) -> SLOEngine:
    """Reconstruct an SLO engine from replayed journal snapshots —
    one process's stream or many processes' merged.

    Per-process snapshots are cumulative, so the fleet-cumulative signal
    at time t is the SUM over runs of each run's latest snapshot at or
    before t.  The returned engine's ``evaluate(t)`` then answers
    exactly what a live fleet-wide engine would have — the drill asserts
    this equals what ``/debug/slo`` served (sim/telemetry.py)."""
    engine = SLOEngine(slos, registry=Registry())
    by_run: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for snap in snapshots:
        key = (str(snap.get("service", "")), str(snap.get("run_id", "")))
        by_run.setdefault(key, []).append(snap)
    for stream in by_run.values():
        stream.sort(key=lambda s: (s.get("seq", 0), s.get("ts", 0.0)))
    times = sorted({float(s.get("ts", 0.0)) for s in snapshots})
    # Per-run stream pointers advance monotonically with t.
    pointers = {key: 0 for key in by_run}
    current: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for t in times:
        for key, stream in by_run.items():
            i = pointers[key]
            while i < len(stream) and float(stream[i].get("ts", 0.0)) <= t:
                current[key] = stream[i]
                i += 1
            pointers[key] = i
        for slo in engine.slos:
            good = total = 0.0
            for snap in current.values():
                g, n = engine._cumulative_from_snapshot(
                    slo, snap.get("metrics", {})
                )
                good += g
                total += n
            engine._ingest(slo, t, good, total)
    if times:
        engine.evaluate(times[-1])
    return engine


# ---------------------------------------------------------------------------
# Process-installed engine (the /debug/slo endpoints read it)
# ---------------------------------------------------------------------------

_ENGINE: Optional[SLOEngine] = None


def install_engine(engine: Optional[SLOEngine]) -> None:
    global _ENGINE
    _ENGINE = engine


def current_engine() -> Optional[SLOEngine]:
    return _ENGINE


def debug_state() -> Dict[str, Any]:
    """The ``/debug/slo`` payload: last evaluated per-SLO state, or an
    empty declaration when no engine is installed."""
    engine = _ENGINE
    if engine is None:
        return {"slos": [], "installed": False}
    out = engine.state()
    out["installed"] = True
    return out
