"""Tracing (reference: OpenTelemetry throughout — otelgrpc handlers on
every server/client, explicit spans around task/piece lifecycles,
SURVEY §5.1).

A minimal otel-shaped tracer: named spans with attributes, parent/child
nesting via a context stack, exporters (in-memory for tests, JSONL for
ops).  Services instrument the same seams the reference does: download
task, piece fetch, schedule round, train run.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_ns: int
    end_ns: int = 0
    attributes: Dict[str, Any] = field(default_factory=dict)
    status: str = "ok"

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def set(self, **attrs: Any) -> None:
        self.attributes.update(attrs)


class Tracer:
    def __init__(self, service: str = "dragonfly", exporter: Optional["SpanExporter"] = None):
        self.service = service
        self.exporter = exporter or InMemoryExporter()
        self._local = threading.local()

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, name: str, **attributes: Any) -> Iterator[Span]:
        stack = self._stack()
        parent = stack[-1] if stack else None
        span = Span(
            name=name,
            trace_id=parent.trace_id if parent else uuid.uuid4().hex,
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent.span_id if parent else None,
            start_ns=time.time_ns(),
            attributes=dict(attributes),
        )
        stack.append(span)
        try:
            yield span
        except BaseException as exc:
            span.status = f"error: {type(exc).__name__}"
            raise
        finally:
            span.end_ns = time.time_ns()
            stack.pop()
            self.exporter.export(span)


class SpanExporter:
    def export(self, span: Span) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class InMemoryExporter(SpanExporter):
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.spans: List[Span] = []

    def export(self, span: Span) -> None:
        with self._mu:
            self.spans.append(span)

    def find(self, name: str) -> List[Span]:
        with self._mu:
            return [s for s in self.spans if s.name == name]


class JSONLExporter(SpanExporter):
    def __init__(self, path: str) -> None:
        self.path = path
        self._mu = threading.Lock()

    def export(self, span: Span) -> None:
        record = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "start_ns": span.start_ns,
            "duration_ms": span.duration_ms,
            "status": span.status,
            "attributes": span.attributes,
        }
        with self._mu:
            with open(self.path, "a") as f:
                f.write(json.dumps(record) + "\n")


# Process-default tracer (services may construct scoped ones).
default_tracer = Tracer()
