"""Tracing (reference: OpenTelemetry throughout — otelgrpc handlers on
every server/client, explicit spans around task/piece lifecycles,
SURVEY §5.1).

A minimal otel-shaped tracer: named spans with attributes, parent/child
nesting via a context stack, exporters (in-memory for tests, JSONL for
ops).  Services instrument the same seams the reference does: download
task, piece fetch, schedule round, train run.

Cross-process propagation uses the W3C ``traceparent`` header format
(``00-<trace_id>-<span_id>-01``) the reference's otelgrpc interceptors
speak (cmd/dependency/dependency.go:263-297): clients ``inject()`` the
current context into request headers/metadata, servers open their
handler span with ``remote_span()`` so one trace id follows a download
through daemon → scheduler → trainer hops.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_ns: int
    end_ns: int = 0
    attributes: Dict[str, Any] = field(default_factory=dict)
    status: str = "ok"

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    @property
    def traceparent(self) -> str:
        """W3C trace-context header value for this span."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def set(self, **attrs: Any) -> None:
        self.attributes.update(attrs)


TRACEPARENT_HEADER = "traceparent"


def _raw_lock():
    """Exporter bookkeeping locks, built from the REAL lock factory
    (the dfcrash precedent): spans may close while a caller holds a
    project lock, and a witnessed exporter lock would put
    caller-lock → exporter-lock edges into the runtime lock graph that
    the static analyzer — which does not traverse generator
    contextmanagers — can never corroborate.  Diagnostics must not
    instrument diagnostics."""
    try:
        from .dflock import _REAL_LOCK

        return _REAL_LOCK()
    except ImportError:  # pragma: no cover — dflock always ships
        return threading.Lock()

# Process-wide tracing toggle (config `tracing.enable`, DESIGN.md §21).
# Disabled, span() hands out a shared no-op span: no ids are drawn, no
# stack is kept, nothing exports — the operator's off switch is also the
# bench's tracing-off arm (tools/bench_sched.py overhead rounds).
_ENABLED = True


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def enabled() -> bool:
    return _ENABLED


class _NoopSpan:
    """Stand-in yielded while tracing is disabled: accepts the same
    writes a real Span does and drops them."""

    __slots__ = ("status",)

    name = ""
    trace_id = ""
    span_id = ""
    parent_id = None
    start_ns = 0
    end_ns = 0
    attributes: Dict[str, Any] = {}
    traceparent = ""

    def set(self, **attrs: Any) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


def trace_sampled(trace_id: str, rate: float) -> bool:
    """Head-sampling decision BY TRACE ID: deterministic across processes
    (crc32 of the id), so every plane keeps or drops the SAME traces and
    a sampled trace assembles end-to-end instead of arriving with random
    per-process holes."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) / 2**32 < rate


def parse_traceparent(value: Optional[str]):
    """→ (trace_id, span_id) or None for absent/malformed headers."""
    if not value:
        return None
    parts = value.split("-")
    if len(parts) < 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    return parts[1], parts[2]


class Tracer:
    def __init__(self, service: str = "dragonfly", exporter: Optional["SpanExporter"] = None):
        self.service = service
        self.exporter = exporter or InMemoryExporter()
        self._local = threading.local()

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        _trace_id: Optional[str] = None,
        _parent_id: Optional[str] = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        """One span lifecycle.  ``_trace_id``/``_parent_id`` seed a REMOTE
        parent context (remote_span uses them); normally the local stack
        provides the parentage."""
        if not _ENABLED:
            yield _NOOP_SPAN  # type: ignore[misc]
            return
        stack = self._stack()
        parent = stack[-1] if stack else None
        span = Span(
            name=name,
            trace_id=_trace_id or (parent.trace_id if parent else uuid.uuid4().hex),
            span_id=uuid.uuid4().hex[:16],
            parent_id=_parent_id or (parent.span_id if parent else None),
            start_ns=time.time_ns(),
            attributes=dict(attributes),
        )
        stack.append(span)
        try:
            yield span
        except BaseException as exc:
            span.status = f"error: {type(exc).__name__}"
            raise
        finally:
            span.end_ns = time.time_ns()
            stack.pop()
            self.exporter.export(span)

    def current_trace_id(self) -> Optional[str]:
        """Trace id of the innermost active span on THIS thread, or None.
        Cheap enough for metric hot paths (one thread-local read) — the
        histogram exemplar hook joins a slow bucket to its trace here."""
        stack = getattr(self._local, "stack", None)
        if not stack:
            return None
        return stack[-1].trace_id

    # -- cross-process propagation (otelgrpc-interceptor analog) -------------

    def inject(self) -> Dict[str, str]:
        """Headers/metadata for an outgoing request: the current span's
        context, or empty when no span is active (callers just merge)."""
        stack = self._stack()
        if not stack:
            return {}
        return {TRACEPARENT_HEADER: stack[-1].traceparent}

    @contextlib.contextmanager
    def remote_span(
        self, name: str, traceparent: Optional[str], **attributes: Any
    ) -> Iterator[Span]:
        """Server-side handler span linked to the CALLER's context: same
        trace id, parent = the caller's span id.  Falls back to a local
        root span when the header is absent/malformed."""
        parsed = parse_traceparent(traceparent)
        trace_id, parent_span_id = parsed if parsed else (None, None)
        with self.span(
            name, _trace_id=trace_id, _parent_id=parent_span_id, **attributes
        ) as span:
            yield span


class SpanExporter:
    def export(self, span: Span) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class InMemoryExporter(SpanExporter):
    """Bounded ring of recent spans.  This is the process DEFAULT
    exporter and every RPC handler/download/piece worker exports through
    it — unbounded growth would leak a long-running daemon to OOM."""

    def __init__(self, max_spans: int = 4096) -> None:
        import collections

        self._mu = _raw_lock()
        self.spans = collections.deque(maxlen=max_spans)

    def export(self, span: Span) -> None:
        with self._mu:
            self.spans.append(span)

    def find(self, name: str) -> List[Span]:
        with self._mu:
            return [s for s in self.spans if s.name == name]


class JSONLExporter(SpanExporter):
    def __init__(self, path: str) -> None:
        self.path = path
        self._mu = _raw_lock()

    def export(self, span: Span) -> None:
        record = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "start_ns": span.start_ns,
            "duration_ms": span.duration_ms,
            "status": span.status,
            "attributes": span.attributes,
        }
        with self._mu:
            with open(self.path, "a") as f:
                f.write(json.dumps(record) + "\n")


def otlp_trace_schema() -> Dict[str, Any]:
    """The vendored JSON Schema for ``ExportTraceServiceRequest``
    (utils/otlp_trace_schema.json — a transcription of
    opentelemetry-proto's trace/common/resource v1 protos under the
    proto3 JSON mapping, strict additionalProperties).  Every request
    this module emits validates against it (tests/test_utils.py); no
    OTLP-ingesting binary exists in the sandbox, so the schema stands
    in for the collector the reference proved its wiring with
    (cmd/dependency/dependency.go:263-297 ran Jaeger)."""
    import os

    path = os.path.join(os.path.dirname(__file__), "otlp_trace_schema.json")
    with open(path) as f:
        return json.load(f)


def _otlp_value(v: Any) -> Dict[str, Any]:
    """Python attribute → OTLP AnyValue (proto3-JSON encoding rules:
    int64 rides as a string, doubles as numbers)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def span_to_otlp(span: Span) -> Dict[str, Any]:
    """One Span → the OTLP/JSON span object (trace/span ids are HEX in
    the OTLP/JSON encoding, unlike generic proto3-JSON's base64 —
    opentelemetry-proto's documented deviation)."""
    out: Dict[str, Any] = {
        "traceId": span.trace_id,
        "spanId": span.span_id,
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(span.start_ns),
        "endTimeUnixNano": str(span.end_ns),
        "attributes": [
            {"key": k, "value": _otlp_value(v)}
            for k, v in span.attributes.items()
        ],
        "status": (
            {"code": 1}
            if span.status == "ok"
            else {"code": 2, "message": span.status}
        ),
    }
    if span.parent_id:
        out["parentSpanId"] = span.parent_id
    return out


class OTLPJSONExporter(SpanExporter):
    """OTLP/JSON exporter — the reference exports to Jaeger via OTel
    (cmd/dependency/dependency.go:263-297); this emits the standard
    ``ExportTraceServiceRequest`` JSON any OTLP collector (Jaeger ≥1.35
    at ``:4318/v1/traces``, otel-collector, Tempo) ingests.

    ``target`` starting with ``http://``/``https://`` POSTs batches to
    that endpoint; anything else is a file path appended one request
    per line (replayable with curl).  Spans buffer up to ``batch_size``
    then flush; a long-running service's tail flushes on close()/atexit.
    Export failures are counted, never raised, and HTTP posts happen on
    a background sender thread behind a bounded queue — a slow/down
    collector must not stall the span-producing data-plane threads
    (span end runs inside piece workers and RPC handlers).
    """

    def __init__(
        self,
        target: str,
        *,
        service: str = "dragonfly",
        batch_size: int = 64,
        queue_batches: int = 16,
    ) -> None:
        import atexit
        import queue as _queue

        self.target = target
        self.service = service
        self.batch_size = batch_size
        self.dropped = 0
        self._mu = _raw_lock()
        self._buf: List[Span] = []
        self._http = target.startswith(("http://", "https://"))
        if self._http:
            self._q: "_queue.Queue" = _queue.Queue(maxsize=queue_batches)
            self._worker = threading.Thread(
                target=self._drain, name="otlp-export", daemon=True
            )
            self._worker.start()
        atexit.register(self.close)

    def export(self, span: Span) -> None:
        with self._mu:
            self._buf.append(span)
            if len(self._buf) < self.batch_size:
                return
            batch, self._buf = self._buf, []
        self._dispatch(batch)

    def flush(self) -> None:
        with self._mu:
            batch, self._buf = self._buf, []
        if batch:
            self._dispatch(batch)
        if self._http:
            # Bounded drain-wait (DF008 timeout sweep): Queue.join() has
            # no timeout parameter, and a wedged exporter must not hang
            # flush() forever — wait on the queue's own all_tasks_done
            # condition with a deadline instead.
            deadline = time.monotonic() + 30.0
            with self._q.all_tasks_done:
                while self._q.unfinished_tasks and time.monotonic() < deadline:
                    self._q.all_tasks_done.wait(1.0)

    def close(self) -> None:
        self.flush()

    def _dispatch(self, batch: List[Span]) -> None:
        if not self._http:
            self._send(batch)
            return
        import queue as _queue

        try:
            self._q.put_nowait(batch)
        except _queue.Full:
            # Collector can't keep up: shed THIS batch, never block the
            # producing thread.
            with self._mu:
                self.dropped += len(batch)

    def _drain(self) -> None:
        import queue as _queue

        while True:
            # Bounded get + loop (DF008 timeout sweep): periodic wake-ups
            # keep this exporter visible to watchdog stack dumps.
            try:
                batch = self._q.get(timeout=30.0)
            except _queue.Empty:
                continue
            try:
                self._send(batch)
            finally:
                self._q.task_done()

    def _request(self, batch: List[Span]) -> Dict[str, Any]:
        return build_export_request(self.service, batch)

    def _send(self, batch: List[Span]) -> None:
        payload = json.dumps(self._request(batch))
        try:
            if self._http:
                import urllib.request

                req = urllib.request.Request(
                    self.target,
                    data=payload.encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                urllib.request.urlopen(req, timeout=10).close()
            else:
                # Under the lock: concurrent flushes interleaving their
                # multi-KB appends would corrupt the JSONL stream.
                with self._mu:
                    with open(self.target, "a") as f:
                        f.write(payload + "\n")
        except Exception:  # noqa: BLE001 — observability must not crash the plane
            with self._mu:
                self.dropped += len(batch)


def build_export_request(service: str, batch: List[Span]) -> Dict[str, Any]:
    """A batch of spans → one ``ExportTraceServiceRequest`` (OTLP/JSON),
    the unit every exporter emits and the vendored schema validates."""
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": [
                        {
                            "key": "service.name",
                            "value": {"stringValue": service},
                        }
                    ]
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "dragonfly2_tpu.utils.tracing"},
                        "spans": [span_to_otlp(s) for s in batch],
                    }
                ],
            }
        ]
    }


# ---------------------------------------------------------------------------
# Flight recorder: crash-safe durable trace log (DESIGN.md §21)
# ---------------------------------------------------------------------------

# One frame per ExportTraceServiceRequest:
#   b"DFTL1 <payload_len> <crc32 payload, 8 hex>\n" + payload + b"\n"
# The header carries the exact byte length (a reader never trusts the
# payload to self-terminate) and the digest (a half-written or bit-rotted
# frame is NEVER admitted on replay).  A SIGKILL mid-append leaves at
# most one torn frame at the TAIL, which replay tolerates by stopping.
FRAME_MAGIC = b"DFTL1 "


class DurableSpanExporter(SpanExporter):
    """Per-process append-only OTLP/JSON-lines trace log.

    Crash-safe by construction: each frame is one ``os.write`` on an
    O_APPEND fd (the kernel serializes appends), written at export time —
    by default every finished span becomes durable IMMEDIATELY
    (``batch_size=1``), so a SIGKILLed daemon's log still holds every
    span that ended before the kill and ``tools/trace_assemble.py`` can
    stitch the surviving per-process logs into the end-to-end trace.

    ``sample_rate`` head-samples BY TRACE ID (``trace_sampled``):
    deterministic across processes, so a kept trace is kept on every
    plane.  Export failures are counted in ``dropped``, never raised —
    observability must not crash the plane.
    """

    def __init__(
        self,
        path: str,
        *,
        service: str = "dragonfly",
        sample_rate: float = 1.0,
        batch_size: int = 1,
        fsync: bool = False,
    ) -> None:
        import atexit

        self.path = path
        self.service = service
        self.sample_rate = sample_rate
        self.batch_size = max(1, batch_size)
        self.fsync = fsync
        self.exported = 0
        self.sampled_out = 0
        self.dropped = 0
        self._mu = _raw_lock()
        self._buf: List[Span] = []
        self._fd: Optional[int] = None
        atexit.register(self.close)

    def export(self, span: Span) -> None:
        if not trace_sampled(span.trace_id, self.sample_rate):
            with self._mu:
                self.sampled_out += 1
            return
        with self._mu:
            self._buf.append(span)
            if len(self._buf) < self.batch_size:
                return
            batch, self._buf = self._buf, []
        self._write(batch)

    def flush(self) -> None:
        with self._mu:
            batch, self._buf = self._buf, []
        if batch:
            self._write(batch)

    def close(self) -> None:
        self.flush()
        with self._mu:
            fd, self._fd = self._fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass

    def _write(self, batch: List[Span]) -> None:
        # sort_keys pins canonical frame bytes (DF019): equal batches
        # must serialize identically regardless of dict hash order.
        payload = json.dumps(
            build_export_request(self.service, batch), sort_keys=True
        ).encode()
        frame = (
            FRAME_MAGIC
            + f"{len(payload)} {zlib.crc32(payload) & 0xFFFFFFFF:08x}\n".encode()
            + payload
            + b"\n"
        )
        with self._mu:
            try:
                if self._fd is None:
                    self._fd = os.open(
                        self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
                    )
                os.write(self._fd, frame)
                if self.fsync:
                    os.fsync(self._fd)
                self.exported += len(batch)
            except OSError:
                self.dropped += len(batch)


class CompositeExporter(SpanExporter):
    """Fan one span out to several exporters — the standard wiring keeps
    the in-memory ring (``/debug/spans``) alongside the durable log."""

    def __init__(self, exporters: List[SpanExporter]) -> None:
        self.exporters = list(exporters)

    def export(self, span: Span) -> None:
        for e in self.exporters:
            e.export(span)

    def flush(self) -> None:
        for e in self.exporters:
            if hasattr(e, "flush"):
                e.flush()

    def close(self) -> None:
        for e in self.exporters:
            if hasattr(e, "close"):
                e.close()

    def find(self, cls) -> Optional[SpanExporter]:
        for e in self.exporters:
            if isinstance(e, cls):
                return e
        return None


def replay_trace_log(path: str) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Replay a durable trace log → (requests, stats).

    Stats: ``frames`` admitted, ``corrupt`` frames rejected by digest or
    JSON decode (NEVER admitted), ``torn_tail`` True when the file ends
    inside a frame (the expected SIGKILL signature — tolerated, not an
    error)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return [], {"frames": 0, "corrupt": 0, "torn_tail": False}
    requests: List[Dict[str, Any]] = []
    corrupt = 0
    torn = False
    pos = 0
    while True:
        idx = data.find(FRAME_MAGIC, pos)
        if idx < 0:
            break
        nl = data.find(b"\n", idx)
        if nl < 0:
            torn = True  # header itself torn at the tail
            break
        header = data[idx + len(FRAME_MAGIC) : nl]
        try:
            len_s, crc_s = header.split()
            length, crc = int(len_s), int(crc_s, 16)
        except ValueError:
            corrupt += 1
            pos = idx + 1  # garbage where a header should be: resync
            continue
        payload = data[nl + 1 : nl + 1 + length]
        if len(payload) < length:
            # Frame cut mid-payload.  At EOF that's the torn tail a
            # SIGKILL leaves (tolerated); mid-file (another frame starts
            # later) it's a corrupt frame — reject and resync.
            nxt = data.find(FRAME_MAGIC, idx + 1)
            if nxt < 0:
                torn = True
                break
            corrupt += 1
            pos = nxt
            continue
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            corrupt += 1
            pos = idx + 1  # digest mismatch: frame not admitted; resync
            continue
        try:
            requests.append(json.loads(payload))
        except ValueError:
            corrupt += 1
            pos = idx + 1
            continue
        pos = nl + 1 + length
    return requests, {"frames": len(requests), "corrupt": corrupt, "torn_tail": torn}


def log_spans(requests: List[Dict[str, Any]]) -> Iterator[Dict[str, Any]]:
    """Flatten replayed requests → span dicts, each annotated with the
    emitting process's ``service`` (resource attr ``service.name``)."""
    for req in requests:
        for rs in req.get("resourceSpans", []):
            service = ""
            for attr in (rs.get("resource") or {}).get("attributes", []):
                if attr.get("key") == "service.name":
                    service = attr.get("value", {}).get("stringValue", "")
            for ss in rs.get("scopeSpans", []):
                for span in ss.get("spans", []):
                    out = dict(span)
                    out["service"] = service
                    yield out


def recent_spans_otlp(tracer: Optional[Tracer] = None) -> Dict[str, Any]:
    """The in-memory ring as ONE OTLP/JSON request — the ``/debug/spans``
    payload on every plane.  Works with the ring installed directly or
    inside a CompositeExporter; empty request otherwise."""
    t = tracer or default_tracer
    exporter = t.exporter
    ring: Optional[InMemoryExporter] = None
    if isinstance(exporter, InMemoryExporter):
        ring = exporter
    elif isinstance(exporter, CompositeExporter):
        found = exporter.find(InMemoryExporter)
        ring = found if isinstance(found, InMemoryExporter) else None
    if ring is None:
        return build_export_request(t.service, [])
    with ring._mu:
        spans = list(ring.spans)
    return build_export_request(t.service, spans)


# Process-default tracer (services may construct scoped ones).
default_tracer = Tracer()


def current_trace_id() -> Optional[str]:
    """Active trace id on this thread (default tracer), or None."""
    return default_tracer.current_trace_id()
