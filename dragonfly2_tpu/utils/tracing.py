"""Tracing (reference: OpenTelemetry throughout — otelgrpc handlers on
every server/client, explicit spans around task/piece lifecycles,
SURVEY §5.1).

A minimal otel-shaped tracer: named spans with attributes, parent/child
nesting via a context stack, exporters (in-memory for tests, JSONL for
ops).  Services instrument the same seams the reference does: download
task, piece fetch, schedule round, train run.

Cross-process propagation uses the W3C ``traceparent`` header format
(``00-<trace_id>-<span_id>-01``) the reference's otelgrpc interceptors
speak (cmd/dependency/dependency.go:263-297): clients ``inject()`` the
current context into request headers/metadata, servers open their
handler span with ``remote_span()`` so one trace id follows a download
through daemon → scheduler → trainer hops.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_ns: int
    end_ns: int = 0
    attributes: Dict[str, Any] = field(default_factory=dict)
    status: str = "ok"

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    @property
    def traceparent(self) -> str:
        """W3C trace-context header value for this span."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def set(self, **attrs: Any) -> None:
        self.attributes.update(attrs)


TRACEPARENT_HEADER = "traceparent"


def parse_traceparent(value: Optional[str]):
    """→ (trace_id, span_id) or None for absent/malformed headers."""
    if not value:
        return None
    parts = value.split("-")
    if len(parts) < 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    return parts[1], parts[2]


class Tracer:
    def __init__(self, service: str = "dragonfly", exporter: Optional["SpanExporter"] = None):
        self.service = service
        self.exporter = exporter or InMemoryExporter()
        self._local = threading.local()

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        _trace_id: Optional[str] = None,
        _parent_id: Optional[str] = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        """One span lifecycle.  ``_trace_id``/``_parent_id`` seed a REMOTE
        parent context (remote_span uses them); normally the local stack
        provides the parentage."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        span = Span(
            name=name,
            trace_id=_trace_id or (parent.trace_id if parent else uuid.uuid4().hex),
            span_id=uuid.uuid4().hex[:16],
            parent_id=_parent_id or (parent.span_id if parent else None),
            start_ns=time.time_ns(),
            attributes=dict(attributes),
        )
        stack.append(span)
        try:
            yield span
        except BaseException as exc:
            span.status = f"error: {type(exc).__name__}"
            raise
        finally:
            span.end_ns = time.time_ns()
            stack.pop()
            self.exporter.export(span)

    # -- cross-process propagation (otelgrpc-interceptor analog) -------------

    def inject(self) -> Dict[str, str]:
        """Headers/metadata for an outgoing request: the current span's
        context, or empty when no span is active (callers just merge)."""
        stack = self._stack()
        if not stack:
            return {}
        return {TRACEPARENT_HEADER: stack[-1].traceparent}

    @contextlib.contextmanager
    def remote_span(
        self, name: str, traceparent: Optional[str], **attributes: Any
    ) -> Iterator[Span]:
        """Server-side handler span linked to the CALLER's context: same
        trace id, parent = the caller's span id.  Falls back to a local
        root span when the header is absent/malformed."""
        parsed = parse_traceparent(traceparent)
        trace_id, parent_span_id = parsed if parsed else (None, None)
        with self.span(
            name, _trace_id=trace_id, _parent_id=parent_span_id, **attributes
        ) as span:
            yield span


class SpanExporter:
    def export(self, span: Span) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class InMemoryExporter(SpanExporter):
    """Bounded ring of recent spans.  This is the process DEFAULT
    exporter and every RPC handler/download/piece worker exports through
    it — unbounded growth would leak a long-running daemon to OOM."""

    def __init__(self, max_spans: int = 4096) -> None:
        import collections

        self._mu = threading.Lock()
        self.spans = collections.deque(maxlen=max_spans)

    def export(self, span: Span) -> None:
        with self._mu:
            self.spans.append(span)

    def find(self, name: str) -> List[Span]:
        with self._mu:
            return [s for s in self.spans if s.name == name]


class JSONLExporter(SpanExporter):
    def __init__(self, path: str) -> None:
        self.path = path
        self._mu = threading.Lock()

    def export(self, span: Span) -> None:
        record = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "start_ns": span.start_ns,
            "duration_ms": span.duration_ms,
            "status": span.status,
            "attributes": span.attributes,
        }
        with self._mu:
            with open(self.path, "a") as f:
                f.write(json.dumps(record) + "\n")


# Process-default tracer (services may construct scoped ones).
default_tracer = Tracer()
