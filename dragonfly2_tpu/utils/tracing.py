"""Tracing (reference: OpenTelemetry throughout — otelgrpc handlers on
every server/client, explicit spans around task/piece lifecycles,
SURVEY §5.1).

A minimal otel-shaped tracer: named spans with attributes, parent/child
nesting via a context stack, exporters (in-memory for tests, JSONL for
ops).  Services instrument the same seams the reference does: download
task, piece fetch, schedule round, train run.

Cross-process propagation uses the W3C ``traceparent`` header format
(``00-<trace_id>-<span_id>-01``) the reference's otelgrpc interceptors
speak (cmd/dependency/dependency.go:263-297): clients ``inject()`` the
current context into request headers/metadata, servers open their
handler span with ``remote_span()`` so one trace id follows a download
through daemon → scheduler → trainer hops.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_ns: int
    end_ns: int = 0
    attributes: Dict[str, Any] = field(default_factory=dict)
    status: str = "ok"

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    @property
    def traceparent(self) -> str:
        """W3C trace-context header value for this span."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def set(self, **attrs: Any) -> None:
        self.attributes.update(attrs)


TRACEPARENT_HEADER = "traceparent"


def parse_traceparent(value: Optional[str]):
    """→ (trace_id, span_id) or None for absent/malformed headers."""
    if not value:
        return None
    parts = value.split("-")
    if len(parts) < 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    return parts[1], parts[2]


class Tracer:
    def __init__(self, service: str = "dragonfly", exporter: Optional["SpanExporter"] = None):
        self.service = service
        self.exporter = exporter or InMemoryExporter()
        self._local = threading.local()

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        _trace_id: Optional[str] = None,
        _parent_id: Optional[str] = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        """One span lifecycle.  ``_trace_id``/``_parent_id`` seed a REMOTE
        parent context (remote_span uses them); normally the local stack
        provides the parentage."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        span = Span(
            name=name,
            trace_id=_trace_id or (parent.trace_id if parent else uuid.uuid4().hex),
            span_id=uuid.uuid4().hex[:16],
            parent_id=_parent_id or (parent.span_id if parent else None),
            start_ns=time.time_ns(),
            attributes=dict(attributes),
        )
        stack.append(span)
        try:
            yield span
        except BaseException as exc:
            span.status = f"error: {type(exc).__name__}"
            raise
        finally:
            span.end_ns = time.time_ns()
            stack.pop()
            self.exporter.export(span)

    # -- cross-process propagation (otelgrpc-interceptor analog) -------------

    def inject(self) -> Dict[str, str]:
        """Headers/metadata for an outgoing request: the current span's
        context, or empty when no span is active (callers just merge)."""
        stack = self._stack()
        if not stack:
            return {}
        return {TRACEPARENT_HEADER: stack[-1].traceparent}

    @contextlib.contextmanager
    def remote_span(
        self, name: str, traceparent: Optional[str], **attributes: Any
    ) -> Iterator[Span]:
        """Server-side handler span linked to the CALLER's context: same
        trace id, parent = the caller's span id.  Falls back to a local
        root span when the header is absent/malformed."""
        parsed = parse_traceparent(traceparent)
        trace_id, parent_span_id = parsed if parsed else (None, None)
        with self.span(
            name, _trace_id=trace_id, _parent_id=parent_span_id, **attributes
        ) as span:
            yield span


class SpanExporter:
    def export(self, span: Span) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class InMemoryExporter(SpanExporter):
    """Bounded ring of recent spans.  This is the process DEFAULT
    exporter and every RPC handler/download/piece worker exports through
    it — unbounded growth would leak a long-running daemon to OOM."""

    def __init__(self, max_spans: int = 4096) -> None:
        import collections

        self._mu = threading.Lock()
        self.spans = collections.deque(maxlen=max_spans)

    def export(self, span: Span) -> None:
        with self._mu:
            self.spans.append(span)

    def find(self, name: str) -> List[Span]:
        with self._mu:
            return [s for s in self.spans if s.name == name]


class JSONLExporter(SpanExporter):
    def __init__(self, path: str) -> None:
        self.path = path
        self._mu = threading.Lock()

    def export(self, span: Span) -> None:
        record = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "start_ns": span.start_ns,
            "duration_ms": span.duration_ms,
            "status": span.status,
            "attributes": span.attributes,
        }
        with self._mu:
            with open(self.path, "a") as f:
                f.write(json.dumps(record) + "\n")


def otlp_trace_schema() -> Dict[str, Any]:
    """The vendored JSON Schema for ``ExportTraceServiceRequest``
    (utils/otlp_trace_schema.json — a transcription of
    opentelemetry-proto's trace/common/resource v1 protos under the
    proto3 JSON mapping, strict additionalProperties).  Every request
    this module emits validates against it (tests/test_utils.py); no
    OTLP-ingesting binary exists in the sandbox, so the schema stands
    in for the collector the reference proved its wiring with
    (cmd/dependency/dependency.go:263-297 ran Jaeger)."""
    import os

    path = os.path.join(os.path.dirname(__file__), "otlp_trace_schema.json")
    with open(path) as f:
        return json.load(f)


def _otlp_value(v: Any) -> Dict[str, Any]:
    """Python attribute → OTLP AnyValue (proto3-JSON encoding rules:
    int64 rides as a string, doubles as numbers)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def span_to_otlp(span: Span) -> Dict[str, Any]:
    """One Span → the OTLP/JSON span object (trace/span ids are HEX in
    the OTLP/JSON encoding, unlike generic proto3-JSON's base64 —
    opentelemetry-proto's documented deviation)."""
    out: Dict[str, Any] = {
        "traceId": span.trace_id,
        "spanId": span.span_id,
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(span.start_ns),
        "endTimeUnixNano": str(span.end_ns),
        "attributes": [
            {"key": k, "value": _otlp_value(v)}
            for k, v in span.attributes.items()
        ],
        "status": (
            {"code": 1}
            if span.status == "ok"
            else {"code": 2, "message": span.status}
        ),
    }
    if span.parent_id:
        out["parentSpanId"] = span.parent_id
    return out


class OTLPJSONExporter(SpanExporter):
    """OTLP/JSON exporter — the reference exports to Jaeger via OTel
    (cmd/dependency/dependency.go:263-297); this emits the standard
    ``ExportTraceServiceRequest`` JSON any OTLP collector (Jaeger ≥1.35
    at ``:4318/v1/traces``, otel-collector, Tempo) ingests.

    ``target`` starting with ``http://``/``https://`` POSTs batches to
    that endpoint; anything else is a file path appended one request
    per line (replayable with curl).  Spans buffer up to ``batch_size``
    then flush; a long-running service's tail flushes on close()/atexit.
    Export failures are counted, never raised, and HTTP posts happen on
    a background sender thread behind a bounded queue — a slow/down
    collector must not stall the span-producing data-plane threads
    (span end runs inside piece workers and RPC handlers).
    """

    def __init__(
        self,
        target: str,
        *,
        service: str = "dragonfly",
        batch_size: int = 64,
        queue_batches: int = 16,
    ) -> None:
        import atexit
        import queue as _queue

        self.target = target
        self.service = service
        self.batch_size = batch_size
        self.dropped = 0
        self._mu = threading.Lock()
        self._buf: List[Span] = []
        self._http = target.startswith(("http://", "https://"))
        if self._http:
            self._q: "_queue.Queue" = _queue.Queue(maxsize=queue_batches)
            self._worker = threading.Thread(
                target=self._drain, name="otlp-export", daemon=True
            )
            self._worker.start()
        atexit.register(self.close)

    def export(self, span: Span) -> None:
        with self._mu:
            self._buf.append(span)
            if len(self._buf) < self.batch_size:
                return
            batch, self._buf = self._buf, []
        self._dispatch(batch)

    def flush(self) -> None:
        with self._mu:
            batch, self._buf = self._buf, []
        if batch:
            self._dispatch(batch)
        if self._http:
            # Bounded drain-wait (DF008 timeout sweep): Queue.join() has
            # no timeout parameter, and a wedged exporter must not hang
            # flush() forever — wait on the queue's own all_tasks_done
            # condition with a deadline instead.
            deadline = time.monotonic() + 30.0
            with self._q.all_tasks_done:
                while self._q.unfinished_tasks and time.monotonic() < deadline:
                    self._q.all_tasks_done.wait(1.0)

    def close(self) -> None:
        self.flush()

    def _dispatch(self, batch: List[Span]) -> None:
        if not self._http:
            self._send(batch)
            return
        import queue as _queue

        try:
            self._q.put_nowait(batch)
        except _queue.Full:
            # Collector can't keep up: shed THIS batch, never block the
            # producing thread.
            with self._mu:
                self.dropped += len(batch)

    def _drain(self) -> None:
        import queue as _queue

        while True:
            # Bounded get + loop (DF008 timeout sweep): periodic wake-ups
            # keep this exporter visible to watchdog stack dumps.
            try:
                batch = self._q.get(timeout=30.0)
            except _queue.Empty:
                continue
            try:
                self._send(batch)
            finally:
                self._q.task_done()

    def _request(self, batch: List[Span]) -> Dict[str, Any]:
        return {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            {
                                "key": "service.name",
                                "value": {"stringValue": self.service},
                            }
                        ]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": "dragonfly2_tpu.utils.tracing"},
                            "spans": [span_to_otlp(s) for s in batch],
                        }
                    ],
                }
            ]
        }

    def _send(self, batch: List[Span]) -> None:
        payload = json.dumps(self._request(batch))
        try:
            if self._http:
                import urllib.request

                req = urllib.request.Request(
                    self.target,
                    data=payload.encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                urllib.request.urlopen(req, timeout=10).close()
            else:
                # Under the lock: concurrent flushes interleaving their
                # multi-KB appends would corrupt the JSONL stream.
                with self._mu:
                    with open(self.target, "a") as f:
                        f.write(payload + "\n")
        except Exception:  # noqa: BLE001 — observability must not crash the plane
            with self._mu:
                self.dropped += len(batch)


# Process-default tracer (services may construct scoped ones).
default_tracer = Tracer()
