"""Plugin loader (reference: internal/dfplugin — Go plugin.Open of
``d7y-<type>-plugin-<name>.so``, used for evaluator/searcher/source
plugins, dfplugin.go:43-88).

The Python analog loads ``df_<type>_plugin_<name>.py`` from a plugin dir
and calls its ``create_plugin(**options)`` factory.  Same naming
discipline, same late binding: the scheduler's ``algorithm: plugin``
resolves its evaluator here.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any, Dict, List

PLUGIN_PREFIX = "df"


class PluginError(RuntimeError):
    pass


def plugin_filename(plugin_type: str, name: str) -> str:
    return f"{PLUGIN_PREFIX}_{plugin_type}_plugin_{name}.py"


def load_plugin(plugin_dir: str, plugin_type: str, name: str, **options: Any) -> Any:
    """Load and instantiate a plugin; raises PluginError with context."""
    path = os.path.join(plugin_dir, plugin_filename(plugin_type, name))
    if not os.path.exists(path):
        raise PluginError(f"plugin not found: {path}")
    spec = importlib.util.spec_from_file_location(
        f"df_plugin_{plugin_type}_{name}", path
    )
    if spec is None or spec.loader is None:
        raise PluginError(f"cannot load spec for {path}")
    module = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(module)
    except Exception as exc:  # noqa: BLE001 — plugin boundary
        raise PluginError(f"{path}: import failed: {exc}") from exc
    factory = getattr(module, "create_plugin", None)
    if factory is None:
        raise PluginError(f"{path}: no create_plugin() factory")
    return factory(**options)


def list_plugins(plugin_dir: str) -> List[Dict[str, str]]:
    """Installed plugins (cmd/dependency plugin listing)."""
    out: List[Dict[str, str]] = []
    if not os.path.isdir(plugin_dir):
        return out
    for fname in sorted(os.listdir(plugin_dir)):
        if not fname.startswith(f"{PLUGIN_PREFIX}_") or not fname.endswith(".py"):
            continue
        parts = fname[: -len(".py")].split("_plugin_")
        if len(parts) != 2:
            continue
        ptype = parts[0][len(PLUGIN_PREFIX) + 1 :]
        out.append({"type": ptype, "name": parts[1], "file": fname})
    return out
