"""Configuration system (reference: per-service config/ + cmd/dependency).

Three tiers, mirroring the reference (SURVEY §5.6):
(a) static YAML + env overrides + validation/defaults here;
(b) dynconfig — manager-sourced dynamic values (manager/dynconfig.py);
(c) cluster-scoped overrides served through dynconfig (candidate/filter
    parent limits, consumed by scheduling).

``load_config(cls, path)`` reads YAML into nested dataclasses;
``DRAGONFLY_<SECTION>_<FIELD>`` env vars override scalar leaves;
``validate()`` enforces the reference's invariants.
"""

from .schema import (  # noqa: F401
    ConfigError,
    DaemonConfig,
    ManagerConfig,
    MetricsConfig,
    SchedulerConfigFile,
    ServerConfig,
    StorageConfig,
    TelemetrySection,
    TrainerConfigFile,
    load_config,
)
