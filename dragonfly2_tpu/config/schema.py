"""Config dataclasses + YAML/env loading + validation.

Defaults track the reference's constants (scheduler/config/constants.go:
candidate/filter parent limits 4/15 :34-37, retry limits 4/5 :66-70,
retry interval 500ms :73, probe queue/count 5/5 :112-115, trainer upload
interval 7d :198; client/config/peerhost.go daemon defaults).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Optional, Type, TypeVar, get_args, get_origin

T = TypeVar("T")

ENV_PREFIX = "DRAGONFLY"


class ConfigError(ValueError):
    pass


@dataclass
class ServerConfig:
    host: str = "0.0.0.0"
    port: int = 8002
    advertise_ip: str = ""
    # Binary gRPC listener alongside the JSON transport; -1 = disabled,
    # 0 = OS-assigned ephemeral.
    grpc_port: int = -1
    # Token-bucket server rate limit (pkg/rpc interceptor.go); 0 = off.
    rate_limit_qps: float = 0.0
    rate_limit_burst: int = 0

    def validate(self) -> None:
        # 0 = OS-assigned ephemeral port (tests / sidecar deployments).
        if not (0 <= self.port < 65536):
            raise ConfigError(f"server.port {self.port} out of range")
        if not (-1 <= self.grpc_port < 65536):
            raise ConfigError(f"server.grpc_port {self.grpc_port} out of range")


@dataclass
class MetricsConfig:
    enable: bool = True
    port: int = 8000


@dataclass
class TracingSection:
    """Flight recorder (utils/tracing.py DurableSpanExporter; DESIGN.md
    §21).  ``log_path`` turns on the per-process crash-safe trace log —
    append-only OTLP/JSON frames any plane's log feeds straight into
    ``tools/trace_assemble.py``.  ``sample_rate`` head-samples BY TRACE
    ID (deterministic across processes, so a kept trace is kept on every
    plane); the default 0.1 holds serving-path overhead under the ≤3%
    bar (BENCHMARKS.md).  ``ring_spans`` bounds the in-memory recent ring
    the ``/debug/spans`` endpoint dumps."""

    enable: bool = True
    log_path: str = ""
    sample_rate: float = 0.1
    ring_spans: int = 4096

    def validate(self) -> None:
        if not (0.0 <= self.sample_rate <= 1.0):
            raise ConfigError("tracing.sample_rate must be in [0, 1]")
        if self.ring_spans < 1:
            raise ConfigError("tracing.ring_spans must be >= 1")


@dataclass
class TelemetrySection:
    """Fleet telemetry plane (utils/metric_journal.py + utils/slo.py;
    DESIGN.md §23).  ``journal_path`` turns on the per-process crash-safe
    metric journal — append-only digest-checked DFMJ1 frames of periodic
    counter/gauge/sketch snapshots, merged fleet-wide by
    ``tools/fleet_assemble.py``.  ``slos`` declares objectives the SLO
    engine evaluates with multi-window burn-rate alerts (each entry:
    ``name``, ``objective`` latency|availability, ``target``, plus
    ``metric``+``threshold_ms`` or ``good_metric``+``total_metric``;
    optional ``fast_window_s``/``slow_window_s``/``burn_threshold``) —
    surfaced on ``/debug/slo`` and as ``slo_burn_rate{slo}`` /
    ``slo_breached{slo}`` gauges."""

    journal_path: str = ""
    journal_interval_s: float = 10.0
    slo_interval_s: float = 5.0
    slos: list = field(default_factory=list)

    def validate(self) -> None:
        if self.journal_interval_s <= 0:
            raise ConfigError("telemetry.journal_interval_s must be > 0")
        if self.slo_interval_s <= 0:
            raise ConfigError("telemetry.slo_interval_s must be > 0")
        try:
            from ..utils.slo import parse_slos

            parse_slos(self.slos)
        except ValueError as exc:
            raise ConfigError(f"telemetry.slos: {exc}") from exc


@dataclass
class LogConfig:
    level: str = "info"
    dir: str = ""
    console: bool = False
    max_bytes: int = 50 << 20
    backups: int = 5

    def validate(self) -> None:
        if self.level not in ("debug", "info", "warning", "error"):
            raise ConfigError(f"log.level {self.level!r} unknown")


@dataclass
class StorageConfig:
    dir: str = "/var/lib/dragonfly/records"
    buffer_size: int = 100
    max_size: int = 100 << 20
    max_backups: int = 10


@dataclass
class SchedulingSection:
    algorithm: str = "default"        # default | nt | ml (evaluator.go:28-46)
    candidate_parent_limit: int = 4
    filter_parent_limit: int = 15
    retry_limit: int = 5
    retry_back_to_source_limit: int = 4
    retry_interval_s: float = 0.5
    back_to_source_count: int = 3
    # Server-initiated stall sweep (push.StallMonitor): running peers
    # idle beyond max_idle get fresh parents pushed down the bidi wire.
    # 0 disables the monitor.
    stall_max_idle_s: float = 10.0
    stall_sweep_interval_s: float = 2.0
    # Serving engine (ml algorithm, DESIGN.md §14): bounded linger the
    # cross-request micro-batcher waits to coalesce concurrent announce
    # evaluations into one padded scorer call (0 = flush immediately),
    # and the host-feature cache's LRU bound.
    eval_batch_linger_ms: float = 1.5
    eval_feature_cache_hosts: int = 65536
    # Rollout plane (DESIGN.md §15): registry poll cadence with seeded
    # anti-herd jitter, the shadow-scoring sample fraction, and the
    # evaluate→report cycle interval.
    model_poll_interval_s: float = 300.0
    model_poll_jitter: float = 0.1
    shadow_sample_rate: float = 0.1
    rollout_report_interval_s: float = 60.0
    # Regional model keys (DESIGN.md §29): this scheduler's idc/region.
    # Set, the model subscriber polls the per-region specialization
    # ``<model>@<idc>`` first and falls back to the global model; empty
    # keeps the reference's fleet-wide single-key behaviour.
    idc: str = ""
    # Sharded fleet (DESIGN.md §24): admission control bounds for this
    # shard — concurrent task-scoped requests past max_inflight (and
    # announce p99 past the budget) start shedding the lowest priority
    # classes with 503+Retry-After.  0 max_inflight disables admission.
    shard_max_inflight: int = 512
    shard_p99_budget_ms: float = 50.0
    # Tenant QoS plane (DESIGN.md §26): with telemetry.slos declared, a
    # metric journal configured and admission enabled, the SLO autopilot
    # feeds burn verdicts back into the shed floor + over-quota tenants'
    # announce caps; False leaves admission on the measured signals only.
    qos_autopilot: bool = True

    def validate(self) -> None:
        if self.algorithm not in ("default", "nt", "ml"):
            raise ConfigError(f"scheduling.algorithm {self.algorithm!r} unknown")
        if self.candidate_parent_limit > self.filter_parent_limit:
            raise ConfigError("candidate_parent_limit > filter_parent_limit")
        if self.candidate_parent_limit < 1:
            raise ConfigError("candidate_parent_limit < 1")
        if self.eval_batch_linger_ms < 0:
            raise ConfigError("eval_batch_linger_ms < 0")
        if self.eval_feature_cache_hosts < 1:
            raise ConfigError("eval_feature_cache_hosts < 1")
        if not (0.0 <= self.shadow_sample_rate <= 1.0):
            raise ConfigError("shadow_sample_rate must be in [0, 1]")
        if self.shard_max_inflight < 0:
            raise ConfigError("shard_max_inflight < 0")
        if self.shard_p99_budget_ms <= 0:
            raise ConfigError("shard_p99_budget_ms <= 0")
        if not (0.0 <= self.model_poll_jitter < 0.5):
            raise ConfigError("model_poll_jitter must be in [0, 0.5)")


@dataclass
class NetworkTopologySection:
    enable: bool = True
    probe_queue_length: int = 5
    probe_count: int = 5
    collect_interval_s: float = 2 * 3600.0


@dataclass
class TrainerLinkSection:
    enable: bool = False
    addr: str = ""
    interval_s: float = 7 * 24 * 3600.0  # constants.go:198


@dataclass
class GCSection:
    host_ttl_s: float = 6 * 3600.0
    task_ttl_s: float = 2 * 3600.0
    peer_ttl_s: float = 24 * 3600.0
    interval_s: float = 60.0


@dataclass
class SecuritySection:
    """Auto-issued mTLS (the reference certify flow, pkg/rpc/security):
    with ``auto_issue`` on and a manager address configured, the service
    requests its identity from the manager's cluster CA at boot
    (security/ca.py request_from_manager) — the key never leaves the
    process; only the CSR travels."""

    auto_issue: bool = False
    identity_dir: str = ""     # persist key/cert/ca here (empty = memory only)
    # 0 = manager default (24h); server-capped at 7d.  The daemon's
    # piece-plane contexts auto-renew in place at half validity
    # (security.ca.IdentityRenewer); gRPC credentials are immutable once
    # built — clusters running mTLS gRPC restart services within the TTL.
    cert_ttl_hours: int = 0
    # Daemon-side: dial the scheduler's gRPC port with TLS when this
    # daemon holds an issued identity.  True assumes a uniformly mTLS'd
    # cluster (the scheduler auto-issued too); set False for mixed
    # deployments where the scheduler's gRPC port is still plaintext.
    scheduler_grpc_tls: bool = True


@dataclass
class SchedulerConfigFile:
    server: ServerConfig = field(default_factory=ServerConfig)
    scheduling: SchedulingSection = field(default_factory=SchedulingSection)
    network_topology: NetworkTopologySection = field(default_factory=NetworkTopologySection)
    storage: StorageConfig = field(default_factory=StorageConfig)
    trainer: TrainerLinkSection = field(default_factory=TrainerLinkSection)
    gc: GCSection = field(default_factory=GCSection)
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    tracing: TracingSection = field(default_factory=TracingSection)
    telemetry: TelemetrySection = field(default_factory=TelemetrySection)
    log: LogConfig = field(default_factory=LogConfig)
    manager_addr: str = ""
    # Bearer credential (PAT or session token) for the manager's RBAC'd
    # job-poll and registration routes; empty on open managers.
    manager_token: str = ""
    security: SecuritySection = field(default_factory=SecuritySection)
    cluster_id: str = "default"
    # How often to poll the manager for cluster-scoped scheduling config
    # (dynconfig.go refresh interval; the reference defaults to 10s for
    # schedulers).
    dynconfig_refresh_s: float = 10.0
    # Cross-replica probe-graph sync cadence (push own edges, pull the
    # other schedulers' via the manager — the Redis-sharing analog).
    topology_sync_interval_s: float = 30.0

    def validate(self) -> None:
        self.server.validate()
        self.scheduling.validate()
        self.log.validate()
        self.tracing.validate()
        self.telemetry.validate()


@dataclass
class TrainingSection:
    epochs: int = 30
    learning_rate: float = 3e-3
    warmup_steps: int = 20
    batch_size: int = 4096
    checkpoint_dir: str = ""

    def validate(self) -> None:
        if self.learning_rate <= 0:
            raise ConfigError("training.learning_rate must be > 0")
        if self.epochs < 1:
            raise ConfigError("training.epochs must be >= 1")


@dataclass
class LifecycleSection:
    """Self-driving lifecycle plane (lifecycle/daemon.py, DESIGN.md §29):
    continuous train → export → rollout cadence and the global-vs-regional
    CANARY arbitration knobs."""

    enable: bool = False
    model_name: str = "parent-bandwidth-mlp"
    # Comma-free region list: one regional arm (``model_name@region``)
    # is trained next to the global arm per entry.
    regions: tuple = ()
    epoch_records: int = 1024          # records per key between epochs
    max_steps_per_epoch: int = 50
    min_joined: int = 50               # arbitration evidence floor
    arbitration_margin: float = 0.02   # regional must beat global by this
    canary_percent: int = 10
    interval_s: float = 30.0           # daemon loop cadence
    trainer_batch_size: int = 256

    def validate(self) -> None:
        # YAML hands lists in; the daemon wants a hashable tuple.
        self.regions = tuple(self.regions or ())
        if self.epoch_records < 1:
            raise ConfigError("lifecycle.epoch_records must be >= 1")
        if self.max_steps_per_epoch < 1:
            raise ConfigError("lifecycle.max_steps_per_epoch must be >= 1")
        if not (0 <= self.canary_percent <= 100):
            raise ConfigError("lifecycle.canary_percent must be in [0, 100]")
        if self.arbitration_margin < 0:
            raise ConfigError("lifecycle.arbitration_margin must be >= 0")
        if self.interval_s <= 0:
            raise ConfigError("lifecycle.interval_s must be > 0")


@dataclass
class TrainerConfigFile:
    server: ServerConfig = field(default_factory=lambda: ServerConfig(port=9090))
    training: TrainingSection = field(default_factory=TrainingSection)
    lifecycle: LifecycleSection = field(default_factory=LifecycleSection)
    data_dir: str = "/var/lib/dragonfly/trainer"
    manager_addr: str = ""
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    tracing: TracingSection = field(default_factory=TracingSection)
    telemetry: TelemetrySection = field(default_factory=TelemetrySection)
    log: LogConfig = field(default_factory=LogConfig)

    def validate(self) -> None:
        self.server.validate()
        self.training.validate()
        self.lifecycle.validate()
        self.log.validate()
        self.tracing.validate()
        self.telemetry.validate()


@dataclass
class ModelRegistrySection:
    blob_dir: str = "/var/lib/dragonfly/models"


@dataclass
class RolloutSection:
    """Rollout-controller guardrails (rollout/controller.py
    RolloutGuardrails; DESIGN.md §15 documents each threshold)."""

    min_shadow_samples: int = 200
    min_canary_samples: int = 200
    max_regret_ratio: float = 1.10
    regret_slack: float = 0.02
    max_inversion_ratio: float = 1.10
    max_psi: float = 0.25
    canary_percent: int = 10

    def validate(self) -> None:
        if not (0 <= self.canary_percent <= 100):
            raise ConfigError("rollout.canary_percent must be in [0, 100]")
        if self.max_regret_ratio < 1.0 or self.max_inversion_ratio < 1.0:
            raise ConfigError("rollout ratio guardrails must be >= 1.0")
        if self.min_shadow_samples < 1 or self.min_canary_samples < 1:
            raise ConfigError("rollout sample floors must be >= 1")


# Placeholder default for HASection.lease_secret (kept in sync with the
# ReplicatedStateBackend constructor default in manager/replication.py).
# It is PUBLIC CODE: validate() refuses to enable HA with it in place —
# anyone holding it can forge leases (fence a live leader via a fake
# high term, keep a dead one looking alive) and fetch the replication
# log/snapshot, credential rows included.
DEFAULT_LEASE_SECRET = "dragonfly-manager-lease"


@dataclass
class HASection:
    """Manager control-plane replication (manager/replication.py,
    DESIGN.md §20).  ``enable`` turns on log-shipping + the
    /api/v1/replication:* surface on a leader; ``replicate_from`` boots
    this process as a hot standby tailing that leader (implies enable).
    ``lease_secret`` must match across the pair — it signs the leader
    lease followers defer to and authenticates log/snapshot fetches.
    ``peers`` lists the other replicas' base URLs: a node booting as
    leader probes them for a higher term first, so a restarted fenced
    leader rejoins as a standby instead of resurrecting a stale term."""

    enable: bool = False
    replicate_from: str = ""
    node_id: str = ""
    lease_ttl_s: float = 10.0
    lease_secret: str = DEFAULT_LEASE_SECRET
    poll_interval_s: float = 1.0
    peers: list = field(default_factory=list)

    def validate(self) -> None:
        if self.lease_ttl_s <= 0:
            raise ConfigError("ha.lease_ttl_s must be > 0")
        if self.poll_interval_s <= 0:
            raise ConfigError("ha.poll_interval_s must be > 0")
        if self.enable or self.replicate_from:
            if self.lease_secret == DEFAULT_LEASE_SECRET:
                raise ConfigError(
                    "ha.lease_secret must be set to a private value when "
                    "HA is enabled — the default is public code, so any "
                    "peer could forge leases and fetch the replicated "
                    "state (users/PATs rows included)"
                )
            if len(self.lease_secret.encode()) < 16:
                raise ConfigError("ha.lease_secret must be >= 16 bytes")


@dataclass
class ManagerConfig:
    server: ServerConfig = field(default_factory=lambda: ServerConfig(port=65003))
    registry: ModelRegistrySection = field(default_factory=ModelRegistrySection)
    rollout: RolloutSection = field(default_factory=RolloutSection)
    keepalive_ttl_s: float = 60.0
    # RBAC (manager users + PATs): token_secret (>=16 bytes) turns auth
    # on; users_db persists accounts; root_password seeds the first admin.
    token_secret: str = ""
    users_db: str = ""
    root_password: str = ""
    # OAuth2 providers (manager/models/oauth.go rows):
    # [{name, client_id, client_secret, auth_url, token_url, profile_url}]
    oauth_providers: list = field(default_factory=list)
    # Object-storage backend the bucket routes manage (handlers/bucket.go
    # proxies to the configured backend): {"kind": "fs"|"s3"|"oss", ...}
    # — empty disables the bucket surface.
    objectstorage: dict = field(default_factory=dict)
    # Cluster CA directory (pkg/issuer analog): non-empty turns on the
    # certificate-issuance surface (POST /api/v1/certs:issue + gRPC twin)
    # with a persistent CA under this path; peers self-provision mTLS
    # identities at boot (security/ca.py request_from_manager).
    ca_dir: str = ""
    # Floor for the job broker's wire-supplied visibility window: a
    # worker's poll may request faster redelivery of popped-but-
    # unreported jobs, but never below this — an impatient worker must
    # not duplicate every in-flight job on its queue.  Operators shrink
    # it for recovery drills/tests.
    jobs_min_requeue_s: float = 30.0
    ha: HASection = field(default_factory=HASection)
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    tracing: TracingSection = field(default_factory=TracingSection)
    telemetry: TelemetrySection = field(default_factory=TelemetrySection)
    log: LogConfig = field(default_factory=LogConfig)

    def validate(self) -> None:
        self.server.validate()
        self.log.validate()
        self.tracing.validate()
        self.telemetry.validate()
        self.rollout.validate()
        self.ha.validate()
        if self.token_secret and len(self.token_secret.encode()) < 16:
            raise ConfigError("token_secret must be >= 16 bytes")
        for p in self.oauth_providers:
            if not isinstance(p, dict) or "name" not in p:
                raise ConfigError(f"oauth provider needs a name: {p!r}")


@dataclass
class DaemonStorageSection:
    dir: str = "/var/lib/dragonfly/daemon"
    quota_bytes: int = 10 << 30


@dataclass
class ProxySection:
    enable: bool = False
    port: int = 65001
    # SNI hijack (client/daemon/proxy hijackHTTPS): TLS listener that
    # terminates matched SNI hosts with CA-minted leaf certs and serves
    # them from P2P; unmatched hosts relay untouched.
    sni_enable: bool = False
    sni_port: int = 65443
    sni_hijack_hosts: list = field(default_factory=list)  # regexes


@dataclass
class DaemonConfig:
    server: ServerConfig = field(default_factory=lambda: ServerConfig(port=65000))
    storage: DaemonStorageSection = field(default_factory=DaemonStorageSection)
    proxy: ProxySection = field(default_factory=ProxySection)
    # Control-API bind (dfget --daemon wire, /download, /obtain_seeds).
    # Loopback by default — /download writes local files; bind a routable
    # host only inside trusted pods/compose networks (the container e2e
    # drives daemons through it).
    control_host: str = "127.0.0.1"
    control_port: int = 0
    # AF_VSOCK control listener for VM guests (pkg/rpc/vsock.go analog);
    # -1 = disabled, 0 = OS-assigned.
    control_vsock_port: int = -1
    scheduler_addr: str = ""
    # Declared tenant identity (DESIGN.md §26): stamped on registers and
    # announces so the scheduler's per-tenant accounting, the upload
    # caps, and the weighted-fair lanes key on it.  Authenticated
    # deployments derive it from the manager credential instead
    # (qos.derive_tenant); "" rides as the default tenant.
    tenant: str = ""
    # Manager address for service-identity bootstrap (daemons otherwise
    # only talk to the scheduler); required when security.auto_issue is on.
    manager_addr: str = ""
    manager_token: str = ""
    security: SecuritySection = field(default_factory=SecuritySection)
    piece_size: int = 4 << 20
    concurrent_upload_limit: int = 50
    # Concurrent back-to-source range groups (peerhost.go ConcurrentOption
    # GoroutineCount); 1 = sequential origin fetch.
    concurrent_source_groups: int = 1
    # Pass-through read plane (DESIGN.md §25): commit-tee buffer depth
    # in pieces per stream consumer; 0 = disable the tee (proxy/gateway
    # streams read every piece back off disk).
    stream_tee_depth: int = 8
    # In-engine piece fetch loop (DESIGN.md §28): the conductor drains a
    # piece window through native pf_* workers when the whole fallback
    # matrix allows (native storage, plain-HTTP transport, no stream
    # consumers, no piece-plane faults).  Off → always the Python arm.
    native_fetch: bool = True
    # Cloud back-to-source credentials by scheme (peerhost.go source
    # plugins): {"s3": {...}, "oss": {...}, "hdfs": {...}, "oras": {...}}
    # — see dragonfly2_tpu.source.configure_sources.
    source: dict = field(default_factory=dict)
    total_rate_limit: float = 1e9
    probe_interval_s: float = 20 * 60.0
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    tracing: TracingSection = field(default_factory=TracingSection)
    telemetry: TelemetrySection = field(default_factory=TelemetrySection)
    log: LogConfig = field(default_factory=LogConfig)

    def validate(self) -> None:
        self.server.validate()
        self.log.validate()
        self.tracing.validate()
        self.telemetry.validate()
        if self.piece_size < 4096:
            raise ConfigError(f"piece_size {self.piece_size} too small")
        if self.stream_tee_depth < 0:
            raise ConfigError(
                f"stream_tee_depth {self.stream_tee_depth} must be >= 0"
            )


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def _from_dict(cls: Type[T], data: dict) -> T:
    kwargs = {}
    hints = {f.name: f.type for f in dataclasses.fields(cls)}
    import typing

    resolved = typing.get_type_hints(cls)
    for name, value in (data or {}).items():
        if name not in hints:
            raise ConfigError(f"{cls.__name__}: unknown key {name!r}")
        ftype = resolved[name]
        if dataclasses.is_dataclass(ftype) and isinstance(value, dict):
            kwargs[name] = _from_dict(ftype, value)
        else:
            kwargs[name] = value
    return cls(**kwargs)


def _apply_env(obj: Any, prefix: str) -> None:
    for f in dataclasses.fields(obj):
        value = getattr(obj, f.name)
        key = f"{prefix}_{f.name}".upper()
        if dataclasses.is_dataclass(value):
            _apply_env(value, key)
            continue
        raw = os.environ.get(key)
        if raw is None:
            continue
        if isinstance(value, bool):
            setattr(obj, f.name, raw.lower() in ("1", "true", "yes", "on"))
        elif isinstance(value, int):
            setattr(obj, f.name, int(raw))
        elif isinstance(value, float):
            setattr(obj, f.name, float(raw))
        else:
            setattr(obj, f.name, raw)


def load_config(cls: Type[T], path: Optional[str] = None, *, env: bool = True) -> T:
    """YAML file (optional) → dataclass; env overrides; validate()."""
    data: dict = {}
    if path:
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f) or {}
    cfg = _from_dict(cls, data)
    if env:
        _apply_env(cfg, f"{ENV_PREFIX}_{cls.__name__.replace('ConfigFile', '').replace('Config', '')}")
    if hasattr(cfg, "validate"):
        cfg.validate()
    return cfg
