"""Lifecycle-plane metrics (DF017 REQUIRED_METRICS).

The zero-human loop's scrape surface: epoch cadence, promotion/rollback
outcomes, and the records-in → candidate-registered epoch latency.  The
``name`` label is the registry model name (``base`` or ``base@region``) —
bounded by configuration, never a per-entity identifier.
"""

from __future__ import annotations

from ..utils.metrics import default_registry as _reg

LIFECYCLE_EPOCHS_TOTAL = _reg.counter(
    "lifecycle_epochs_total",
    "training epochs cut by the lifecycle daemon (exported + registered)",
    ["name"],
)

LIFECYCLE_PROMOTIONS_TOTAL = _reg.counter(
    "lifecycle_promotions_total",
    "candidates the zero-human loop promoted to ACTIVE",
    ["name"],
)

LIFECYCLE_ROLLBACKS_TOTAL = _reg.counter(
    "lifecycle_rollbacks_total",
    "candidates auto-rolled back or retired by the guardrails/arbitration",
    ["name"],
)

LIFECYCLE_DROPPED_RECORDS_TOTAL = _reg.counter(
    "lifecycle_dropped_records_total",
    "records dropped at the trainer-queue boundary (never trained on, "
    "never counted toward the epoch cadence)",
    ["name"],
)

LIFECYCLE_EPOCH_SECONDS = _reg.sketch(
    "lifecycle_epoch_seconds",
    "one epoch's train → export → register → rollout-begin latency",
)
