"""Self-driving model lifecycle plane (DESIGN.md §29).

The LifecycleDaemon closes the loop the reference left as TODOs
(trainGNN/trainMLP): it streams live download records into per-key
``StreamingTrainer`` instances, cuts training epochs at a configurable
record cadence, exports each epoch's scorer blob WITH the stamped
``train_bin_edges``/``train_bin_fracs`` drift baseline, registers it as a
CANDIDATE through the HA-failover-aware registry client, enters it into
the guardrailed rollout plane (``rollout_client.begin``), and then pumps
replay evaluations so the existing ``RolloutController`` walks it
SHADOW → CANARY → ACTIVE with zero human steps — injected regressions
roll back on the controller's guardrails exactly like operator-driven
rollouts.

Per-region specialization: every configured region trains its own arm
(registry key ``name@region``) alongside the fleet-wide global arm;
before ANY candidate may enter CANARY the pure arbiter
(lifecycle/arbiter.py, a declared DF018 replay root) compares
global-vs-regional regret@k — losers are retired, winners' reports are
forwarded to the controller.

Durability: epoch watermarks, candidate lineage and promotion history
persist in the DF014-checked ``lifecycle`` StateBackend namespace
(lifecycle/state.py) — on the replicated backend a manager bounce
mid-promotion RESUMES (the controller's ``_reconcile`` repairs rollout
rows, the store hands the daemon its watermarks and in-flight candidate
back) instead of restarting the loop.  Without a backend the store runs
in-memory: the cadence contract (epoch every ``epoch_records`` NEW
records) still holds for the life of the process — that is the trainer
CLI wiring, which has no StateBackend of its own.

Every decision is computed in lifecycle/arbiter.py pure functions; the
daemon only samples the world (record counters, replay logs) and carries
the verdicts out.  The ``lifecycle.register``/``lifecycle.report`` fault
seams (DF004) let the chaos drills cut the train→serve plane at its two
network edges.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import faultinject
from ..utils.tracing import default_tracer
from . import metrics
from .arbiter import GLOBAL_KEY, arbitrate_candidates, plan_epoch, regional_model_name
from .state import LifecycleStore

logger = logging.getLogger(__name__)


@dataclass
class LifecycleConfig:
    scheduler_id: str = "scheduler-local"
    model_name: str = "parent-bandwidth-mlp"
    # Regional arms trained alongside the global one; each serves
    # ``model_name@region`` to that region's schedulers.
    regions: Tuple[str, ...] = ()
    # Cadence: cut an epoch every ``epoch_records`` new records per key.
    epoch_records: int = 1024
    max_steps_per_epoch: int = 50
    min_joined: int = 50              # arbitration evidence floor
    arbitration_margin: float = 0.02  # regional must beat global by this
    canary_percent: int = 10
    regret_k: int = 4
    interval_s: float = 30.0          # serve-loop cadence
    trainer_batch_size: int = 256
    trainer_snapshot_rows: int = 2048
    model_type: str = "mlp"


# replay_source(key) -> None | (shadow_rows, download_rows[, psi_max]):
# the daemon's read side of the DFC1 shadow/replay plane.  Deployments
# plug the scheduler's shadow logs + record store; sim plugs synthetic
# generators.
ReplaySource = Callable[[str], Optional[tuple]]


class LifecycleDaemon:
    def __init__(
        self,
        registry,
        rollout_client,
        *,
        config: Optional[LifecycleConfig] = None,
        backend=None,
        trainer_factory: Optional[Callable[[str], object]] = None,
        replay_source: Optional[ReplaySource] = None,
        export_transform: Optional[Callable] = None,
    ) -> None:
        self.registry = registry
        self.client = rollout_client
        self.config = config or LifecycleConfig()
        # backend=None → in-memory rows: watermarks/lineage still advance
        # (the cadence contract needs them) but die with the process.
        self.store = LifecycleStore(backend)
        self.replay_source = replay_source
        # Chaos/drill hook: transforms the exported scorer before it is
        # registered (sim/lifecycle.py injects an inverted head through
        # it).  Production wiring leaves it None.
        self.export_transform = export_transform
        self._keys: Tuple[str, ...] = (GLOBAL_KEY,) + tuple(self.config.regions)
        factory = trainer_factory or self._default_trainer
        self._trainers = {key: factory(key) for key in self._keys}
        self._mu = threading.Lock()
        self._records: Dict[str, int] = {}
        self._dropped: Dict[str, int] = {}
        for key in self._keys:
            # Un-flushed feeds die with the process; cadence restarts
            # from the persisted watermark.
            self._records[key] = int(self.store.row(key).get("watermark", 0))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _default_trainer(self, key: str):
        from ..trainer.streaming import StreamingConfig, StreamingTrainer

        return StreamingTrainer(
            StreamingConfig(
                batch_size=self.config.trainer_batch_size,
                snapshot_rows=self.config.trainer_snapshot_rows,
            )
        )

    # -- identity -------------------------------------------------------------

    def keys(self) -> Tuple[str, ...]:
        return self._keys

    def model_name_for(self, key: str) -> str:
        return regional_model_name(self.config.model_name, key)

    # -- ingest ---------------------------------------------------------------

    def feed(self, rows: np.ndarray, *, region: Optional[str] = None) -> None:
        """Offer live download records: every batch trains the global arm;
        region-attributed batches ALSO train that region's arm."""
        n = int(np.asarray(rows).shape[0])
        if n == 0:
            return
        targets = [GLOBAL_KEY]
        if region and region in self._trainers:
            targets.append(region)
        for key in targets:
            if self._trainers[key].feed(rows, block=False):
                with self._mu:
                    self._records[key] = self._records.get(key, 0) + n
            else:
                # Queue full: the rows never reached the trainer, so
                # they must not advance the epoch cadence either — an
                # epoch cut on phantom records would train on an empty
                # queue and export unchanged weights.
                with self._mu:
                    self._dropped[key] = dropped = self._dropped.get(key, 0) + n
                metrics.LIFECYCLE_DROPPED_RECORDS_TOTAL.inc(
                    n, name=self.model_name_for(key)
                )
                logger.warning(
                    "lifecycle %s: trainer queue full, dropped %d rows "
                    "(%d total)", key, n, dropped,
                )

    def records_seen(self, key: str) -> int:
        with self._mu:
            return self._records.get(key, 0)

    def records_dropped(self, key: str) -> int:
        with self._mu:
            return self._dropped.get(key, 0)

    # online_sink surface (trainer/service.py): the lifecycle ingest
    # rides the same wire adapter as the online graph trainer, so every
    # chunk landing on the trainer's ingest servers also streams here.
    def feed_download_rows(self, rows: np.ndarray) -> None:
        self.feed(rows)

    def feed_topology_rows(self, rows: np.ndarray) -> None:
        """Topology rows don't train the MLP lifecycle (the GNN arm
        consumes them in a later round)."""

    # -- training epochs ------------------------------------------------------

    def _candidate_in_flight(self, key: str) -> bool:
        model = self.registry.candidate_model(
            self.config.scheduler_id, self.model_name_for(key)
        )
        return model is not None

    def maybe_epoch(self, key: str) -> Optional[dict]:
        """Cut one training epoch for ``key`` if the cadence decision
        (arbiter.plan_epoch, a replay root) says so."""
        row = self.store.row(key)
        try:
            in_flight = self._candidate_in_flight(key)
        except Exception as exc:  # noqa: BLE001 — manager outage: retry next cycle
            logger.warning("lifecycle %s: candidate poll failed: %s", key, exc)
            return None
        plan = plan_epoch(
            records_seen=self.records_seen(key),
            watermark=int(row.get("watermark", 0)),
            epoch_records=self.config.epoch_records,
            candidate_in_flight=in_flight,
        )
        if not plan["train"]:
            return None
        return self.run_epoch(key, watermark=int(plan["watermark"]))

    def run_epoch(self, key: str, *, watermark: int) -> Optional[dict]:
        """train → export(+drift baseline) → register CANDIDATE → begin
        rollout, as one traced epoch."""
        from ..trainer.export import scorer_to_bytes

        cfg = self.config
        name = self.model_name_for(key)
        epoch = int(self.store.row(key).get("epoch", 0)) + 1
        t0 = time.monotonic()
        with default_tracer.span(
            "lifecycle/epoch",
            key=key, model_name=name, epoch=epoch, watermark=watermark,
        ):
            trainer = self._trainers[key]
            # trainer.step is cumulative across epochs — only THIS
            # call's step count says whether the epoch trained anything.
            steps = trainer.run(max_steps=cfg.max_steps_per_epoch, idle_timeout=0.01)
            if steps == 0:
                # Not enough queued rows for one full batch yet: leave
                # the watermark so the cadence re-fires once they land,
                # instead of exporting unchanged weights.
                logger.info("lifecycle %s: no full batch yet; epoch deferred", key)
                return None
            scorer = trainer.export_scorer()
            if self.export_transform is not None:
                scorer = self.export_transform(scorer, key, epoch)
            try:
                faultinject.fire("lifecycle.register")
                model = self.registry.create_model(
                    name=name,
                    type=cfg.model_type,
                    scheduler_id=cfg.scheduler_id,
                    artifact=scorer_to_bytes(scorer),
                    evaluation={"records_seen": float(trainer.records_seen)},
                )
                self.client.begin(model.id, canary_percent=cfg.canary_percent)
            except Exception as exc:  # noqa: BLE001 — retry on the next cycle
                logger.warning("lifecycle %s: register/begin failed: %s", key, exc)
                return None
        self.store.update(
            key,
            epoch=epoch,
            watermark=watermark,
            candidate_id=model.id,
            candidate_version=model.version,
        )
        self.store.append_history(
            key,
            {"epoch": epoch, "event": "registered",
             "model_id": model.id, "version": model.version},
        )
        metrics.LIFECYCLE_EPOCHS_TOTAL.inc(name=name)
        metrics.LIFECYCLE_EPOCH_SECONDS.observe(time.monotonic() - t0)
        logger.info(
            "lifecycle %s: epoch %d registered %s v%d → shadow",
            key, epoch, model.id, model.version,
        )
        return {"key": key, "epoch": epoch, "model_id": model.id,
                "version": model.version}

    # -- rollout pump ---------------------------------------------------------

    def _resolve_candidate(self, key: str, row: dict) -> None:
        """The in-flight candidate disappeared from the registry: record
        how it resolved (promoted by the controller, or rolled back) so
        lineage survives a manager bounce the daemon never witnessed."""
        if not row.get("candidate_id"):
            return
        try:
            active = self.registry.active_model(
                self.config.scheduler_id, self.model_name_for(key)
            )
        except Exception as exc:  # noqa: BLE001 — resolve on a later cycle
            logger.warning("lifecycle %s: lineage resolve failed: %s", key, exc)
            return
        outcome = (
            "promoted"
            if active is not None and active.id == row["candidate_id"]
            else "rolled_back"
        )
        self.store.append_history(
            key,
            {"epoch": int(row.get("epoch", 0)), "event": outcome,
             "model_id": row["candidate_id"],
             "version": int(row.get("candidate_version", 0))},
        )
        self.store.update(key, candidate_id="", candidate_version=0)

    def pump_rollouts(self) -> List[dict]:
        """One evaluate → arbitrate → report sweep over every key with a
        candidate in flight.  SHADOW candidates pass the regret@k
        arbitration gate before their reports reach the controller
        (i.e. before they may enter CANARY); CANARY/ACTIVE candidates
        report unconditionally — the guardrail watch must keep judging
        them."""
        cfg = self.config
        infos: Dict[str, object] = {}
        reports: Dict[str, dict] = {}
        for key in self._keys:
            name = self.model_name_for(key)
            row = self.store.row(key)
            try:
                info = self.client.candidate(cfg.scheduler_id, name)
            except Exception as exc:  # noqa: BLE001 — manager outage
                logger.warning("lifecycle %s: candidate poll failed: %s", key, exc)
                continue
            if info is None:
                self._resolve_candidate(key, row)
                continue
            src = self.replay_source(key) if self.replay_source else None
            if src is None:
                continue
            shadow_rows, download_rows = src[0], src[1]
            psi_max = src[2] if len(src) > 2 else None
            if not shadow_rows.shape[0]:
                continue
            from ..rollout.evaluation import evaluate_shadow

            infos[key] = info
            reports[key] = evaluate_shadow(
                shadow_rows, download_rows, k=cfg.regret_k, psi_max=psi_max
            )
        if not reports:
            return []
        shadow_reports = {
            key: rep
            for key, rep in reports.items()
            if getattr(infos[key], "phase", "") == "shadow"
        }
        with default_tracer.span(
            "lifecycle/promote",
            model_name=cfg.model_name, keys=",".join(sorted(reports)),
        ):
            verdict = arbitrate_candidates(
                shadow_reports,
                min_joined=cfg.min_joined,
                margin=cfg.arbitration_margin,
            )
            outcomes = self._apply(reports, infos, verdict)
        return outcomes

    def _apply(self, reports, infos, verdict) -> List[dict]:
        cfg = self.config
        outcomes: List[dict] = []
        to_report = [
            key
            for key in sorted(reports)
            if key in verdict["advance"]
            or getattr(infos[key], "phase", "") != "shadow"
        ]
        for key, reason in sorted(verdict["retire"].items()):
            name = self.model_name_for(key)
            model = getattr(infos[key], "model", None)
            try:
                deactivate = getattr(self.registry, "deactivate", None)
                if deactivate is not None and model is not None:
                    deactivate(model.id)
            except Exception as exc:  # noqa: BLE001 — retire on a later cycle
                logger.warning("lifecycle %s: retire failed: %s", key, exc)
                continue
            row = self.store.row(key)
            self.store.append_history(
                key,
                {"epoch": int(row.get("epoch", 0)),
                 "event": "arbitration_retired", "reason": reason,
                 "model_id": row.get("candidate_id", "")},
            )
            self.store.update(key, candidate_id="", candidate_version=0)
            metrics.LIFECYCLE_ROLLBACKS_TOTAL.inc(name=name)
            outcomes.append({"key": key, "decision": "retired", "reason": reason})
            logger.info("lifecycle %s: candidate retired by arbitration: %s",
                        key, reason)
        for key in to_report:
            name = self.model_name_for(key)
            try:
                faultinject.fire("lifecycle.report")
                decision = self.client.report(
                    cfg.scheduler_id, name, reports[key]
                )
            except KeyError:
                # Registered candidate with no rollout row yet (a crash
                # between create_model and begin): re-enter it.
                try:
                    model = getattr(infos[key], "model", None)
                    if model is not None:
                        self.client.begin(
                            model.id, canary_percent=cfg.canary_percent
                        )
                except Exception as exc:  # noqa: BLE001
                    logger.warning("lifecycle %s: re-begin failed: %s", key, exc)
                continue
            except Exception as exc:  # noqa: BLE001 — manager outage
                logger.warning("lifecycle %s: report failed: %s", key, exc)
                continue
            outcome = {"key": key, "decision": decision.get("decision"),
                       "phase": decision.get("phase"),
                       "reason": decision.get("reason", "")}
            outcomes.append(outcome)
            row = self.store.row(key)
            if decision.get("decision") in ("advance", "promote", "rollback"):
                self.store.append_history(
                    key,
                    {"epoch": int(row.get("epoch", 0)),
                     "event": decision.get("decision"),
                     "phase": decision.get("phase"),
                     "model_id": row.get("candidate_id", "")},
                )
            if decision.get("decision") in ("promote", "rollback"):
                self.store.update(key, candidate_id="", candidate_version=0)
            if decision.get("decision") == "promote":
                metrics.LIFECYCLE_PROMOTIONS_TOTAL.inc(name=name)
            elif decision.get("decision") == "rollback":
                metrics.LIFECYCLE_ROLLBACKS_TOTAL.inc(name=name)
        return outcomes

    # -- loop -----------------------------------------------------------------

    def step(self) -> dict:
        """One full lifecycle cycle: cadence-gated epochs for every key,
        then the evaluate→arbitrate→report pump."""
        epochs = []
        for key in self._keys:
            res = self.maybe_epoch(key)
            if res is not None:
                epochs.append(res)
        return {"epochs": epochs, "reports": self.pump_rollouts()}

    def serve(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self.config.interval_s):
                try:
                    self.step()
                except Exception:  # noqa: BLE001
                    logger.exception("lifecycle cycle failed")

        self._thread = threading.Thread(
            target=loop, name="lifecycle-daemon", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


def file_replay_source(
    shadow_paths: Dict[str, List[str]], download_paths: List[str]
) -> ReplaySource:
    """Deployment read side: per-key DFC1 shadow replay shards joined
    against the record store's download shards (the same loaders the
    RolloutReporter uses)."""
    from ..rollout.evaluation import load_replay_rows

    def source(key: str):
        paths = shadow_paths.get(key)
        if not paths:
            return None
        shadow_rows = load_replay_rows(paths)
        download_rows = load_replay_rows(download_paths)
        return shadow_rows, download_rows

    return source
