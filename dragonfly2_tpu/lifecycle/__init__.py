"""Self-driving model lifecycle plane (DESIGN.md §29): continuous
train → export → rollout with zero human steps."""

from .arbiter import (
    GLOBAL_KEY,
    arbitrate_candidates,
    plan_epoch,
    regional_model_name,
)
from .daemon import LifecycleConfig, LifecycleDaemon, file_replay_source
from .state import LifecycleStore

__all__ = [
    "GLOBAL_KEY",
    "LifecycleConfig",
    "LifecycleDaemon",
    "LifecycleStore",
    "arbitrate_candidates",
    "file_replay_source",
    "plan_epoch",
    "regional_model_name",
]
