"""Durable lifecycle state: epoch watermarks, candidate lineage,
promotion history (DF014 namespace ``lifecycle``).

One row per lifecycle key (``"global"`` or a region name):

    {"epoch": int, "watermark": int, "candidate_id": str,
     "candidate_version": int, "history": [event, ...]}

Rows ride the manager's StateBackend — on the replicated backend
(DESIGN.md §20) they follow the WAL to the standby, so a manager bounce
mid-promotion resumes the loop exactly where it was (the daemon reads the
watermark and in-flight candidate back instead of retraining from
scratch).  Every mutation is one ``put`` under ``_mu`` and the loader is
the constructor, per records/state_contracts.py.

``backend=None`` runs the store in-memory: rows behave identically
within the process (watermarks advance, lineage accumulates) but die
with it.  The trainer CLI wiring uses this mode — it has no
StateBackend of its own — so the epoch cadence contract holds even
without durability; only crash-resume needs the backend.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Dict, List, Optional

if TYPE_CHECKING:  # lock-graph resolver type (§16): _table nests under _mu
    from ..manager.state import StateBackend

# Bounded promotion-history tail kept per key: lineage for operators and
# drills, not an unbounded event log.
HISTORY_KEEP = 64


def _default_row() -> dict:
    return {
        "epoch": 0,
        "watermark": 0,
        "candidate_id": "",
        "candidate_version": 0,
        "history": [],
    }


class LifecycleStore:
    """Owner of the ``lifecycle`` namespace (records/state_contracts.py)."""

    def __init__(self, backend: Optional["StateBackend"] = None) -> None:
        self._mu = threading.Lock()
        self._rows: Dict[str, dict] = {}
        self._table = backend.table("lifecycle") if backend is not None else None
        if self._table is not None:
            for key, doc in self._table.load_all().items():
                row = _default_row()
                row.update(doc)
                self._rows[key] = row

    def keys(self) -> List[str]:
        with self._mu:
            return sorted(self._rows)

    def row(self, key: str) -> dict:
        with self._mu:
            row = self._rows.get(key)
            return dict(row) if row is not None else _default_row()

    def update(self, key: str, **fields) -> dict:
        with self._mu:
            row = dict(self._rows.get(key) or _default_row())
            row.update(fields)
            self._rows[key] = row
            if self._table is not None:
                self._table.put(key, row)
            return dict(row)

    def append_history(self, key: str, event: dict) -> dict:
        with self._mu:
            row = dict(self._rows.get(key) or _default_row())
            history = list(row.get("history") or [])
            history.append(dict(event))
            row["history"] = history[-HISTORY_KEEP:]
            self._rows[key] = row
            if self._table is not None:
                self._table.put(key, row)
            return dict(row)

    def candidate(self, key: str) -> Optional[str]:
        """In-flight candidate model id for this key, or None."""
        with self._mu:
            row = self._rows.get(key)
            return (row or {}).get("candidate_id") or None
