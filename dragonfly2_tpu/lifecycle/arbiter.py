"""Lifecycle decision kernel: the pure functions the daemon replays.

Every decision the self-driving lifecycle plane makes — when to cut a
training epoch, and which of a global/regional candidate set may advance
toward CANARY — is computed HERE as a pure function of its inputs, and
declared a replay root in records/determinism_contracts.py (DF018/DF019).
The daemon (lifecycle/daemon.py) samples the ambient world (record
counters, replay-log evaluations) outside these functions and passes the
values in, so the §27 dual-run divergence drill can re-run the loop that
retrains the fleet's brain over journal bytes and demand byte-identical
decisions.

Regional arbitration (DESIGN.md §29): a regional candidate
(``name@region`` registry key) competes with the global candidate for its
region's traffic.  Admission to CANARY is regret@k-gated:

- a candidate below ``min_joined`` joined samples is **held** (not
  enough evidence to judge either way);
- while a global candidate exists but is itself below the evidence
  floor, every regional candidate is **held** too — a regional may only
  advance by BEATING the global arm, never by out-accumulating joined
  samples while the global arm is still unjudged;
- an eligible regional candidate **advances** only if its regret beats
  the global candidate's by ``margin`` — ties go to global (one model
  for the whole fleet is cheaper than a specialization that buys
  nothing) — otherwise it is **retired** (deactivated, freeing the
  region's candidate slot); with no global candidate in the report set
  at all there is nothing to beat and eligible regionals advance;
- the eligible global candidate advances unless EVERY eligible regional
  candidate beat it, in which case it is retired.

Keep these functions pure: no clocks, no RNG, no ambient reads — DF018
taints everything reachable from them.
"""

from __future__ import annotations

from typing import Dict, Optional

# The pseudo-region of the fleet-wide model: its registry key is the bare
# model name, every real region's key is ``name@region``.
GLOBAL_KEY = "global"


def regional_model_name(base: str, region: Optional[str]) -> str:
    """Registry model name for a lifecycle key: the bare ``base`` for the
    global arm, ``base@region`` for a regional specialization (the
    registry keys models per (scheduler_id, name), so regional keys ride
    composed names with no registry change)."""
    if not region or region == GLOBAL_KEY:
        return base
    return f"{base}@{region}"


def plan_epoch(
    *,
    records_seen: int,
    watermark: int,
    epoch_records: int,
    candidate_in_flight: bool,
) -> Dict:
    """Cut a new training epoch?  Pure cadence arithmetic: an epoch is
    due once ``epoch_records`` new records have arrived past the last
    watermark AND the previous candidate has resolved (one candidate per
    key in flight — the registry enforces the same exclusivity)."""
    fresh = max(int(records_seen) - int(watermark), 0)
    if candidate_in_flight:
        return {
            "train": False,
            "watermark": int(watermark),
            "reason": "candidate still in flight",
        }
    if epoch_records <= 0 or fresh < epoch_records:
        return {
            "train": False,
            "watermark": int(watermark),
            "reason": f"{fresh}/{epoch_records} records since watermark",
        }
    return {
        "train": True,
        "watermark": int(records_seen),
        "reason": f"cadence reached ({fresh} records)",
    }


def arbitrate_candidates(
    reports: Dict[str, dict], *, min_joined: int = 50, margin: float = 0.02
) -> Dict:
    """Global-vs-regional CANARY admission over one base name's SHADOW
    candidates.  ``reports`` maps lifecycle key (``"global"`` or a region
    name) → rollout/evaluation.py ``evaluate_shadow`` report.  Returns
    ``{"advance": [keys], "hold": {key: reason}, "retire": {key:
    reason}}`` with deterministic (sorted) ordering."""
    hold: Dict[str, str] = {}
    retire: Dict[str, str] = {}
    eligible: Dict[str, float] = {}
    for key in sorted(reports):
        rep = reports[key] or {}
        joined = int(rep.get("joined_edges", 0))
        if joined < min_joined:
            hold[key] = f"{joined}/{min_joined} joined samples"
            continue
        regret = (rep.get("regret_at_k") or {}).get("candidate", 0.0)
        eligible[key] = float(regret)
    advance = []
    global_regret = eligible.get(GLOBAL_KEY)
    regional = [k for k in sorted(eligible) if k != GLOBAL_KEY]
    if GLOBAL_KEY in hold:
        # A global candidate exists but is below the evidence floor:
        # "no eligible global" must not read as "nothing to beat", or
        # admission would depend on which arm accumulates joined
        # samples first.  Hold the eligible regionals until the global
        # arm can be judged.
        for key in regional:
            hold[key] = (
                f"global candidate below evidence floor "
                f"({hold[GLOBAL_KEY]})"
            )
        return {"advance": [], "hold": hold, "retire": retire}
    beaten_everywhere = bool(regional)
    for key in regional:
        if global_regret is None or eligible[key] + margin < global_regret:
            advance.append(key)
        else:
            beaten_everywhere = False
            retire[key] = (
                f"regional regret {eligible[key]:.4f} does not beat global "
                f"{global_regret:.4f} by {margin}"
            )
    if global_regret is not None:
        if beaten_everywhere:
            retire[GLOBAL_KEY] = (
                "every eligible regional candidate beat the global arm by "
                f"{margin}"
            )
        else:
            advance.insert(0, GLOBAL_KEY)
    return {"advance": advance, "hold": hold, "retire": retire}
