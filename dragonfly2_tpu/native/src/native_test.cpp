// Concurrency self-test for the native engine (run under TSAN/ASAN via
// `make tsan` / `make asan` — the SURVEY §5.2 sanitizer gate).
//
// Exercises the shared-state paths that matter under threads:
//   1. concurrent piece writers on distinct tasks + readers on the same
//      task (TaskStore mutex, PieceStore map);
//   2. the in-engine HTTP server under 8 concurrent fetchers while a
//      writer keeps committing new pieces (server threads vs writer);
//   3. delete-while-reading (shared_ptr lifetime + closed flag).
//
// The library source is #included so the sanitizers see one TU.

#include "native.cpp"

#include <cassert>
#include <cstdlib>
#include <functional>

namespace {

int http_get(uint16_t port, const std::string& path, std::string& body) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  std::string req = "GET " + path + " HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n";
  send_all(fd, req.data(), req.size());
  std::string resp;
  char buf[8192];
  ssize_t n;
  while ((n = recv(fd, buf, sizeof(buf), 0)) > 0) resp.append(buf, (size_t)n);
  close(fd);
  size_t hdr = resp.find("\r\n\r\n");
  if (hdr == std::string::npos) return -2;
  body = resp.substr(hdr + 4);
  return atoi(resp.c_str() + 9);
}

std::vector<uint8_t> piece_bytes(uint32_t task, uint32_t number, size_t len) {
  std::vector<uint8_t> v(len);
  for (size_t i = 0; i < len; i++) v[i] = (uint8_t)((task * 31 + number * 7 + i) & 0xFF);
  return v;
}

// Minimal hostile/slow parent for the pf_* robustness tests: listens on
// an ephemeral loopback port, accepts ONE connection, and answers every
// received request head per `reply` after `delay_us`.
int listen_local(uint16_t* port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = 0;
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(fd, 8) != 0) {
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &alen);
  *port = ntohs(addr.sin_port);
  return fd;
}

void fake_parent(int lfd, const std::string& reply, int delay_us) {
  int cfd = accept(lfd, nullptr, nullptr);
  if (cfd < 0) return;
  std::string acc;
  char buf[8192];
  for (;;) {
    ssize_t n = recv(cfd, buf, sizeof(buf), 0);
    if (n <= 0) break;
    acc.append(buf, (size_t)n);
    size_t nreq = 0, pos = 0;
    while ((pos = acc.find("\r\n\r\n")) != std::string::npos) {
      acc.erase(0, pos + 4);
      nreq++;
    }
    if (nreq == 0) continue;
    if (delay_us > 0) usleep(delay_us);
    for (size_t i = 0; i < nreq; i++)
      if (!send_all(cfd, reply.data(), reply.size())) break;
  }
  close(cfd);
}

}  // namespace

int main() {
  char tmpl[] = "/tmp/native_test_XXXXXX";
  std::string root = mkdtemp(tmpl);
  int64_t h = ps_open(root.c_str());
  assert(h > 0);
  const uint32_t kPiece = 256 * 1024;

  // 1. Concurrent writers on distinct tasks + readers chasing them.
  {
    std::vector<std::thread> ts;
    std::atomic<int> errors{0};
    for (int t = 0; t < 4; t++) {
      ts.emplace_back([&, t] {
        std::string task = "task-" + std::to_string(t);
        if (ps_create_task(h, task.c_str(), kPiece, 8 * kPiece) != 0) {
          errors++;
          return;
        }
        for (uint32_t n = 0; n < 8; n++) {
          auto data = piece_bytes(t, n, kPiece);
          if (ps_write_piece(h, task.c_str(), n, data.data(), kPiece) < 0) errors++;
        }
      });
      ts.emplace_back([&, t] {
        std::string task = "task-" + std::to_string(t);
        std::vector<uint8_t> buf(kPiece);
        for (int spin = 0; spin < 200; spin++) {
          int64_t c = ps_piece_count(h, task.c_str());
          if (c >= 8) {
            for (uint32_t n = 0; n < 8; n++) {
              int64_t r = ps_read_piece(h, task.c_str(), n, buf.data(), kPiece, 1);
              if (r != (int64_t)kPiece) errors++;
            }
            return;
          }
          usleep(1000);
        }
      });
    }
    for (auto& t : ts) t.join();
    assert(errors.load() == 0);
  }

  // 2. HTTP server under concurrent fetchers while a writer commits.
  {
    int64_t port = ps_serve(h, "127.0.0.1", 0, 64);
    assert(port > 0);
    std::atomic<int> errors{0};
    std::thread writer([&] {
      ps_create_task(h, "live", kPiece, 16 * kPiece);
      for (uint32_t n = 0; n < 16; n++) {
        auto data = piece_bytes(99, n, kPiece);
        if (ps_write_piece(h, "live", n, data.data(), kPiece) < 0) errors++;
        usleep(2000);
      }
    });
    std::vector<std::thread> fetchers;
    for (int f = 0; f < 8; f++) {
      fetchers.emplace_back([&, f] {
        std::string body;
        for (int round = 0; round < 30; round++) {
          uint32_t n = (uint32_t)((f + round) % 8);
          std::string want_task = "task-" + std::to_string(f % 4);
          int code = http_get((uint16_t)port, "/pieces/" + want_task + "/" +
                              std::to_string(n), body);
          if (code != 200 || body.size() != kPiece) errors++;
          auto want = piece_bytes((uint32_t)(f % 4), n, kPiece);
          if (memcmp(body.data(), want.data(), kPiece) != 0) errors++;
          // bitmap + range while the live task is still being written
          http_get((uint16_t)port, "/tasks/live/pieces", body);
        }
      });
    }
    writer.join();
    for (auto& t : fetchers) t.join();
    std::string body;
    assert(http_get((uint16_t)port, "/tasks/task-0/pieces", body) == 200);
    assert(body.size() == 8);
    assert(http_get((uint16_t)port, "/pieces/ghost/0", body) == 404);
    assert(errors.load() == 0);
    assert(ps_serve_stop(h) == 0);
  }

  // 3. delete-while-reading.
  {
    std::thread reader([&] {
      std::vector<uint8_t> buf(kPiece);
      for (int i = 0; i < 200; i++)
        ps_read_piece(h, "task-1", (uint32_t)(i % 8), buf.data(), kPiece, 1);
    });
    usleep(1000);
    ps_delete_task(h, "task-1");
    reader.join();
  }

  assert(ps_close(h) == 0);

  // 4. Online ingest engine: concurrent feeders (mapping + eviction under
  //    the engine mutex) vs a taker draining dispatch blocks, with a
  //    topology mapper and a stats poller in the mix; then
  //    destroy-while-blocked (feeder waiting on a full ring must wake).
  {
    const int32_t kNodes = 64, kFeat = 12, kWidth = 2 + 2 * kFeat + 1;
    int64_t oh = oi_create(kNodes, 1 << 16, kFeat, kWidth, 5.0, 4096);
    assert(oh > 0);
    std::atomic<int> errors{0};
    std::atomic<bool> stop{false};
    std::vector<std::thread> feeders;
    for (int f = 0; f < 2; f++) {
      feeders.emplace_back([&, f] {
        std::vector<float> rows((size_t)256 * kWidth, 0.5f);
        for (int round = 0; round < 120; round++) {
          for (int i = 0; i < 256; i++) {
            // Churn through 3x capacity so eviction paths run.
            rows[(size_t)i * kWidth] =
                (float)((f * 7919 + round * 131 + i) % (3 * kNodes) + 100);
            rows[(size_t)i * kWidth + 1] =
                (float)((f * 104729 + round * 37 + i) % (3 * kNodes) + 100);
          }
          int64_t kept = oi_feed_download_rows(oh, rows.data(), 256,
                                               (double)round, 1);
          if (kept < 0) errors++;
        }
      });
    }
    std::thread taker([&] {
      std::vector<int32_t> src(512), dst(512);
      std::vector<float> y(512);
      while (!stop.load()) {
        oi_take_edges(oh, 512, src.data(), dst.data(), y.data(), 20);
      }
    });
    std::thread mapper([&] {
      std::vector<float> b(64);
      std::vector<int32_t> out(64);
      for (int round = 0; round < 200; round++) {
        for (int i = 0; i < 64; i++) b[i] = (float)(100 + (round + i) % 192);
        if (oi_map_buckets(oh, b.data(), 64, (double)(round % 120), out.data()) != 0)
          errors++;
        int64_t ov, ev, ni, ri;
        if (oi_stats(oh, &ov, &ev, &ni, &ri) != 0) errors++;
        std::vector<int32_t> rec(kNodes);
        oi_take_recycled(oh, rec.data(), kNodes);
      }
    });
    for (auto& t : feeders) t.join();
    mapper.join();
    stop.store(true);
    taker.join();
    assert(errors.load() == 0);
    // Consistent-export contract: drained pending → export succeeds.
    {
      std::vector<int32_t> rec(kNodes);
      while (oi_take_recycled(oh, rec.data(), kNodes) > 0) {}
      std::vector<int32_t> idt(1 << 16);
      std::vector<int64_t> bof(kNodes);
      std::vector<double> ls(kNodes);
      std::vector<int32_t> fr(kNodes);
      std::vector<float> fs((size_t)kNodes * kFeat), fc(kNodes);
      int64_t scalars[3];
      int64_t n = oi_export_state(oh, idt.data(), bof.data(), ls.data(),
                                  fr.data(), kNodes, fs.data(), fc.data(),
                                  scalars);
      assert(n >= 0);
      assert(oi_import_state(oh, idt.data(), bof.data(), ls.data(), fr.data(),
                             n, fs.data(), fc.data(), scalars[0], scalars[1],
                             scalars[2]) == 0);
    }
    // Destroy-while-blocked: fill the ring, park a feeder on cv_space,
    // then destroy — the feeder must wake with -1, not deadlock.
    std::thread blocked([&] {
      std::vector<float> rows((size_t)8192 * kWidth, 0.5f);
      for (int i = 0; i < 8192; i++) {
        rows[(size_t)i * kWidth] = (float)(100 + i % kNodes);
        rows[(size_t)i * kWidth + 1] = (float)(100 + (i + 1) % kNodes);
      }
      while (oi_feed_download_rows(oh, rows.data(), 8192, 1000.0, 1) >= 0) {}
    });
    usleep(50000);
    assert(oi_destroy(oh) == 0);
    blocked.join();
  }

  // 5. In-engine client fetch loop (pf_*, DESIGN.md §28) against the
  //    in-engine server: pipelined bursts must trigger the server's
  //    batched submission, commits must be byte-exact in the client
  //    store, error completions must carry the right status, and the
  //    process-wide leak counters must stay zero.
  {
    char src_tmpl[] = "/tmp/native_test_src_XXXXXX";
    char dst_tmpl[] = "/tmp/native_test_dst_XXXXXX";
    int64_t src = ps_open(mkdtemp(src_tmpl));
    int64_t dst = ps_open(mkdtemp(dst_tmpl));
    assert(src > 0 && dst > 0);
    const uint32_t kSmall = 16 * 1024;
    const uint32_t kN = 32;
    assert(ps_create_task(src, "pf-task", kSmall, kN * kSmall) == 0);
    for (uint32_t n = 0; n < kN; n++) {
      auto data = piece_bytes(5, n, kSmall);
      assert(ps_write_piece(src, "pf-task", n, data.data(), kSmall) ==
             (int64_t)kSmall);
    }
    int64_t port = ps_serve(src, "127.0.0.1", 0, 64);
    assert(port > 0);
    assert(ps_create_task(dst, "pf-task", kSmall, kN * kSmall) == 0);

    // One worker keeps the burst assembly deterministic: 32 queued
    // 16 KiB jobs form 8-deep bursts under the 512 KiB byte cap, and
    // each burst lands at the server as back-to-back GETs -> writev.
    int64_t fh = pf_open(dst, 1, "tenant-test");
    assert(fh > 0);
    assert(pf_parent(fh, 0, "127.0.0.1", (uint16_t)port) == 0);
    assert(pf_parent(fh, 1, "127.0.0.1", 1) == 0);  // dead parent slot
    for (uint32_t n = 0; n < kN; n++)
      assert(pf_submit(fh, "pf-task", 0, n, kSmall) == 0);
    assert(pf_submit(fh, "ghost", 0, 0, 0) == 0);          // server 404
    assert(pf_submit(fh, "pf-task", 0, 3, kSmall - 1) == 0);  // len mismatch
    assert(pf_submit(fh, "pf-task", 1, 0, kSmall) == 0);   // conn refused
    int ok = 0, st404 = 0, stlen = 0, stconn = 0, drained = 0;
    FetchDone recs[64];
    for (int spin = 0; spin < 200 && drained < (int)kN + 3; spin++) {
      int n = pf_complete(fh, (uint8_t*)recs, 64, 100);
      assert(n >= 0);
      for (int i = 0; i < n; i++) {
        drained++;
        if (recs[i].status == 0) {
          assert(recs[i].length == kSmall && recs[i].slot == 0);
          assert(recs[i].cost_ns > 0);
          ok++;
        } else if (recs[i].status == 404) {
          st404++;
        } else if (recs[i].status == -2) {
          stlen++;
        } else if (recs[i].status == -1) {
          assert(recs[i].slot == 1);
          stconn++;
        }
      }
    }
    assert(drained == (int)kN + 3);
    assert(ok == (int)kN && st404 == 1 && stlen == 1 && stconn == 1);
    assert(pf_pending(fh) == 0);
    std::vector<uint8_t> buf(kSmall);
    for (uint32_t n = 0; n < kN; n++) {
      assert(ps_read_piece(dst, "pf-task", n, buf.data(), kSmall, 1) ==
             (int64_t)kSmall);
      auto want = piece_bytes(5, n, kSmall);
      assert(memcmp(buf.data(), want.data(), kSmall) == 0);
    }
    int64_t pieces = 0, bytes = 0, batched = 0, conns = 0;
    assert(ps_serve_stats2(src, &pieces, &bytes, &batched, &conns) == 0);
    assert(pieces >= (int64_t)kN);
    assert(batched > 0);  // the §28 coalesced-writev evidence
    assert(pf_close(fh) == 0);
    assert(pf_submit(fh, "pf-task", 0, 0, kSmall) == -1);  // handle gone
    assert(ps_serve_stop(src) == 0);
    assert(ps_close(src) == 0);
    assert(ps_close(dst) == 0);
    int64_t leaked_servers = 0, leaked_conns = 0;
    assert(ps_leak_stats(&leaked_servers, &leaked_conns) == 0);
    assert(leaked_servers == 0 && leaked_conns == 0);
  }

  // 6. pf_* robustness against hostile/wedged parents (REVIEW fixes):
  //    a) an absurd Content-Length is a -2 completion, not a bad_alloc
  //       that std::terminates the daemon;
  //    b) pf_close DISCARDS the queued backlog (only in-flight bursts
  //       finish) and safely wakes a concurrently blocked pf_complete;
  //    c) a foreign client pipelining piece GETs past the server's
  //       512 KiB batch byte cap still gets byte-exact bodies, and the
  //       batched counter never covers the over-cap tail.
  {
    char dst_tmpl[] = "/tmp/native_test_rb_XXXXXX";
    int64_t dst = ps_open(mkdtemp(dst_tmpl));
    assert(dst > 0);
    const uint32_t kSmall = 16 * 1024;
    assert(ps_create_task(dst, "rb-task", kSmall, 64 * kSmall) == 0);

    // a) hostile Content-Length.
    {
      uint16_t port = 0;
      int lfd = listen_local(&port);
      assert(lfd >= 0);
      std::thread parent(fake_parent, lfd,
                         "HTTP/1.1 200 OK\r\n"
                         "Content-Length: 9000000000000000\r\n\r\n",
                         0);
      int64_t fh = pf_open(dst, 1, "tenant-test");
      assert(fh > 0);
      assert(pf_parent(fh, 0, "127.0.0.1", port) == 0);
      // expected_len 0: even the unknown-size path must cap the body.
      assert(pf_submit(fh, "rb-task", 0, 0, 0) == 0);
      FetchDone rec{};
      int drained = 0;
      for (int spin = 0; spin < 100 && drained == 0; spin++)
        drained = pf_complete(fh, (uint8_t*)&rec, 1, 100);
      assert(drained == 1 && rec.status == -2);
      assert(pf_close(fh) == 0);
      parent.join();
      close(lfd);
    }

    // b) close-discards-queue + concurrent pf_complete lifetime.
    {
      uint16_t port = 0;
      int lfd = listen_local(&port);
      assert(lfd >= 0);
      // 400 ms per burst: fetching the whole 64-job backlog (8 bursts on
      // 1 worker) would take >= 3.2 s; discard must close far sooner.
      std::thread parent(fake_parent, lfd,
                         "HTTP/1.1 404 Not Found\r\n"
                         "Content-Length: 0\r\n\r\n",
                         400 * 1000);
      int64_t fh = pf_open(dst, 1, "tenant-test");
      assert(fh > 0);
      assert(pf_parent(fh, 0, "127.0.0.1", port) == 0);
      for (uint32_t n = 0; n < 64; n++)
        assert(pf_submit(fh, "rb-task", 0, n, kSmall) == 0);
      // A waiter parked inside pf_complete across the close: the
      // shared_ptr holder + closing-wake must make this return cleanly
      // (ASAN would flag the old raw-pointer use-after-free here).
      std::thread waiter([&] {
        FetchDone recs[64];
        (void)pf_complete(fh, (uint8_t*)recs, 64, 10000);
      });
      usleep(50 * 1000);  // let the first burst go in-flight
      timespec c0, c1;
      clock_gettime(CLOCK_MONOTONIC, &c0);
      assert(pf_close(fh) == 0);
      clock_gettime(CLOCK_MONOTONIC, &c1);
      int64_t close_ms = (c1.tv_sec - c0.tv_sec) * 1000 +
                         (c1.tv_nsec - c0.tv_nsec) / 1000000;
      assert(close_ms < 2000);  // one in-flight burst, not the backlog
      waiter.join();
      parent.join();
      close(lfd);
    }

    // c) server batch byte cap under foreign pipelining.
    {
      char src_tmpl[] = "/tmp/native_test_cap_XXXXXX";
      int64_t src = ps_open(mkdtemp(src_tmpl));
      assert(src > 0);
      const uint32_t kBig = 200 * 1024;  // 3 pipelined > the 512 KiB cap
      assert(ps_create_task(src, "cap-task", kBig, 3 * kBig) == 0);
      for (uint32_t n = 0; n < 3; n++) {
        auto data = piece_bytes(9, n, kBig);
        assert(ps_write_piece(src, "cap-task", n, data.data(), kBig) ==
               (int64_t)kBig);
      }
      int64_t port = ps_serve(src, "127.0.0.1", 0, 16);
      assert(port > 0);
      int fd = socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons((uint16_t)port);
      inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
      assert(connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0);
      std::string reqs;
      for (int n = 0; n < 3; n++)
        reqs += "GET /pieces/cap-task/" + std::to_string(n) +
                " HTTP/1.1\r\nHost: x\r\n\r\n";
      assert(send_all(fd, reqs.data(), reqs.size()));  // one segment
      std::string acc;
      for (uint32_t n = 0; n < 3; n++) {
        std::string body;
        assert(read_response(fd, acc, &body, kBig) == 200);
        auto want = piece_bytes(9, n, kBig);
        assert(body.size() == kBig &&
               memcmp(body.data(), want.data(), kBig) == 0);
      }
      close(fd);
      // The conn thread bumps the counters AFTER the last body bytes
      // are already readable client-side — poll briefly.
      int64_t pieces = 0, bytes = 0, batched = 0, conns = 0;
      for (int spin = 0; spin < 200 && pieces < 3; spin++) {
        assert(ps_serve_stats2(src, &pieces, &bytes, &batched, &conns) == 0);
        if (pieces < 3) usleep(5000);
      }
      assert(pieces == 3 && bytes == 3 * (int64_t)kBig);
      assert(batched <= 2);  // the over-cap tail never rode the batch
      assert(ps_serve_stop(src) == 0);
      assert(ps_close(src) == 0);
    }
    assert(ps_close(dst) == 0);
  }

  // 7. ABI manifest witness (DESIGN.md §30): the compiled self-description
  // must exist and carry the layout facts the ctypes side depends on, and
  // the probe export must round-trip the sentinel through the REAL struct.
  {
    static_assert(sizeof(FetchDone) == 24, "FetchDone wire size");
    const char* m = df_abi_manifest();
    assert(m != nullptr);
    std::string mj(m);
    assert(mj.find("\"version\":1") != std::string::npos);
    assert(mj.find("\"df_abi_probe_fetchdone\"") != std::string::npos);
    assert(mj.find("\"kBatchBytesMax\":524288") != std::string::npos);
    assert(df_abi_manifest() == m);  // stable pointer, never freed

    uint8_t buf[sizeof(FetchDone)];
    assert(df_abi_probe_fetchdone(buf, sizeof(buf)) ==
           (int32_t)sizeof(FetchDone));
    FetchDone d;
    memcpy(&d, buf, sizeof(d));
    assert(d.number == 0xA1B2C3D4u && d.status == kFetchStatusProto &&
           d.length == 0x00C0FFEEu && d.slot == -7 &&
           d.cost_ns == 0x0102030405060708LL);
    assert(df_abi_probe_fetchdone(buf, sizeof(buf) - 1) == -1);
    assert(df_abi_probe_fetchdone(nullptr, 64) == -1);
  }

  printf("native_test: OK\n");
  return 0;
}
