// dragonfly2_tpu native runtime: columnar record engine + piece store.
//
// The reference's data plane is compiled Go (client/daemon/storage/
// storage_manager.go, local_storage.go: per-task metadata+data files,
// piece-granular writes, crash reload; scheduler/storage/storage.go:
// buffered record files).  This is the C++ equivalent for the rebuild:
//
//  * record engine — appends fixed-width float32 rows to DFC1 columnar
//    files (the format spec lives in records/columnar.py); append is a
//    single buffered write, no serialization.
//  * piece store  — per-task {meta,data} file pairs. Piece writes land at
//    piece_number*piece_size offsets; each commit appends a fixed-size
//    metadata record (number, offset, length, crc32, flags) fsync-ordered
//    after the data write, so a crash can lose at most the in-flight
//    piece; reload scans metadata and re-validates lengths.
//
// Exposed as a C ABI for the ctypes bindings in ../__init__.py.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// crc32 (IEEE).  Slice-by-8: processes 8 bytes per step through 8 derived
// tables — ~8x the single-table byte loop (which measured ~400 MB/s and
// made native piece reads 10x slower than Python's SIMD zlib.crc32).
// Same polynomial/init/final-xor as zlib, so stored CRCs stay valid.
// ---------------------------------------------------------------------------

uint32_t crc32_tab8[8][256];
std::once_flag crc_once;

void crc32_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_tab8[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int t = 1; t < 8; t++)
      crc32_tab8[t][i] =
          crc32_tab8[0][crc32_tab8[t - 1][i] & 0xFF] ^ (crc32_tab8[t - 1][i] >> 8);
}

uint32_t crc32(const uint8_t* data, size_t len) {
  std::call_once(crc_once, crc32_init);
  uint32_t c = 0xFFFFFFFFu;
  while (len >= 8) {
    uint32_t lo, hi;
    memcpy(&lo, data, 4);
    memcpy(&hi, data + 4, 4);
    lo ^= c;
    c = crc32_tab8[7][lo & 0xFF] ^ crc32_tab8[6][(lo >> 8) & 0xFF] ^
        crc32_tab8[5][(lo >> 16) & 0xFF] ^ crc32_tab8[4][lo >> 24] ^
        crc32_tab8[3][hi & 0xFF] ^ crc32_tab8[2][(hi >> 8) & 0xFF] ^
        crc32_tab8[1][(hi >> 16) & 0xFF] ^ crc32_tab8[0][hi >> 24];
    data += 8;
    len -= 8;
  }
  for (size_t i = 0; i < len; i++)
    c = crc32_tab8[0][(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// Columnar record engine (DFC1; spec: records/columnar.py)
// ---------------------------------------------------------------------------

constexpr char kMagic[4] = {'D', 'F', 'C', '1'};

struct RecordFile {
  FILE* f = nullptr;
  uint32_t width = 0;       // columns
  int64_t data_offset = 0;
  std::mutex mu;
  bool closed = false;  // re_close sets it; in-flight appends must bail
};

using RecordPtr = std::shared_ptr<RecordFile>;

std::mutex g_records_mu;
std::map<int64_t, RecordPtr> g_records;
std::atomic<int64_t> g_next_handle{1};

// ---------------------------------------------------------------------------
// Piece store
// ---------------------------------------------------------------------------

#pragma pack(push, 1)
struct PieceMeta {
  uint32_t number;
  uint32_t length;
  int64_t offset;
  uint32_t crc;
  uint32_t flags;  // 1 = committed
};

struct TaskHeader {
  char magic[4];          // "DFPS"
  uint32_t piece_size;
  int64_t content_length;
};
#pragma pack(pop)

struct TaskStore {
  std::string dir;
  FILE* data = nullptr;
  FILE* meta = nullptr;
  TaskHeader header{};
  std::map<uint32_t, PieceMeta> pieces;
  std::mutex mu;
  bool closed = false;  // set by delete/close; late readers must bail
};

using TaskPtr = std::shared_ptr<TaskStore>;

struct PieceStore {
  std::string root;
  std::map<std::string, TaskPtr> tasks;
  std::mutex mu;
};

std::mutex g_stores_mu;
std::map<int64_t, PieceStore*> g_stores;

std::string task_dir(const PieceStore* ps, const char* task_id) {
  return ps->root + "/" + task_id;
}

bool load_task(TaskStore* ts) {
  // Re-read committed piece metadata; tolerate a torn trailing record.
  fseeko(ts->meta, 0, SEEK_END);
  off_t size = ftello(ts->meta);
  if (size < (off_t)sizeof(TaskHeader)) return false;
  fseeko(ts->meta, 0, SEEK_SET);
  if (fread(&ts->header, sizeof(TaskHeader), 1, ts->meta) != 1) return false;
  if (memcmp(ts->header.magic, "DFPS", 4) != 0) return false;
  size_t n = (size - sizeof(TaskHeader)) / sizeof(PieceMeta);
  for (size_t i = 0; i < n; i++) {
    PieceMeta pm;
    if (fread(&pm, sizeof(PieceMeta), 1, ts->meta) != 1) break;
    if (pm.flags & 1) ts->pieces[pm.number] = pm;
  }
  fseeko(ts->meta, 0, SEEK_END);
  return true;
}

TaskPtr open_task(PieceStore* ps, const char* task_id, uint32_t piece_size,
                  int64_t content_length, bool create) {
  std::lock_guard<std::mutex> lk(ps->mu);
  auto it = ps->tasks.find(task_id);
  if (it != ps->tasks.end()) return it->second;

  std::string dir = task_dir(ps, task_id);
  std::string meta_path = dir + "/meta";
  std::string data_path = dir + "/data";
  struct stat st;
  bool exists = stat(meta_path.c_str(), &st) == 0;
  if (!exists && !create) return nullptr;
  if (!exists) {
    if (mkdir(dir.c_str(), 0755) != 0 && errno != EEXIST) return nullptr;
  }

  TaskPtr ts = std::make_shared<TaskStore>();
  ts->dir = dir;
  ts->meta = fopen(meta_path.c_str(), exists ? "r+b" : "w+b");
  ts->data = fopen(data_path.c_str(), exists ? "r+b" : "w+b");
  if (!ts->meta || !ts->data) {
    if (ts->meta) fclose(ts->meta);
    if (ts->data) fclose(ts->data);
    return nullptr;
  }
  if (exists) {
    if (!load_task(ts.get())) {
      fclose(ts->meta);
      fclose(ts->data);
      return nullptr;
    }
  } else {
    memcpy(ts->header.magic, "DFPS", 4);
    ts->header.piece_size = piece_size;
    ts->header.content_length = content_length;
    fwrite(&ts->header, sizeof(TaskHeader), 1, ts->meta);
    fflush(ts->meta);
  }
  ps->tasks[task_id] = ts;
  return ts;
}

int remove_tree(const std::string& dir) {
  DIR* d = opendir(dir.c_str());
  if (!d) return -1;
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    if (strcmp(e->d_name, ".") == 0 || strcmp(e->d_name, "..") == 0) continue;
    std::string path = dir + "/" + e->d_name;
    unlink(path.c_str());
  }
  closedir(d);
  return rmdir(dir.c_str());
}

}  // namespace

extern "C" {

// -- record engine ----------------------------------------------------------

int64_t re_open(const char* path, const char* header_json, uint32_t width) {
  struct stat st;
  bool exists = stat(path, &st) == 0 && st.st_size > 0;
  FILE* f = fopen(path, exists ? "r+b" : "w+b");
  if (!f) return -1;
  RecordPtr rf = std::make_shared<RecordFile>();
  rf->f = f;
  rf->width = width;
  if (exists) {
    char magic[4];
    uint32_t hlen = 0;
    if (fread(magic, 4, 1, f) != 1 || memcmp(magic, kMagic, 4) != 0 ||
        fread(&hlen, 4, 1, f) != 1) {
      fclose(f);
      return -2;
    }
    rf->data_offset = 8 + hlen;
    // Width consistency against the existing payload: the data section
    // must be a whole number of rows at the claimed width, else appends
    // would land misaligned (the bindings also validate the header JSON).
    fseeko(f, 0, SEEK_END);
    off_t payload = ftello(f) - rf->data_offset;
    if (payload % (off_t)(sizeof(float) * width) != 0) {
      fclose(f);
      return -3;
    }
  } else {
    uint32_t hlen = (uint32_t)strlen(header_json);
    fwrite(kMagic, 4, 1, f);
    fwrite(&hlen, 4, 1, f);
    fwrite(header_json, 1, hlen, f);
    rf->data_offset = 8 + hlen;
    fflush(f);
  }
  std::lock_guard<std::mutex> lk(g_records_mu);
  int64_t h = g_next_handle++;
  g_records[h] = rf;
  return h;
}

int64_t re_append(int64_t handle, const float* rows, int64_t n_rows) {
  RecordPtr rf;
  {
    std::lock_guard<std::mutex> lk(g_records_mu);
    auto it = g_records.find(handle);
    if (it == g_records.end()) return -1;
    rf = it->second;  // shared_ptr outlives a concurrent re_close
  }
  std::lock_guard<std::mutex> lk(rf->mu);
  if (rf->closed) return -2;
  size_t wrote = fwrite(rows, sizeof(float) * rf->width, n_rows, rf->f);
  return (int64_t)wrote;
}

int re_flush(int64_t handle) {
  RecordPtr rf;
  {
    std::lock_guard<std::mutex> lk(g_records_mu);
    auto it = g_records.find(handle);
    if (it == g_records.end()) return -1;
    rf = it->second;
  }
  std::lock_guard<std::mutex> lk2(rf->mu);
  if (rf->closed) return -2;
  fflush(rf->f);
  return 0;
}

int64_t re_rows(int64_t handle) {
  RecordPtr rf;
  {
    std::lock_guard<std::mutex> lk(g_records_mu);
    auto it = g_records.find(handle);
    if (it == g_records.end()) return -1;
    rf = it->second;
  }
  std::lock_guard<std::mutex> lk2(rf->mu);
  if (rf->closed) return -2;
  fflush(rf->f);
  off_t end = ftello(rf->f);
  return (end - rf->data_offset) / (sizeof(float) * rf->width);
}

int re_close(int64_t handle) {
  RecordPtr rf;
  {
    std::lock_guard<std::mutex> lk(g_records_mu);
    auto it = g_records.find(handle);
    if (it == g_records.end()) return -1;
    rf = it->second;
    g_records.erase(it);
  }
  std::lock_guard<std::mutex> lk(rf->mu);
  if (!rf->closed) {
    fclose(rf->f);
    rf->closed = true;
  }
  return 0;
}

// -- piece store ------------------------------------------------------------

int64_t ps_open(const char* root) {
  if (mkdir(root, 0755) != 0 && errno != EEXIST) return -1;
  PieceStore* ps = new PieceStore();
  ps->root = root;
  std::lock_guard<std::mutex> lk(g_stores_mu);
  int64_t h = g_next_handle++;
  g_stores[h] = ps;
  return h;
}

static PieceStore* get_store(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_stores_mu);
  auto it = g_stores.find(handle);
  return it == g_stores.end() ? nullptr : it->second;
}

int ps_create_task(int64_t handle, const char* task_id, uint32_t piece_size,
                   int64_t content_length) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, piece_size, content_length, true);
  return ts ? 0 : -2;
}

int ps_load_task(int64_t handle, const char* task_id) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  return ts ? 0 : -2;
}

int64_t ps_write_piece(int64_t handle, const char* task_id, uint32_t number,
                       const uint8_t* data, uint32_t length) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  if (ts->closed) return -7;
  int64_t offset = (int64_t)number * ts->header.piece_size;
  fseeko(ts->data, offset, SEEK_SET);
  if (fwrite(data, 1, length, ts->data) != length) return -3;
  fflush(ts->data);
  // Data durable before metadata commit: a crash between the two leaves an
  // uncommitted piece that reload simply redownloads.
  fsync(fileno(ts->data));
  PieceMeta pm{number, length, offset, crc32(data, length), 1};
  fseeko(ts->meta, 0, SEEK_END);
  if (fwrite(&pm, sizeof(PieceMeta), 1, ts->meta) != 1) return -4;
  fflush(ts->meta);
  fsync(fileno(ts->meta));
  ts->pieces[number] = pm;
  return (int64_t)length;
}

int64_t ps_read_piece(int64_t handle, const char* task_id, uint32_t number,
                      uint8_t* buf, uint32_t buf_len, int verify) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  if (ts->closed) return -7;
  auto it = ts->pieces.find(number);
  if (it == ts->pieces.end()) return -3;
  const PieceMeta& pm = it->second;
  if (pm.length > buf_len) return -4;
  fseeko(ts->data, pm.offset, SEEK_SET);
  if (fread(buf, 1, pm.length, ts->data) != pm.length) return -5;
  if (verify && crc32(buf, pm.length) != pm.crc) return -6;
  return (int64_t)pm.length;
}

int64_t ps_piece_count(int64_t handle, const char* task_id) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  return (int64_t)ts->pieces.size();
}

// Fill `bitmap` (caller-allocated, n_pieces bytes) with 1 per present piece.
int ps_piece_bitmap(int64_t handle, const char* task_id, uint8_t* bitmap,
                    uint32_t n_pieces) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  memset(bitmap, 0, n_pieces);
  for (auto& kv : ts->pieces)
    if (kv.first < n_pieces) bitmap[kv.first] = 1;
  return 0;
}

int64_t ps_task_bytes(int64_t handle, const char* task_id) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  int64_t total = 0;
  for (auto& kv : ts->pieces) total += kv.second.length;
  return total;
}

int64_t ps_content_length(int64_t handle, const char* task_id) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  return ts->header.content_length;
}

int64_t ps_piece_size(int64_t handle, const char* task_id) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  return (int64_t)ts->header.piece_size;
}

int ps_delete_task(int64_t handle, const char* task_id) {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts;
  {
    std::lock_guard<std::mutex> lk(ps->mu);
    auto it = ps->tasks.find(task_id);
    if (it != ps->tasks.end()) {
      ts = it->second;  // shared_ptr keeps the struct alive for in-flight readers
      ps->tasks.erase(it);
    }
  }
  if (ts) {
    std::lock_guard<std::mutex> tlk(ts->mu);
    fclose(ts->meta);
    fclose(ts->data);
    ts->closed = true;
  }
  return remove_tree(task_dir(ps, task_id));
}

int ps_close(int64_t handle) {
  PieceStore* ps;
  {
    std::lock_guard<std::mutex> lk(g_stores_mu);
    auto it = g_stores.find(handle);
    if (it == g_stores.end()) return -1;
    ps = it->second;
    g_stores.erase(it);
  }
  std::lock_guard<std::mutex> lk(ps->mu);
  for (auto& kv : ps->tasks) {
    std::lock_guard<std::mutex> tlk(kv.second->mu);
    if (!kv.second->closed) {
      fclose(kv.second->meta);
      fclose(kv.second->data);
      kv.second->closed = true;
    }
  }
  ps->tasks.clear();
  delete ps;
  return 0;
}

}  // extern "C"
