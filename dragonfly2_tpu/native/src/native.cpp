// dragonfly2_tpu native runtime: columnar record engine + piece store.
//
// The reference's data plane is compiled Go (client/daemon/storage/
// storage_manager.go, local_storage.go: per-task metadata+data files,
// piece-granular writes, crash reload; scheduler/storage/storage.go:
// buffered record files).  This is the C++ equivalent for the rebuild:
//
//  * record engine — appends fixed-width float32 rows to DFC1 columnar
//    files (the format spec lives in records/columnar.py); append is a
//    single buffered write, no serialization.
//  * piece store  — per-task {meta,data} file pairs. Piece writes land at
//    piece_number*piece_size offsets; each commit appends a fixed-size
//    metadata record (number, offset, length, crc32, flags) fsync-ordered
//    after the data write, so a crash can lose at most the in-flight
//    piece; reload scans metadata and re-validates lengths.
//
// Exposed as a C ABI for the ctypes bindings in ../__init__.py.

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <deque>
#include <dirent.h>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <thread>
#include <type_traits>
#include <unistd.h>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Shared ABI constants (DESIGN.md §30).  Every `kName` below is declared
// once more in records/abi_contracts.py; dflint DF020 cross-checks the two
// texts and the df_abi_manifest() witness re-emits the COMPILED values —
// change either side alone and tier-1 fails by constant name.
// ---------------------------------------------------------------------------

constexpr char kMagic[] = "DFC1";      // columnar record file magic
constexpr char kTaskMagic[] = "DFPS";  // piece-store task header magic

// Batched submission / pipelining caps.  The server coalesces up to
// kBatchMax pipelined piece GETs into one gather-write burst, byte-capped
// at kBatchBytesMax (the batch's whole scratch RSS cost: a foreign client
// pipelining 16 x 4 MiB GETs must not make every connection thread stage
// 64 MiB or throw bad_alloc).  The client's fetch workers pipeline up to
// kFetchBurstMax GETs under the SAME byte cap — a burst serializes its
// responses on one connection, so big pieces spread across workers
// instead.  kMaxFetchBody bounds any single body allocation when the
// caller doesn't know the piece length (16x the common 4 MiB piece): a
// hostile parent advertising `Content-Length: 9e15` must be a protocol
// error, not a bad_alloc.
constexpr size_t kBatchMax = 16;
constexpr int64_t kBatchBytesMax = 512 * 1024;
constexpr size_t kFetchBurstMax = 8;
constexpr int64_t kMaxFetchBody = 64LL * 1024 * 1024;

// Worker / slot / serving caps shared with the bindings' docstrings and
// the Python server's wire behavior (long-poll bound).
constexpr int kFetchWorkersDefault = 4;
constexpr int kFetchWorkersMax = 64;
constexpr int kParentSlotMax = 255;
constexpr int kServeLimitDefault = 64;
constexpr int64_t kLongPollMaxMs = 30000;

// FetchDone.status codes: 0 ok, >0 raw HTTP status, negatives local.
constexpr int32_t kFetchStatusOk = 0;
constexpr int32_t kFetchStatusConn = -1;    // dial/socket error; queued jobs discarded on close
constexpr int32_t kFetchStatusProto = -2;   // protocol / length mismatch / oversized body
constexpr int32_t kFetchStatusCommit = -3;  // local ps_write_piece failure

// Catch-all containment sentinel (DF021): an extern "C" accessor that
// swallows an exception returns this instead of letting it escape the C
// ABI — an escaping exception would std::terminate the embedding daemon.
constexpr int32_t kAbiTrap = -125;

// PieceMeta.flags bits.
constexpr uint32_t kPieceFlagCommitted = 1;
constexpr uint32_t kPieceFlagVerified = 2;  // CRC checked on first serve

// ---------------------------------------------------------------------------
// crc32 (IEEE).  Slice-by-8: processes 8 bytes per step through 8 derived
// tables — ~8x the single-table byte loop (which measured ~400 MB/s and
// made native piece reads 10x slower than Python's SIMD zlib.crc32).
// Same polynomial/init/final-xor as zlib, so stored CRCs stay valid.
// ---------------------------------------------------------------------------

uint32_t crc32_tab8[8][256];
std::once_flag crc_once;

void crc32_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_tab8[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int t = 1; t < 8; t++)
      crc32_tab8[t][i] =
          crc32_tab8[0][crc32_tab8[t - 1][i] & 0xFF] ^ (crc32_tab8[t - 1][i] >> 8);
}

uint32_t crc32(const uint8_t* data, size_t len) {
  std::call_once(crc_once, crc32_init);
  uint32_t c = 0xFFFFFFFFu;
  while (len >= 8) {
    uint32_t lo, hi;
    memcpy(&lo, data, 4);
    memcpy(&hi, data + 4, 4);
    lo ^= c;
    c = crc32_tab8[7][lo & 0xFF] ^ crc32_tab8[6][(lo >> 8) & 0xFF] ^
        crc32_tab8[5][(lo >> 16) & 0xFF] ^ crc32_tab8[4][lo >> 24] ^
        crc32_tab8[3][hi & 0xFF] ^ crc32_tab8[2][(hi >> 8) & 0xFF] ^
        crc32_tab8[1][(hi >> 16) & 0xFF] ^ crc32_tab8[0][hi >> 24];
    data += 8;
    len -= 8;
  }
  for (size_t i = 0; i < len; i++)
    c = crc32_tab8[0][(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// Columnar record engine (DFC1; spec: records/columnar.py)
// ---------------------------------------------------------------------------

struct RecordFile {
  FILE* f = nullptr;
  uint32_t width = 0;       // columns
  int64_t data_offset = 0;
  std::mutex mu;
  bool closed = false;  // re_close sets it; in-flight appends must bail
};

using RecordPtr = std::shared_ptr<RecordFile>;

std::mutex g_records_mu;
std::map<int64_t, RecordPtr> g_records;
std::atomic<int64_t> g_next_handle{1};

// ---------------------------------------------------------------------------
// Piece store
// ---------------------------------------------------------------------------

#pragma pack(push, 1)
struct PieceMeta {
  uint32_t number;
  uint32_t length;
  int64_t offset;
  uint32_t crc;
  uint32_t flags;  // kPieceFlagCommitted | kPieceFlagVerified
};

struct TaskHeader {
  char magic[4];          // "DFPS"
  uint32_t piece_size;
  int64_t content_length;
};
#pragma pack(pop)

struct TaskStore {
  std::string dir;
  FILE* data = nullptr;
  FILE* meta = nullptr;
  TaskHeader header{};
  std::map<uint32_t, PieceMeta> pieces;
  std::mutex mu;
  bool closed = false;  // set by delete/close; late readers must bail
};

using TaskPtr = std::shared_ptr<TaskStore>;

struct PieceStore {
  std::string root;
  std::map<std::string, TaskPtr> tasks;
  std::mutex mu;
};

std::mutex g_stores_mu;
std::map<int64_t, PieceStore*> g_stores;

std::string task_dir(const PieceStore* ps, const char* task_id) {
  return ps->root + "/" + task_id;
}

bool load_task(TaskStore* ts) {
  // Re-read committed piece metadata; tolerate a torn trailing record.
  fseeko(ts->meta, 0, SEEK_END);
  off_t size = ftello(ts->meta);
  if (size < (off_t)sizeof(TaskHeader)) return false;
  fseeko(ts->meta, 0, SEEK_SET);
  if (fread(&ts->header, sizeof(TaskHeader), 1, ts->meta) != 1) return false;
  if (memcmp(ts->header.magic, kTaskMagic, 4) != 0) return false;
  size_t n = (size - sizeof(TaskHeader)) / sizeof(PieceMeta);
  for (size_t i = 0; i < n; i++) {
    PieceMeta pm;
    if (fread(&pm, sizeof(PieceMeta), 1, ts->meta) != 1) break;
    if (pm.flags & kPieceFlagCommitted) ts->pieces[pm.number] = pm;
  }
  fseeko(ts->meta, 0, SEEK_END);
  return true;
}

TaskPtr open_task(PieceStore* ps, const char* task_id, uint32_t piece_size,
                  int64_t content_length, bool create) {
  std::lock_guard<std::mutex> lk(ps->mu);
  auto it = ps->tasks.find(task_id);
  if (it != ps->tasks.end()) return it->second;

  std::string dir = task_dir(ps, task_id);
  std::string meta_path = dir + "/meta";
  std::string data_path = dir + "/data";
  struct stat st;
  bool exists = stat(meta_path.c_str(), &st) == 0;
  if (!exists && !create) return nullptr;
  if (!exists) {
    if (mkdir(dir.c_str(), 0755) != 0 && errno != EEXIST) return nullptr;
  }

  TaskPtr ts = std::make_shared<TaskStore>();
  ts->dir = dir;
  ts->meta = fopen(meta_path.c_str(), exists ? "r+b" : "w+b");
  ts->data = fopen(data_path.c_str(), exists ? "r+b" : "w+b");
  if (!ts->meta || !ts->data) {
    if (ts->meta) fclose(ts->meta);
    if (ts->data) fclose(ts->data);
    return nullptr;
  }
  if (exists) {
    if (!load_task(ts.get())) {
      fclose(ts->meta);
      fclose(ts->data);
      return nullptr;
    }
  } else {
    memcpy(ts->header.magic, kTaskMagic, 4);
    ts->header.piece_size = piece_size;
    ts->header.content_length = content_length;
    fwrite(&ts->header, sizeof(TaskHeader), 1, ts->meta);
    fflush(ts->meta);
  }
  ps->tasks[task_id] = ts;
  return ts;
}

int remove_tree(const std::string& dir) {
  DIR* d = opendir(dir.c_str());
  if (!d) return -1;
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    if (strcmp(e->d_name, ".") == 0 || strcmp(e->d_name, "..") == 0) continue;
    std::string path = dir + "/" + e->d_name;
    unlink(path.c_str());
  }
  closedir(d);
  return rmdir(dir.c_str());
}

}  // namespace

extern "C" {

// -- record engine ----------------------------------------------------------

int64_t re_open(const char* path, const char* header_json, uint32_t width) try {
  struct stat st;
  bool exists = stat(path, &st) == 0 && st.st_size > 0;
  FILE* f = fopen(path, exists ? "r+b" : "w+b");
  if (!f) return -1;
  RecordPtr rf = std::make_shared<RecordFile>();
  rf->f = f;
  rf->width = width;
  if (exists) {
    char magic[4];
    uint32_t hlen = 0;
    if (fread(magic, 4, 1, f) != 1 || memcmp(magic, kMagic, 4) != 0 ||
        fread(&hlen, 4, 1, f) != 1) {
      fclose(f);
      return -2;
    }
    rf->data_offset = 8 + hlen;
    // Width consistency against the existing payload: the data section
    // must be a whole number of rows at the claimed width, else appends
    // would land misaligned (the bindings also validate the header JSON).
    fseeko(f, 0, SEEK_END);
    off_t payload = ftello(f) - rf->data_offset;
    if (payload % (off_t)(sizeof(float) * width) != 0) {
      fclose(f);
      return -3;
    }
  } else {
    uint32_t hlen = (uint32_t)strlen(header_json);
    fwrite(kMagic, 4, 1, f);
    fwrite(&hlen, 4, 1, f);
    fwrite(header_json, 1, hlen, f);
    rf->data_offset = 8 + hlen;
    fflush(f);
  }
  std::lock_guard<std::mutex> lk(g_records_mu);
  int64_t h = g_next_handle++;
  g_records[h] = rf;
  return h;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t re_append(int64_t handle, const float* rows, int64_t n_rows) try {
  RecordPtr rf;
  {
    std::lock_guard<std::mutex> lk(g_records_mu);
    auto it = g_records.find(handle);
    if (it == g_records.end()) return -1;
    rf = it->second;  // shared_ptr outlives a concurrent re_close
  }
  std::lock_guard<std::mutex> lk(rf->mu);
  if (rf->closed) return -2;
  size_t wrote = fwrite(rows, sizeof(float) * rf->width, n_rows, rf->f);
  return (int64_t)wrote;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int re_flush(int64_t handle) try {
  RecordPtr rf;
  {
    std::lock_guard<std::mutex> lk(g_records_mu);
    auto it = g_records.find(handle);
    if (it == g_records.end()) return -1;
    rf = it->second;
  }
  std::lock_guard<std::mutex> lk2(rf->mu);
  if (rf->closed) return -2;
  fflush(rf->f);
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t re_rows(int64_t handle) try {
  RecordPtr rf;
  {
    std::lock_guard<std::mutex> lk(g_records_mu);
    auto it = g_records.find(handle);
    if (it == g_records.end()) return -1;
    rf = it->second;
  }
  std::lock_guard<std::mutex> lk2(rf->mu);
  if (rf->closed) return -2;
  fflush(rf->f);
  off_t end = ftello(rf->f);
  return (end - rf->data_offset) / (sizeof(float) * rf->width);
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int re_close(int64_t handle) try {
  RecordPtr rf;
  {
    std::lock_guard<std::mutex> lk(g_records_mu);
    auto it = g_records.find(handle);
    if (it == g_records.end()) return -1;
    rf = it->second;
    g_records.erase(it);
  }
  std::lock_guard<std::mutex> lk(rf->mu);
  if (!rf->closed) {
    fclose(rf->f);
    rf->closed = true;
  }
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// -- piece store ------------------------------------------------------------

int64_t ps_open(const char* root) try {
  if (mkdir(root, 0755) != 0 && errno != EEXIST) return -1;
  PieceStore* ps = new PieceStore();
  ps->root = root;
  std::lock_guard<std::mutex> lk(g_stores_mu);
  int64_t h = g_next_handle++;
  g_stores[h] = ps;
  return h;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

static PieceStore* get_store(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_stores_mu);
  auto it = g_stores.find(handle);
  return it == g_stores.end() ? nullptr : it->second;
}

int ps_create_task(int64_t handle, const char* task_id, uint32_t piece_size,
                   int64_t content_length) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, piece_size, content_length, true);
  return ts ? 0 : -2;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int ps_load_task(int64_t handle, const char* task_id) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  return ts ? 0 : -2;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t ps_write_piece(int64_t handle, const char* task_id, uint32_t number,
                       const uint8_t* data, uint32_t length) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  if (ts->closed) return -7;
  int64_t offset = (int64_t)number * ts->header.piece_size;
  fseeko(ts->data, offset, SEEK_SET);
  if (fwrite(data, 1, length, ts->data) != length) return -3;
  fflush(ts->data);
  // Data durable before metadata commit: a crash between the two leaves an
  // uncommitted piece that reload simply redownloads.
  fsync(fileno(ts->data));
  PieceMeta pm{number, length, offset, crc32(data, length),
               kPieceFlagCommitted};
  fseeko(ts->meta, 0, SEEK_END);
  if (fwrite(&pm, sizeof(PieceMeta), 1, ts->meta) != 1) return -4;
  fflush(ts->meta);
  fsync(fileno(ts->meta));
  ts->pieces[number] = pm;
  return (int64_t)length;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t ps_read_piece(int64_t handle, const char* task_id, uint32_t number,
                      uint8_t* buf, uint32_t buf_len, int verify) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  if (ts->closed) return -7;
  auto it = ts->pieces.find(number);
  if (it == ts->pieces.end()) return -3;
  const PieceMeta& pm = it->second;
  if (pm.length > buf_len) return -4;
  fseeko(ts->data, pm.offset, SEEK_SET);
  if (fread(buf, 1, pm.length, ts->data) != pm.length) return -5;
  if (verify && crc32(buf, pm.length) != pm.crc) return -6;
  return (int64_t)pm.length;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t ps_piece_count(int64_t handle, const char* task_id) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  return (int64_t)ts->pieces.size();
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Fill `bitmap` (caller-allocated, n_pieces bytes) with 1 per present piece.
int ps_piece_bitmap(int64_t handle, const char* task_id, uint8_t* bitmap,
                    uint32_t n_pieces) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  memset(bitmap, 0, n_pieces);
  for (auto& kv : ts->pieces)
    if (kv.first < n_pieces) bitmap[kv.first] = 1;
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t ps_task_bytes(int64_t handle, const char* task_id) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  std::lock_guard<std::mutex> lk(ts->mu);
  int64_t total = 0;
  for (auto& kv : ts->pieces) total += kv.second.length;
  return total;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t ps_content_length(int64_t handle, const char* task_id) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  return ts->header.content_length;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t ps_piece_size(int64_t handle, const char* task_id) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts = open_task(ps, task_id, 0, 0, false);
  if (!ts) return -2;
  return (int64_t)ts->header.piece_size;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int ps_delete_task(int64_t handle, const char* task_id) try {
  PieceStore* ps = get_store(handle);
  if (!ps) return -1;
  TaskPtr ts;
  {
    std::lock_guard<std::mutex> lk(ps->mu);
    auto it = ps->tasks.find(task_id);
    if (it != ps->tasks.end()) {
      ts = it->second;  // shared_ptr keeps the struct alive for in-flight readers
      ps->tasks.erase(it);
    }
  }
  if (ts) {
    std::lock_guard<std::mutex> tlk(ts->mu);
    fclose(ts->meta);
    fclose(ts->data);
    ts->closed = true;
  }
  return remove_tree(task_dir(ps, task_id));
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

}  // extern "C"

// ---------------------------------------------------------------------------
// HTTP piece server (the perf-critical serving hot path).
//
// Reference: client/daemon/upload/upload_manager.go:59-76 — compiled-Go
// HTTP serving of piece ranges.  The Python stand-in topped out at
// 0.45 GB/s aggregate (per-request setup + GIL); this serves the SAME
// wire contract (piece_transport.py):
//
//   GET /pieces/<task>/<n>      → 200 piece bytes (503 over the cap)
//   GET /tasks/<task>/pieces    → 200 piece bitmap (1 byte per piece)
//   GET /tasks/<task>  + Range  → 206 assembled byte range
//
// Thread-per-connection with keep-alive; piece/range bodies go through
// sendfile(2), so payload bytes never cross user space.  Piece integrity:
// CRC is verified ON FIRST SERVE of each piece (flags bit 2 caches the
// result) — per-request re-hashing is what kept the Python path slow,
// and the client still digest-verifies every piece on its side.
// ---------------------------------------------------------------------------

extern "C" int ps_serve_stop(int64_t handle);

namespace {

struct HttpServer {
  int lfd = -1;
  std::atomic<bool> stopping{false};
  std::atomic<int> active{0};       // DATA requests being served (503 cap)
  std::atomic<int> meta_active{0};  // parked bitmap long-polls (degrade cap)
  std::atomic<int> conn_count{0};   // live connection threads
  std::atomic<int64_t> pieces_served{0};
  std::atomic<int64_t> bytes_served{0};
  std::atomic<int64_t> batched_pieces{0};  // pieces served via burst path
  int limit = kServeLimitDefault;
  int64_t store_handle = 0;
  std::thread accept_th;
  uint16_t port = 0;
  std::mutex conns_mu;
  std::map<int, int> conns;         // live connection fds (for stop wakeup)
};

std::mutex g_servers_mu;
std::map<int64_t, HttpServer*> g_servers;  // keyed by store handle

// Wedged-shutdown accounting (ps_serve_stop past the 5 s grace): the
// struct is intentionally leaked rather than freed under live threads,
// but the *fact* must be observable — bench/test teardowns assert these
// stay zero instead of grepping stderr.
std::atomic<int64_t> g_leaked_servers{0};
std::atomic<int64_t> g_leaked_conns{0};

// Append more bytes until `acc` holds at least one full request head.
// Residual bytes from a previous read stay in `acc` — pipelined or
// coalesced requests must not be discarded.
bool read_request(int fd, std::string& acc) {
  char buf[4096];
  while (acc.size() < 65536) {
    if (acc.find("\r\n\r\n") != std::string::npos) return true;
    ssize_t n = recv(fd, buf, sizeof(buf), 0);
    if (n <= 0) return false;
    acc.append(buf, (size_t)n);
  }
  return false;
}

bool send_all(int fd, const char* data, size_t len) {
  while (len > 0) {
    ssize_t n = send(fd, data, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    data += n;
    len -= (size_t)n;
  }
  return true;
}

bool send_head(int fd, int code, const char* reason, int64_t content_length) {
  char head[256];
  int n = snprintf(head, sizeof(head),
                   "HTTP/1.1 %d %s\r\n"
                   "Content-Type: application/octet-stream\r\n"
                   "Content-Length: %lld\r\n\r\n",
                   code, reason, (long long)content_length);
  return send_all(fd, head, (size_t)n);
}

bool send_error_http(int fd, int code, const char* reason) {
  return send_head(fd, code, reason, 0);
}

bool sendfile_all(int out_fd, int in_fd, int64_t offset, int64_t count) {
  off_t off = (off_t)offset;
  while (count > 0) {
    ssize_t n = sendfile(out_fd, in_fd, &off, (size_t)count);
    if (n <= 0) return false;
    count -= n;
  }
  return true;
}

// Strict digit parse (atoll accepts garbage as 0 — "bytes=zz-5" must 416,
// matching the Python server's ValueError path).
bool parse_i64(const std::string& s, int64_t* out) {
  if (s.empty()) return false;
  int64_t v = 0;
  for (char c : s) {
    if (c < '0' || c > '9') return false;
    int d = c - '0';
    if (v > (INT64_MAX - d) / 10) return false;  // overflow → reject, not wrap
    v = v * 10 + d;
  }
  *out = v;
  return true;
}

// Verify a piece's CRC once; afterwards flags bit 2 short-circuits.
bool piece_verified(TaskStore* ts, PieceMeta& pm) {
  if (pm.flags & kPieceFlagVerified) return true;
  std::vector<uint8_t> buf(pm.length);
  {
    std::lock_guard<std::mutex> lk(ts->mu);
    if (ts->closed) return false;
    fseeko(ts->data, pm.offset, SEEK_SET);
    if (fread(buf.data(), 1, pm.length, ts->data) != pm.length) return false;
  }
  if (crc32(buf.data(), pm.length) != pm.crc) return false;
  std::lock_guard<std::mutex> lk(ts->mu);
  auto it = ts->pieces.find(pm.number);
  if (it != ts->pieces.end()) it->second.flags |= kPieceFlagVerified;
  pm.flags |= kPieceFlagVerified;
  return true;
}

// "key=value" lookup in a raw query string; leaves *out untouched when
// the key is absent or non-numeric.
void parse_query_i64(const std::string& query, const char* key, int64_t* out) {
  std::string needle = std::string(key) + "=";
  size_t pos = 0;
  while (pos < query.size()) {
    size_t amp = query.find('&', pos);
    std::string pair = query.substr(pos, amp == std::string::npos
                                             ? std::string::npos
                                             : amp - pos);
    if (pair.rfind(needle, 0) == 0) {
      int64_t v = 0;
      if (parse_i64(pair.substr(needle.size()), &v)) *out = v;
      return;
    }
    if (amp == std::string::npos) break;
    pos = amp + 1;
  }
}

// Network-supplied task components must stay inside the store root:
// reject empty, '.', '..', and path separators before open_task — a bare
// "GET /pieces/../N" would otherwise open <root>/../meta and cache the
// foreign entry in ps->tasks.
bool valid_task_id(const std::string& id) {
  if (id.empty() || id == "." || id == "..") return false;
  return id.find('/') == std::string::npos &&
         id.find('\\') == std::string::npos;
}

// Serve-safe data fd: dup() under the task lock so ps_delete_task's
// fclose cannot invalidate the descriptor mid-sendfile.  -1 when the
// task is closed.  Caller close()s it.
int dup_data_fd(TaskStore* ts) {
  std::lock_guard<std::mutex> lk(ts->mu);
  if (ts->closed) return -1;
  return dup(fileno(ts->data));
}

// Gather-write a full iovec array.  sendmsg (not writev) so MSG_NOSIGNAL
// holds — a peer that hangs up mid-burst must surface as an error, not a
// process-killing SIGPIPE (native_test runs without Python's handler).
bool sendv_all(int fd, iovec* iov, size_t n) {
  size_t i = 0;
  while (i < n) {
    msghdr msg{};
    msg.msg_iov = iov + i;
    msg.msg_iovlen = std::min(n - i, (size_t)64);
    ssize_t w = sendmsg(fd, &msg, MSG_NOSIGNAL);
    if (w <= 0) return false;
    while (i < n && (size_t)w >= iov[i].iov_len) {
      w -= (ssize_t)iov[i].iov_len;
      i++;
    }
    if (i < n && w > 0) {
      iov[i].iov_base = (char*)iov[i].iov_base + w;
      iov[i].iov_len -= (size_t)w;
    }
  }
  return true;
}

// Batched submission (DESIGN.md §28): a pipelined run of piece GETs
// already buffered in `acc` is served as ONE gather-write burst —
// headers and bodies interleaved in a single sendmsg — instead of a
// head+sendfile syscall pair per piece.  Only the happy path batches:
// any request that is not a plain keep-alive piece GET, or any piece
// that is missing/unverified, sends the whole run back to the
// per-request path so error semantics (404/500/503 ordering) stay
// byte-identical with the Python server.  Returns the number of
// requests consumed, 0 when the normal path should take over, -1 on a
// send failure (caller drops the connection).
int try_piece_batch(HttpServer* srv, int fd, std::string& acc) {
  // kBatchMax/kBatchBytesMax caps: see the shared-constants block.
  // Pieces past the byte cap stay in `acc` for the next iteration —
  // they re-batch or ride the per-request sendfile path.
  struct PieceReq {
    std::string task;
    uint32_t number;
    size_t head_len;
  };
  std::vector<PieceReq> reqs;
  size_t pos = 0;
  while (reqs.size() < kBatchMax) {
    size_t head_end = acc.find("\r\n\r\n", pos);
    if (head_end == std::string::npos) break;
    size_t head_len = head_end + 4 - pos;
    size_t line_end = acc.find("\r\n", pos);
    std::string line = acc.substr(pos, line_end - pos);
    std::string lower = acc.substr(pos, head_len);
    for (auto& c : lower) c = (char)tolower(c);
    if (lower.find("connection: close") != std::string::npos) break;
    if (line.rfind("GET /pieces/", 0) != 0) break;
    size_t sp = line.find(' ', 4);
    if (sp == std::string::npos) break;
    std::string path = line.substr(4, sp - 4);
    if (path.find('?') != std::string::npos) break;
    std::string rest = path.substr(8);
    size_t slash = rest.find('/');
    int64_t number = -1;
    if (slash == std::string::npos ||
        !parse_i64(rest.substr(slash + 1), &number) ||
        !valid_task_id(rest.substr(0, slash)))
      break;
    reqs.push_back({rest.substr(0, slash), (uint32_t)number, head_len});
    pos += head_len;
  }
  if (reqs.size() < 2) return 0;
  PieceStore* ps = get_store(srv->store_handle);
  if (!ps) return 0;
  // A burst occupies ONE data-plane slot (it is one continuous write on
  // one connection); over the cap the per-request path owns the 503s.
  if (srv->active.fetch_add(1) >= srv->limit) {
    srv->active.fetch_sub(1);
    return 0;
  }
  struct Entry {
    PieceMeta pm;
    TaskPtr ts;
  };
  std::vector<Entry> entries;
  for (auto& r : reqs) {
    TaskPtr ts = open_task(ps, r.task.c_str(), 0, 0, false);
    PieceMeta pm{};
    bool found = false;
    if (ts) {
      std::lock_guard<std::mutex> lk(ts->mu);
      auto it = ts->pieces.find(r.number);
      if (it != ts->pieces.end() && !ts->closed) {
        pm = it->second;
        found = true;
      }
    }
    if (!found || !piece_verified(ts.get(), pm)) {
      srv->active.fetch_sub(1);
      return 0;
    }
    entries.push_back({pm, ts});
  }
  // Trim to the longest prefix under the byte cap (sizes are only known
  // after the meta lookups above); under 2 the batch gains nothing.
  size_t keep = 0;
  int64_t total = 0;
  while (keep < entries.size() &&
         total + (int64_t)entries[keep].pm.length <= kBatchBytesMax) {
    total += entries[keep].pm.length;
    keep++;
  }
  if (keep < 2) {
    srv->active.fetch_sub(1);
    return 0;
  }
  entries.resize(keep);
  reqs.resize(keep);
  std::vector<uint8_t> scratch((size_t)total);
  std::vector<std::string> heads(entries.size());
  size_t off = 0;
  for (size_t i = 0; i < entries.size(); i++) {
    int dfd = dup_data_fd(entries[i].ts.get());
    bool ok = dfd >= 0;
    if (ok) {
      int64_t got = 0;
      while (got < (int64_t)entries[i].pm.length) {
        ssize_t n = pread(dfd, scratch.data() + off + got,
                          (size_t)(entries[i].pm.length - got),
                          (off_t)(entries[i].pm.offset + got));
        if (n <= 0) {
          ok = false;
          break;
        }
        got += n;
      }
      close(dfd);
    }
    if (!ok) {
      srv->active.fetch_sub(1);
      return 0;
    }
    char h[256];
    int n = snprintf(h, sizeof(h),
                     "HTTP/1.1 200 OK\r\n"
                     "Content-Type: application/octet-stream\r\n"
                     "Content-Length: %u\r\n\r\n",
                     entries[i].pm.length);
    heads[i].assign(h, (size_t)n);
    off += entries[i].pm.length;
  }
  std::vector<iovec> iov(entries.size() * 2);
  off = 0;
  for (size_t i = 0; i < entries.size(); i++) {
    iov[2 * i].iov_base = (void*)heads[i].data();
    iov[2 * i].iov_len = heads[i].size();
    iov[2 * i + 1].iov_base = scratch.data() + off;
    iov[2 * i + 1].iov_len = entries[i].pm.length;
    off += entries[i].pm.length;
  }
  bool sent = sendv_all(fd, iov.data(), iov.size());
  srv->active.fetch_sub(1);
  if (!sent) return -1;
  srv->pieces_served.fetch_add((int64_t)entries.size());
  srv->bytes_served.fetch_add(total);
  srv->batched_pieces.fetch_add((int64_t)entries.size());
  size_t consumed = 0;
  for (auto& r : reqs) consumed += r.head_len;
  acc.erase(0, consumed);
  return (int)entries.size();
}

void handle_conn(HttpServer* srv, int fd) {
  // Whole serving loop inside a catch-all (DF021): one hostile request
  // that lands a bad_alloc (oversized batch staging, header churn) must
  // cost THIS connection, never std::terminate the embedding daemon.
  // The shared cleanup below the try runs on every exit path, so the
  // conns map / conn_count accounting that ps_serve_stop joins on stays
  // exact.
  try {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::string acc;
  while (!srv->stopping.load() && read_request(fd, acc)) {
    // Batched-submission fast path first: a pipelined run of piece GETs
    // goes out as one gather-write burst.
    int batched = try_piece_batch(srv, fd, acc);
    if (batched < 0) break;
    if (batched > 0) continue;
    // Consume exactly one request head (GETs carry no body); residual
    // bytes stay in `acc` for the next iteration (pipelining).
    size_t head_end = acc.find("\r\n\r\n");
    std::string req = acc.substr(0, head_end + 4);
    acc.erase(0, head_end + 4);

    size_t line_end = req.find("\r\n");
    std::string line = req.substr(0, line_end);
    bool keep_alive = true;
    std::string range;
    {
      size_t pos = line_end + 2;
      while (pos < req.size()) {
        size_t e = req.find("\r\n", pos);
        if (e == std::string::npos || e == pos) break;
        std::string h = req.substr(pos, e - pos);
        for (size_t i = 0; i < h.size() && h[i] != ':'; i++)
          h[i] = (char)tolower(h[i]);
        if (h.rfind("range:", 0) == 0) {
          range = h.substr(6);
          while (!range.empty() && range.front() == ' ') range.erase(0, 1);
        } else if (h.rfind("connection:", 0) == 0 &&
                   h.find("close") != std::string::npos) {
          keep_alive = false;
        }
        pos = e + 2;
      }
    }
    if (line.rfind("GET ", 0) != 0) {
      send_error_http(fd, 405, "Method Not Allowed");
      break;
    }
    size_t sp = line.find(' ', 4);
    std::string path = line.substr(4, sp - 4);
    std::string query;
    size_t qpos = path.find('?');
    if (qpos != std::string::npos) {
      query = path.substr(qpos + 1);
      path = path.substr(0, qpos);
    }

    PieceStore* ps = get_store(srv->store_handle);
    // The 503 cap protects the DATA plane (sendfile piece/range bodies).
    // Bitmap requests — including long-poll subscriptions that PARK for
    // up to 30 s — do not count: a swarm of starved children parked on a
    // busy seed must not consume its piece-serving slots (they are still
    // bounded by the per-connection threads).
    bool metadata = path.rfind("/tasks/", 0) == 0 &&
                    path.size() >= 7 &&
                    path.rfind("/pieces") == path.size() - 7;
    if (!ps || (!metadata && srv->active.fetch_add(1) >= srv->limit)) {
      if (ps && !metadata) srv->active.fetch_sub(1);
      send_error_http(fd, 503, "Busy");
      if (!keep_alive || !ps) break;
      continue;
    }

    bool ok_conn = true;
    if (path.rfind("/pieces/", 0) == 0) {
      // /pieces/<task>/<n>
      std::string rest = path.substr(8);
      size_t slash = rest.find('/');
      int64_t number = -1;
      if (slash == std::string::npos ||
          !parse_i64(rest.substr(slash + 1), &number) ||
          !valid_task_id(rest.substr(0, slash))) {
        ok_conn = send_error_http(fd, 404, "Not Found");
      } else {
        std::string task = rest.substr(0, slash);
        TaskPtr ts = open_task(ps, task.c_str(), 0, 0, false);
        PieceMeta pm{};
        bool found = false;
        if (ts) {
          std::lock_guard<std::mutex> lk(ts->mu);
          auto it = ts->pieces.find((uint32_t)number);
          if (it != ts->pieces.end() && !ts->closed) {
            pm = it->second;
            found = true;
          }
        }
        int dfd = -1;
        if (!found) {
          ok_conn = send_error_http(fd, 404, "Not Found");
        } else if (!piece_verified(ts.get(), pm)) {
          ok_conn = send_error_http(fd, 500, "Corrupt");
        } else if ((dfd = dup_data_fd(ts.get())) < 0) {
          ok_conn = send_error_http(fd, 404, "Gone");
        } else {
          ok_conn = send_head(fd, 200, "OK", pm.length) &&
                    sendfile_all(fd, dfd, pm.offset, pm.length);
          if (ok_conn) {
            srv->pieces_served.fetch_add(1);
            srv->bytes_served.fetch_add(pm.length);
          }
        }
        if (dfd >= 0) close(dfd);
      }
    } else if (path.rfind("/tasks/", 0) == 0) {
      std::string rest = path.substr(7);
      size_t slash = rest.find('/');
      if (slash != std::string::npos && rest.substr(slash) == "/pieces") {
        std::string task = rest.substr(0, slash);
        // Long-poll subscription (?have=N&wait_ms=M, Python-server wire
        // parity — peertask_piecetask_synchronizer semantics): defer the
        // bitmap until this store holds MORE than N committed pieces, so
        // a child following a mid-download parent sees new pieces as
        // they land.  Bounded at 30 s; re-opens the task each tick so a
        // not-yet-registered task can appear during the window.
        int64_t have = -1, wait_ms = 0;
        parse_query_i64(query, "have", &have);
        parse_query_i64(query, "wait_ms", &wait_ms);
        if (wait_ms > kLongPollMaxMs) wait_ms = kLongPollMaxMs;
        // Long-polls don't consume data-plane slots, but they are not
        // unbounded either: past 4x the serving cap of PARKED pollers,
        // the subscription degrades to an immediate snapshot (clients
        // fall back to interval polling) instead of stacking threads.
        bool parked = false;
        if (wait_ms > 0) {
          parked = true;
          if (srv->meta_active.fetch_add(1) >= srv->limit * 4) wait_ms = 0;
        }
        TaskPtr ts;
        int64_t waited_ms = 0;
        for (;;) {
          ts = valid_task_id(task) ? open_task(ps, task.c_str(), 0, 0, false)
                                   : nullptr;
          int64_t held = 0;
          if (ts) {
            std::lock_guard<std::mutex> lk(ts->mu);
            held = (int64_t)ts->pieces.size();
          }
          if ((ts && held > have) || waited_ms >= wait_ms ||
              srv->stopping.load())
            break;
          usleep(20 * 1000);
          waited_ms += 20;
        }
        if (parked) srv->meta_active.fetch_sub(1);
        int64_t n_pieces =
            (!ts || ts->header.piece_size == 0)
                ? 0
                : (ts->header.content_length + ts->header.piece_size - 1) /
                      (int64_t)ts->header.piece_size;
        if (n_pieces <= 0) {
          // Python-server parity: unknown AND zero-length tasks both 404.
          ok_conn = send_error_http(fd, 404, "Not Found");
        } else {
          std::vector<uint8_t> bm((size_t)n_pieces, 0);
          {
            std::lock_guard<std::mutex> lk(ts->mu);
            for (auto& kv : ts->pieces)
              if (kv.first < n_pieces) bm[kv.first] = 1;
          }
          ok_conn = send_head(fd, 200, "OK", (int64_t)bm.size()) &&
                    send_all(fd, (const char*)bm.data(), bm.size());
        }
      } else if (slash == std::string::npos) {
        // /tasks/<task> with Range (bytes=S-E / S- / -N)
        TaskPtr ts = valid_task_id(rest)
                         ? open_task(ps, rest.c_str(), 0, 0, false)
                         : nullptr;
        int64_t total = ts ? ts->header.content_length : -1;
        uint32_t psz = ts ? ts->header.piece_size : 0;
        int64_t start = -1, end = -1;
        if (ts && total >= 0 && psz > 0 && range.rfind("bytes=", 0) == 0) {
          std::string spec = range.substr(6);
          size_t dash = spec.find('-');
          if (dash != std::string::npos) {
            std::string s = spec.substr(0, dash), e = spec.substr(dash + 1);
            int64_t sv = 0, ev = 0;
            if (s.empty() && parse_i64(e, &ev)) {  // suffix: bytes=-N
              start = total - ev < 0 ? 0 : total - ev;
              end = total - 1;
            } else if (parse_i64(s, &sv)) {
              if (e.empty()) {                     // open end: bytes=S-
                start = sv;
                end = total - 1;
              } else if (parse_i64(e, &ev)) {
                start = sv;
                end = ev;
              }
            }
          }
        }
        // Clamp BEFORE the start/end sanity check: bytes=100-200 on a
        // 10-byte task must 416, not send a negative Content-Length.
        if (end > total - 1) end = total - 1;
        if (start < 0 || end < start) {
          ok_conn = send_error_http(fd, 416, "Range Not Satisfiable");
        } else {
          // Writer invariant: piece n lives at offset n*piece_size, so a
          // byte range maps directly onto the data file — IF every
          // covering piece is committed.
          bool covered = true;
          {
            std::lock_guard<std::mutex> lk(ts->mu);
            if (ts->closed) covered = false;
            for (int64_t n = start / psz; covered && n <= end / psz; n++)
              if (ts->pieces.find((uint32_t)n) == ts->pieces.end())
                covered = false;
          }
          int dfd = -1;
          if (!covered || (dfd = dup_data_fd(ts.get())) < 0) {
            ok_conn = send_error_http(fd, 404, "Not Found");
          } else {
            ok_conn = send_head(fd, 206, "Partial Content", end - start + 1) &&
                      sendfile_all(fd, dfd, start, end - start + 1);
            if (ok_conn) srv->bytes_served.fetch_add(end - start + 1);
          }
          if (dfd >= 0) close(dfd);
        }
      } else {
        ok_conn = send_error_http(fd, 404, "Not Found");
      }
    } else {
      ok_conn = send_error_http(fd, 404, "Not Found");
    }
    if (!metadata) srv->active.fetch_sub(1);
    if (!ok_conn || !keep_alive) break;
  }
  } catch (...) {
    // Contained: the request that threw gets no response (the client
    // sees a dropped connection and retries); the data-plane slot was
    // already released on the normal paths above, and a throw between
    // fetch_add and fetch_sub cannot happen (no allocation in between).
  }
  {
    std::lock_guard<std::mutex> lk(srv->conns_mu);
    srv->conns.erase(fd);
  }
  close(fd);
  srv->conn_count.fetch_sub(1);
}

void accept_loop(HttpServer* srv) try {
  while (!srv->stopping.load()) {
    int fd = accept(srv->lfd, nullptr, nullptr);
    if (fd < 0) {
      if (srv->stopping.load()) return;
      // EMFILE/transient errors: back off instead of pinning a core.
      usleep(10 * 1000);
      continue;
    }
    {
      std::lock_guard<std::mutex> lk(srv->conns_mu);
      srv->conns[fd] = 1;
    }
    srv->conn_count.fetch_add(1);
    std::thread(handle_conn, srv, fd).detach();
  }
} catch (...) {
  // DF021 containment: a std::thread construction failure (EAGAIN under
  // fd/thread pressure) must stop accepting, not terminate the process.
  // ps_serve_stop still joins this thread and closes the listener; the
  // one connection that failed to spawn leaks its fd accounting into
  // conn_count, which stop's bounded grace tolerates.
}

}  // namespace

extern "C" {

// Start serving the store's pieces on host:port (port 0 = ephemeral).
// Returns the bound port, or <0 on error.  One server per store handle.
int64_t ps_serve(int64_t handle, const char* host, uint16_t port, int limit) try {
  // Serialize whole-call: two concurrent ps_serve on one handle must not
  // both pass the duplicate check and leak the loser's live server.
  static std::mutex serve_setup_mu;
  std::lock_guard<std::mutex> setup_lk(serve_setup_mu);
  if (!get_store(handle)) return -1;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    if (g_servers.count(handle)) return -2;
  }
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return -3;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(lfd);
    return -4;
  }
  if (bind(lfd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(lfd, 128) != 0) {
    close(lfd);
    return -5;
  }
  socklen_t alen = sizeof(addr);
  getsockname(lfd, (sockaddr*)&addr, &alen);
  HttpServer* srv = new HttpServer();
  srv->lfd = lfd;
  srv->limit = limit > 0 ? limit : kServeLimitDefault;
  srv->store_handle = handle;
  srv->port = ntohs(addr.sin_port);
  srv->accept_th = std::thread(accept_loop, srv);
  std::lock_guard<std::mutex> lk(g_servers_mu);
  g_servers[handle] = srv;
  return (int64_t)srv->port;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int ps_serve_stop(int64_t handle) try {
  HttpServer* srv;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    auto it = g_servers.find(handle);
    if (it == g_servers.end()) return -1;
    srv = it->second;
    g_servers.erase(it);
  }
  srv->stopping.store(true);
  // shutdown alone wakes the blocked accept(); close only AFTER the join
  // or the fd number can be reused by another thread and accept() would
  // then operate on an unrelated descriptor.
  shutdown(srv->lfd, SHUT_RDWR);
  if (srv->accept_th.joinable()) srv->accept_th.join();
  close(srv->lfd);
  // Wake every connection thread (idle keep-alive recv()s included) and
  // wait for ALL of them to exit — deleting srv with live detached
  // threads is a use-after-free, and ps_close right after would free the
  // store under an in-flight request.
  {
    std::lock_guard<std::mutex> lk(srv->conns_mu);
    for (auto& kv : srv->conns) shutdown(kv.first, SHUT_RDWR);
  }
  for (int i = 0; i < 500 && srv->conn_count.load() > 0; i++)
    usleep(10 * 1000);
  if (srv->conn_count.load() > 0) {
    // A thread is wedged past the 5 s grace: leak the server struct
    // rather than free memory it still references — and COUNT it, so
    // teardowns can assert the condition never happened (ps_leak_stats)
    // instead of scraping stderr.
    g_leaked_servers.fetch_add(1);
    g_leaked_conns.fetch_add(srv->conn_count.load());
    return 1;
  }
  delete srv;
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Extended serving counters: adds the batched-burst piece count and the
// live connection-thread count to ps_serve_stats.
int ps_serve_stats2(int64_t handle, int64_t* pieces, int64_t* bytes,
                    int64_t* batched, int64_t* conns) try {
  std::lock_guard<std::mutex> lk(g_servers_mu);
  auto it = g_servers.find(handle);
  if (it == g_servers.end()) return -1;
  *pieces = it->second->pieces_served.load();
  *bytes = it->second->bytes_served.load();
  *batched = it->second->batched_pieces.load();
  *conns = (int64_t)it->second->conn_count.load();
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Process-wide wedged-shutdown counters (never reset): servers leaked by
// ps_serve_stop past the stop grace, and the stuck connection threads
// they held.  Zero on a healthy run — test/bench teardowns assert it.
int ps_leak_stats(int64_t* servers, int64_t* conns) try {
  *servers = g_leaked_servers.load();
  *conns = g_leaked_conns.load();
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int ps_close(int64_t handle) try {
  // A wedged server (ps_serve_stop → 1: connection threads alive past the
  // grace) still references the store's TaskStore FILE*s — freeing it here
  // would be a use-after-free.  Leak the store alongside the leaked server
  // and report a distinct code; the handle is dead either way.
  if (ps_serve_stop(handle) == 1) {  // no-op (-1) when no server attached
    std::lock_guard<std::mutex> lk(g_stores_mu);
    auto it = g_stores.find(handle);
    if (it != g_stores.end()) g_stores.erase(it);  // counted via ps_leak_stats
    return -2;
  }
  PieceStore* ps;
  {
    std::lock_guard<std::mutex> lk(g_stores_mu);
    auto it = g_stores.find(handle);
    if (it == g_stores.end()) return -1;
    ps = it->second;
    g_stores.erase(it);
  }
  {
    // Scope the guard: deleting ps while holding ps->mu would unlock a
    // destroyed mutex in the guard's destructor (found by `make tsan`).
    std::lock_guard<std::mutex> lk(ps->mu);
    for (auto& kv : ps->tasks) {
      std::lock_guard<std::mutex> tlk(kv.second->mu);
      if (!kv.second->closed) {
        fclose(kv.second->meta);
        fclose(kv.second->data);
        kv.second->closed = true;
      }
    }
    ps->tasks.clear();
  }
  delete ps;
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

}  // extern "C"

// ---------------------------------------------------------------------------
// In-engine piece fetch loop (pf_*): the CLIENT half of the native data
// plane (DESIGN.md §28).  The Python per-piece loop (conductor fetch_one →
// HTTPPieceFetcher → CommitPipeline) is the semantic spec and stays as the
// byte-identical fallback arm; this engine drains a piece *window* with
// zero Python per-piece overhead:
//
//   worker thread:  pooled keep-alive socket per parent slot → pipelined
//   GET burst (up to 8 pieces; triggers the server's batched-submission
//   path) → length-check → ps_write_piece (crc + fsync-ordered commit,
//   the same durability contract as every other write) → completion.
//
// Python keeps scheduling OWNERSHIP: it picks parents (slots), submits
// pieces, and drains a bounded completion queue — any non-zero status
// simply puts the piece back into the ordinary Python retry/hedge path.
// Completion records are fixed 24-byte structs so the ctypes drain is one
// memcpy + struct.iter_unpack, not a per-field FFI round-trip.
// ---------------------------------------------------------------------------

namespace {

struct FetchJob {
  std::string task;
  uint32_t number = 0;
  int32_t slot = 0;
  uint32_t expected_len = 0;
};

#pragma pack(push, 1)
struct FetchDone {        // 24 bytes; mirrored by NativePieceFetcher.RECORD
  uint32_t number;
  int32_t status;         // kFetchStatusOk / >0 HTTP / kFetchStatus{Conn,Proto,Commit}
  uint32_t length;
  int32_t slot;
  int64_t cost_ns;
};
#pragma pack(pop)

struct PieceFetcher {
  int64_t store_handle = 0;
  std::string tenant;
  std::mutex mu;
  std::condition_variable cv_jobs, cv_done;
  std::deque<FetchJob> jobs;
  std::deque<FetchDone> done;
  std::vector<std::pair<std::string, uint16_t>> parents;  // slot-indexed
  bool closing = false;
  std::vector<std::thread> workers;
};

// shared_ptr holders (the TaskPtr discipline): a caller blocked inside
// pf_complete's cv_done wait keeps the fetcher alive across a concurrent
// pf_close — close erases the handle, wakes waiters, joins workers, and
// the LAST reference frees.  The conductor happens to use the handle
// single-threaded, but the extern-C ABI makes no such promise.
using FetcherPtr = std::shared_ptr<PieceFetcher>;

std::mutex g_fetchers_mu;
std::map<int64_t, FetcherPtr> g_fetchers;

FetcherPtr get_fetcher(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_fetchers_mu);
  auto it = g_fetchers.find(handle);
  return it == g_fetchers.end() ? nullptr : it->second;
}

int64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int connect_parent(const std::string& ip, uint16_t port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, ip.c_str(), &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  // Non-blocking connect with a bounded poll: a black-holed parent must
  // cost a worker at most this dial timeout, not the kernel's minutes-
  // long SYN retry ladder — pf_close joins workers, so an unbounded
  // connect here would stall the conductor's `finally: fetcher.close()`
  // long past piece_wait_timeout_s.
  int flags = fcntl(fd, F_GETFL, 0);
  if (flags < 0 || fcntl(fd, F_SETFL, flags | O_NONBLOCK) != 0) {
    close(fd);
    return -1;
  }
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    if (errno != EINPROGRESS) {
      close(fd);
      return -1;
    }
    pollfd pfd{fd, POLLOUT, 0};
    int err = 0;
    socklen_t elen = sizeof(err);
    if (poll(&pfd, 1, 5000) != 1 ||
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) != 0 || err != 0) {
      close(fd);
      return -1;
    }
  }
  fcntl(fd, F_SETFL, flags);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // A wedged parent must park a worker for at most the recv timeout —
  // Python owns rescheduling, it just needs the error completion.
  timeval tv{30, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  return fd;
}

// One HTTP response (head + Content-Length body) off a keep-alive client
// socket.  Residual bytes persist in `acc` across calls so pipelined
// responses are never dropped.  Returns the HTTP status with the body in
// *body, or <0 on socket/protocol error.  `expected_len` (when > 0)
// bounds the body allocation up front; error bodies still get a small
// floor so a verbose 404/503 page doesn't masquerade as -2.
int read_response(int fd, std::string& acc, std::string* body,
                  uint32_t expected_len) {
  char buf[65536];
  size_t head_end;
  while ((head_end = acc.find("\r\n\r\n")) == std::string::npos) {
    if (acc.size() > 65536) return kFetchStatusProto;
    ssize_t n = recv(fd, buf, sizeof(buf), 0);
    if (n <= 0) return kFetchStatusConn;
    acc.append(buf, (size_t)n);
  }
  std::string head = acc.substr(0, head_end + 4);
  acc.erase(0, head_end + 4);
  if (head.rfind("HTTP/1.", 0) != 0 || head.size() < 12)
    return kFetchStatusProto;
  int status = atoi(head.c_str() + 9);
  if (status < 100) return kFetchStatusProto;
  std::string lower = head;
  for (auto& c : lower) c = (char)tolower(c);
  size_t p = lower.find("content-length:");
  int64_t clen = -1;
  if (p != std::string::npos) {
    size_t e = lower.find("\r\n", p);
    std::string v = head.substr(p + 15, e - p - 15);
    while (!v.empty() && v.front() == ' ') v.erase(0, 1);
    if (!parse_i64(v, &clen)) return kFetchStatusProto;
  }
  if (clen < 0) return kFetchStatusProto;
  int64_t cap = expected_len > 0
                    ? std::max<int64_t>(expected_len, 64 * 1024)
                    : kMaxFetchBody;
  if (clen > cap) return kFetchStatusProto;
  // Bulk path: splice whatever body bytes already rode in with the head,
  // then recv the remainder straight into the body buffer — one copy per
  // byte instead of append+assign, and length-capped reads never overshoot
  // into the next pipelined response (overshoot stays in the socket).
  size_t have = acc.size() > (size_t)clen ? (size_t)clen : acc.size();
  body->resize((size_t)clen);
  if (have) memcpy(&(*body)[0], acc.data(), have);
  acc.erase(0, have);
  size_t got = have;
  while ((int64_t)got < clen) {
    ssize_t n = recv(fd, &(*body)[got], (size_t)clen - got, 0);
    if (n <= 0) return kFetchStatusConn;
    got += (size_t)n;
  }
  return status;
}

void fetch_worker(PieceFetcher* pf) {
  // Worker-local keep-alive sockets, one per parent slot — the pooled
  // reuse that makes a piece fetch cost ~one syscall pair, plus the
  // residual-byte accumulator that makes pipelining safe.
  std::map<int32_t, int> socks;
  std::map<int32_t, std::string> residual;
  // Whole drain loop inside a catch-all (DF021): the per-burst handler
  // below already converts a throwing burst into error completions, so
  // this outer net only catches allocation failure in the loop plumbing
  // itself — the worker exits (sockets still closed below) and pf_close
  // discards its queued jobs as kFetchStatusConn completions.
  try {
  for (;;) {
    std::vector<FetchJob> burst;
    {
      std::unique_lock<std::mutex> lk(pf->mu);
      pf->cv_jobs.wait(lk, [&] { return pf->closing || !pf->jobs.empty(); });
      if (pf->closing) {
        // Close DISCARDS the queue: each queued job becomes a -1
        // completion (Python's submitted-minus-drained ledger stays
        // balanced for any concurrent pf_complete) and only in-flight
        // bursts finish — pf_close joins workers, so fetching a whole
        // backlog from a wedged parent here would stall the conductor's
        // `finally: fetcher.close()` for minutes after the window's
        // deadline already fired.
        while (!pf->jobs.empty()) {
          FetchJob& j = pf->jobs.front();
          pf->done.push_back({j.number, kFetchStatusConn, 0, j.slot, 0});
          pf->jobs.pop_front();
        }
        pf->cv_done.notify_all();
        break;
      }
      burst.push_back(std::move(pf->jobs.front()));
      pf->jobs.pop_front();
      // Opportunistic pipelining: pull queued jobs bound for the SAME
      // parent+task into one request burst (up to kFetchBurstMax) —
      // back-to-back GETs on one socket are what trigger the server's
      // batched submission.  Byte-capped at kBatchBytesMax: a burst
      // serializes its responses on ONE connection, so big pieces must
      // spread across workers instead (an 8 x 4 MiB burst on one socket
      // idles the other workers and LOSES to the parallel Python arm);
      // unknown-size pieces never pipeline.
      int64_t burst_bytes = burst[0].expected_len;
      for (auto it = pf->jobs.begin();
           it != pf->jobs.end() && burst.size() < kFetchBurstMax &&
           burst[0].expected_len > 0 && burst_bytes < kBatchBytesMax;) {
        if (it->slot == burst[0].slot && it->task == burst[0].task &&
            it->expected_len > 0 &&
            burst_bytes + it->expected_len <= kBatchBytesMax) {
          burst_bytes += it->expected_len;
          burst.push_back(std::move(*it));
          it = pf->jobs.erase(it);
        } else {
          ++it;
        }
      }
    }
    int32_t slot = burst[0].slot;
    int64_t t0 = now_ns();
    size_t completed = 0;  // completions already pushed for this burst
    auto fail_rest = [&](int32_t status) {
      std::lock_guard<std::mutex> lk(pf->mu);
      while (completed < burst.size()) {
        pf->done.push_back(
            {burst[completed].number, status, 0, slot, now_ns() - t0});
        completed++;
      }
    };
    // Every job in the burst completes exactly once, even on a C++
    // exception: an exception escaping a std::thread entry would
    // std::terminate the whole daemon, so one bad peer response must
    // cost error completions (Python reschedules), never the process.
    try {
      std::string ip;
      uint16_t port = 0;
      {
        std::lock_guard<std::mutex> lk(pf->mu);
        if (slot >= 0 && (size_t)slot < pf->parents.size()) {
          ip = pf->parents[slot].first;
          port = pf->parents[slot].second;
        }
      }
      if (ip.empty() || port == 0) {
        fail_rest(kFetchStatusConn);
        pf->cv_done.notify_all();
        continue;
      }
      // Send the whole burst; one reconnect retry covers a parent having
      // dropped the idle pooled socket between windows (same shape as the
      // Python pool's retry_call(attempts=2)).
      bool sent = false;
      for (int attempt = 0; attempt < 2 && !sent; attempt++) {
        auto it = socks.find(slot);
        if (it == socks.end() || it->second < 0) {
          int nfd = connect_parent(ip, port);
          socks[slot] = nfd;
          residual[slot].clear();
          if (nfd < 0) break;
        }
        std::string reqs;
        for (auto& b : burst) {
          char req[512];
          int n = snprintf(req, sizeof(req),
                           "GET /pieces/%s/%u HTTP/1.1\r\n"
                           "Host: %s:%u\r\n"
                           "X-Dragonfly-Tenant: %s\r\n\r\n",
                           b.task.c_str(), b.number, ip.c_str(),
                           (unsigned)port, pf->tenant.c_str());
          reqs.append(req, (size_t)n);
        }
        if (send_all(socks[slot], reqs.data(), reqs.size())) {
          sent = true;
        } else {
          close(socks[slot]);
          socks[slot] = -1;
        }
      }
      if (!sent) {
        fail_rest(kFetchStatusConn);
        pf->cv_done.notify_all();
        continue;
      }
      // Read responses in order; commit each good body through the same
      // crc+fsync write path every other commit uses.
      for (size_t i = 0; i < burst.size(); i++) {
        std::string body;
        int status = read_response(socks[slot], residual[slot], &body,
                                   burst[i].expected_len);
        if (status < 0) {
          close(socks[slot]);
          socks[slot] = -1;
          fail_rest(status);
          break;
        }
        FetchDone d{burst[i].number, kFetchStatusOk, 0, slot, 0};
        if (status != 200) {
          d.status = status;
        } else if (burst[i].expected_len > 0 &&
                   body.size() != burst[i].expected_len) {
          d.status = kFetchStatusProto;
        } else {
          int64_t wrote = ps_write_piece(
              pf->store_handle, burst[i].task.c_str(), burst[i].number,
              (const uint8_t*)body.data(), (uint32_t)body.size());
          d.status = wrote < 0 ? kFetchStatusCommit : kFetchStatusOk;
          d.length = (uint32_t)body.size();
        }
        d.cost_ns = now_ns() - t0;
        {
          std::lock_guard<std::mutex> lk(pf->mu);
          pf->done.push_back(d);
        }
        completed++;
      }
      pf->cv_done.notify_all();
    } catch (...) {
      // The socket's stream position is unknown mid-exception: drop it
      // so the next burst starts on a clean connection.
      auto it = socks.find(slot);
      if (it != socks.end() && it->second >= 0) {
        close(it->second);
        it->second = -1;
      }
      fail_rest(kFetchStatusProto);
      pf->cv_done.notify_all();
    }
  }
  } catch (...) {
    // Last-resort containment; see the comment above the loop.
  }
  for (auto& kv : socks)
    if (kv.second >= 0) close(kv.second);
}

}  // namespace

extern "C" {

// Open a fetch engine bound to a local piece store.  `workers` threads
// drain the submit queue; `tenant` rides every request as the
// X-Dragonfly-Tenant header (requester-pays upload accounting, §26/§28).
int64_t pf_open(int64_t store_handle, int workers, const char* tenant) try {
  if (!get_store(store_handle)) return -1;
  if (workers <= 0) workers = kFetchWorkersDefault;
  if (workers > kFetchWorkersMax) workers = kFetchWorkersMax;
  FetcherPtr pf = std::make_shared<PieceFetcher>();
  pf->store_handle = store_handle;
  pf->tenant = tenant ? tenant : "";
  // Raw pointer is safe: pf_close joins the workers while still holding
  // a reference, so the object outlives every worker thread.
  for (int i = 0; i < workers; i++)
    pf->workers.emplace_back(fetch_worker, pf.get());
  std::lock_guard<std::mutex> lk(g_fetchers_mu);
  int64_t h = g_next_handle++;
  g_fetchers[h] = pf;
  return h;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Register/replace the parent endpoint behind `slot` (Python owns parent
// selection; slots keep the per-piece submit free of string churn).
int pf_parent(int64_t fh, int slot, const char* ip, uint16_t port) try {
  FetcherPtr pf = get_fetcher(fh);
  if (!pf || slot < 0 || slot > kParentSlotMax || !ip) return -1;
  std::lock_guard<std::mutex> lk(pf->mu);
  if ((size_t)slot >= pf->parents.size()) pf->parents.resize((size_t)slot + 1);
  pf->parents[(size_t)slot] = {ip, port};
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int pf_submit(int64_t fh, const char* task_id, int slot, uint32_t number,
              uint32_t expected_len) try {
  FetcherPtr pf = get_fetcher(fh);
  if (!pf || !task_id) return -1;
  {
    std::lock_guard<std::mutex> lk(pf->mu);
    if (pf->closing) return -2;
    pf->jobs.push_back({task_id, number, slot, expected_len});
  }
  pf->cv_jobs.notify_one();
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Drain up to `max_records` completions into `out` (packed FetchDone
// records).  Blocks up to timeout_ms for the first one; 0 on timeout.
int pf_complete(int64_t fh, uint8_t* out, int max_records, int timeout_ms) try {
  FetcherPtr pf = get_fetcher(fh);
  if (!pf || !out || max_records <= 0) return -1;
  std::unique_lock<std::mutex> lk(pf->mu);
  // `closing` in the predicate: a concurrent pf_close wakes this waiter
  // immediately (it drains whatever landed) instead of parking it for
  // the full timeout on an object about to go away.
  //
  // system_clock wait_until, NOT wait_for: libstdc++'s steady-clock
  // timed waits compile to pthread_cond_clockwait, which this
  // toolchain's libtsan does not intercept — TSAN then misses the
  // unlock inside the wait and every later report in the run is
  // poisoned (spurious double-lock/data-race).  The system-clock path
  // uses the intercepted pthread_cond_timedwait; a wall-clock jump can
  // only stretch/cut one bounded drain timeout, which callers retry.
  pf->cv_done.wait_until(
      lk,
      std::chrono::system_clock::now() +
          std::chrono::milliseconds(timeout_ms),
      [&] { return pf->closing || !pf->done.empty(); });
  if (pf->done.empty()) return 0;
  int n = 0;
  while (n < max_records && !pf->done.empty()) {
    memcpy(out + (size_t)n * sizeof(FetchDone), &pf->done.front(),
           sizeof(FetchDone));
    pf->done.pop_front();
    n++;
  }
  return n;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Jobs not yet completed (queued + in flight is Python's submitted-minus-
// drained count; this exposes just the queue for diagnostics).
int64_t pf_pending(int64_t fh) try {
  FetcherPtr pf = get_fetcher(fh);
  if (!pf) return -1;
  std::lock_guard<std::mutex> lk(pf->mu);
  return (int64_t)pf->jobs.size();
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Discard queued jobs (each becomes a -1 completion; in-flight bursts
// finish), join workers, release the handle.  The object itself is
// freed by the last shared_ptr holder — a racing pf_complete keeps it
// alive past this return.
int pf_close(int64_t fh) try {
  FetcherPtr pf;
  {
    std::lock_guard<std::mutex> lk(g_fetchers_mu);
    auto it = g_fetchers.find(fh);
    if (it == g_fetchers.end()) return -1;
    pf = it->second;
    g_fetchers.erase(it);
  }
  {
    std::lock_guard<std::mutex> lk(pf->mu);
    pf->closing = true;
  }
  pf->cv_jobs.notify_all();
  pf->cv_done.notify_all();
  for (auto& t : pf->workers)
    if (t.joinable()) t.join();
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Online ingest engine (oi_*): the wire→trainer hot path of the online graph
// trainer.  Semantics mirror trainer/online_graph.py WireIngestAdapter — that
// Python class is the spec (bucket→dense-id first-come mapping, TTL eviction
// + id recycling, host-feature accumulation, bounded edge ring with
// backpressure) — but the whole per-chunk pass runs here without the GIL:
// the measured ceiling of the composed wire-fed loop was the single Python
// consumer process (BENCHMARKS.md bottleneck ledger), not any one stage.
//
// Parity notes (asserted in tests/test_native_ingest.py):
//  * id assignment is per-chunk sorted-unique over BOTH endpoint columns in
//    one call — byte-identical mappings to the Python adapter for the same
//    arrival order;
//  * feature accumulation credits parent cols [2+H, 2+2H) to src and child
//    cols [2, 2+H) to dst (records.features.accumulate_host_feature_sums);
//    unlike Python's sampled fold it accumulates EVERY kept row (C++ can
//    afford it; means only converge harder);
//  * eviction runs under the engine lock with the caller-supplied clock, so
//    injectable-clock tests drive both implementations identically.
// ---------------------------------------------------------------------------

namespace {

struct OnlineIngest {
  int32_t num_nodes = 0;
  int64_t n_buckets = 0;
  int32_t feat_dim = 0;
  int32_t row_width = 0;
  double ttl = 0.0;

  std::mutex mu;
  std::condition_variable cv_space;  // feeders wait for ring room
  std::condition_variable cv_data;   // taker waits for enough edges

  std::vector<int32_t> id_table;   // [n_buckets]  -2 unseen, -1 overflow
  std::vector<int64_t> bucket_of;  // [num_nodes]  -1 free
  std::vector<double> last_seen;   // [num_nodes]
  std::vector<int32_t> free_ids;   // recycled ids, pop from back
  int32_t next_id = 0;
  double last_scan = -1e300;
  int64_t overflow_edges = 0;
  int64_t evicted_nodes = 0;
  int64_t rows_in = 0;
  std::vector<int32_t> pending_recycle;

  // double internally: the engine folds EVERY kept row (no sampling),
  // so a hot node passes float32's 2^24 integer ceiling within hours at
  // wire rate and `cnt += 1.0f` would silently freeze the mean.  The
  // ABI (export/node_features) stays float32 — the shared state format.
  std::vector<double> feat_sum;  // [num_nodes * feat_dim]
  std::vector<double> feat_cnt;  // [num_nodes]

  int64_t cap = 0;  // edge ring capacity
  std::vector<int32_t> ring_src, ring_dst;
  std::vector<float> ring_y;
  int64_t head = 0, size = 0;
  bool eof = false;
  bool closed = false;

  std::vector<int32_t> ids_scratch;
  std::vector<int64_t> new_scratch;
  // Per-chunk staging (reused; the engine mutex serializes feeders).
  std::vector<float> cols_scratch;
  std::vector<int32_t> st_src, st_dst;
  std::vector<float> st_y;
};

using IngestPtr = std::shared_ptr<OnlineIngest>;

std::mutex g_oi_mu;
std::map<int64_t, IngestPtr> g_oi;
int64_t g_oi_next = 1;

// shared_ptr copy: callers blocked inside the engine (cv waits) keep it
// alive across a concurrent oi_destroy — destroy unmaps + wakes, the
// last user frees (the TSAN gate caught the raw-pointer version).
IngestPtr oi_get(int64_t h) {
  std::lock_guard<std::mutex> lk(g_oi_mu);
  auto it = g_oi.find(h);
  return it == g_oi.end() ? nullptr : it->second;
}

// Reclaim ids silent past ttl (trainer/online_graph.py _evict_expired):
// throttled full scan; frees mapping + accumulators, queues the row reset.
// Caller holds e->mu.
int64_t oi_evict_locked(OnlineIngest* e, double now) {
  if (e->ttl <= 0 || now - e->last_scan < e->ttl * 0.25) return 0;
  e->last_scan = now;
  int64_t k = 0;
  for (int32_t id = 0; id < e->num_nodes; id++) {
    if (e->bucket_of[id] < 0 || now - e->last_seen[id] <= e->ttl) continue;
    e->id_table[e->bucket_of[id]] = -2;
    e->bucket_of[id] = -1;
    std::fill_n(&e->feat_sum[(int64_t)id * e->feat_dim], e->feat_dim, 0.0);
    e->feat_cnt[id] = 0.0;
    e->free_ids.push_back(id);
    e->pending_recycle.push_back(id);
    k++;
  }
  if (k) {
    e->evicted_nodes += k;
    // Un-memoize overflow buckets: dropped hosts may claim freed ids.
    for (int64_t b = 0; b < e->n_buckets; b++)
      if (e->id_table[b] == -1) e->id_table[b] = -2;
  }
  return k;
}

// bucket → dense id over one flat column (trainer/online_graph.py _map_ids):
// touch-before-evict, sorted-unique allocation, in-loop eviction retry.
// Out-of-range buckets map to -1 (hostile wire input must not fault).
// Caller holds e->mu.
void oi_map_locked(OnlineIngest* e, const float* buckets, int64_t n,
                   double now, int32_t* out) {
  bool any_unseen = false, any_dropped = false;
  for (int64_t i = 0; i < n; i++) {
    int64_t b = (int64_t)buckets[i];
    int32_t v = (b < 0 || b >= e->n_buckets) ? -1 : e->id_table[b];
    out[i] = v;
    if (v == -2) any_unseen = true;
    if (v == -1) any_dropped = true;
    if (e->ttl > 0 && v >= 0) e->last_seen[v] = now;
  }
  if (!any_unseen && !(e->ttl > 0 && any_dropped)) return;
  if (e->free_ids.empty() && e->next_id >= e->num_nodes) {
    if (oi_evict_locked(e, now) > 0) {
      for (int64_t i = 0; i < n; i++) {
        int64_t b = (int64_t)buckets[i];
        out[i] = (b < 0 || b >= e->n_buckets) ? -1 : e->id_table[b];
      }
    }
  }
  e->new_scratch.clear();
  for (int64_t i = 0; i < n; i++)
    if (out[i] == -2) e->new_scratch.push_back((int64_t)buckets[i]);
  std::sort(e->new_scratch.begin(), e->new_scratch.end());
  e->new_scratch.erase(
      std::unique(e->new_scratch.begin(), e->new_scratch.end()),
      e->new_scratch.end());
  for (int64_t nb : e->new_scratch) {
    if (e->id_table[nb] != -2) continue;
    if (e->free_ids.empty() && e->next_id >= e->num_nodes)
      oi_evict_locked(e, now);  // pool drained mid-chunk; throttled
    int32_t nid;
    if (!e->free_ids.empty()) {
      nid = e->free_ids.back();
      e->free_ids.pop_back();
    } else if (e->next_id < e->num_nodes) {
      nid = e->next_id++;
    } else {
      e->id_table[nb] = -1;
      continue;
    }
    e->id_table[nb] = nid;
    e->bucket_of[nid] = nb;
    e->last_seen[nid] = now;
  }
  for (int64_t i = 0; i < n; i++) {
    int64_t b = (int64_t)buckets[i];
    out[i] = (b < 0 || b >= e->n_buckets) ? -1 : e->id_table[b];
  }
}

}  // namespace

extern "C" {

int64_t oi_create(int32_t num_nodes, int64_t n_buckets, int32_t feat_dim,
                  int32_t row_width, double ttl, int64_t ring_cap) try {
  if (num_nodes <= 0 || n_buckets <= 0 || feat_dim <= 0 ||
      row_width < 2 + 2 * feat_dim + 1 || ring_cap <= 0)
    return -1;
  auto e = std::make_shared<OnlineIngest>();
  e->num_nodes = num_nodes;
  e->n_buckets = n_buckets;
  e->feat_dim = feat_dim;
  e->row_width = row_width;
  e->ttl = ttl;
  e->id_table.assign(n_buckets, -2);
  e->bucket_of.assign(num_nodes, -1);
  e->last_seen.assign(num_nodes, 0.0);
  e->feat_sum.assign((int64_t)num_nodes * feat_dim, 0.0);
  e->feat_cnt.assign(num_nodes, 0.0);
  e->cap = ring_cap;
  e->ring_src.resize(ring_cap);
  e->ring_dst.resize(ring_cap);
  e->ring_y.resize(ring_cap);
  std::lock_guard<std::mutex> lk(g_oi_mu);
  int64_t h = g_oi_next++;
  g_oi[h] = e;
  return h;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Map + accumulate + ring-append one chunk of download rows ([n, row_width]
// float32, src bucket col 0, dst col 1, target last col).  Blocks for ring
// space (backpressure) when block != 0.  Returns edges kept (overflow rows
// dropped+counted), -1 on bad handle / closed.
int64_t oi_feed_download_rows(int64_t h, const float* rows, int64_t n,
                              double now, int32_t block) try {
  IngestPtr e = oi_get(h);
  if (!e || n < 0) return -1;
  if (n == 0) return 0;
  std::unique_lock<std::mutex> lk(e->mu);
  if (e->closed) return -1;
  const int32_t w = e->row_width, H = e->feat_dim;
  e->ids_scratch.resize(2 * n);
  // ONE mapping pass over both endpoint columns (gathered strided →
  // flat), matching the Python adapter's combined call: every host in
  // the chunk is touched before any eviction can reclaim it.
  e->cols_scratch.resize(2 * n);
  float* cols = e->cols_scratch.data();
  for (int64_t i = 0; i < n; i++) {
    cols[i] = rows[i * w];
    cols[n + i] = rows[i * w + 1];
  }
  oi_map_locked(e.get(), cols, 2 * n, now, e->ids_scratch.data());
  // Pass 1 (atomic with the mapping — the Python spec's _mu scope):
  // feature credit + edge staging.  No cv waits happen in here, so a
  // concurrent eviction during backpressure can't recycle an id between
  // its mapping and its feature credit.
  auto& st_src = e->st_src;
  auto& st_dst = e->st_dst;
  auto& st_y = e->st_y;
  st_src.clear();
  st_dst.clear();
  st_y.clear();
  for (int64_t i = 0; i < n; i++) {
    int32_t s = e->ids_scratch[i], d = e->ids_scratch[n + i];
    if (s < 0 || d < 0) {
      e->overflow_edges++;
      continue;
    }
    const float* r = rows + i * w;
    e->feat_cnt[s] += 1.0;
    e->feat_cnt[d] += 1.0;
    double* fs = &e->feat_sum[(int64_t)s * H];
    double* fd = &e->feat_sum[(int64_t)d * H];
    for (int32_t j = 0; j < H; j++) {
      fs[j] += r[2 + H + j];  // parent cols credit src
      fd[j] += r[2 + j];      // child cols credit dst
    }
    st_src.push_back(s);
    st_dst.push_back(d);
    st_y.push_back(r[w - 1]);
  }
  e->rows_in += n;
  // Pass 2: ring append with backpressure.  Edges staged here may still
  // reference an id evicted while we wait — the documented aliasing
  // window, identical to the Python queue path.
  int64_t kept = 0;
  for (size_t i = 0; i < st_src.size(); i++) {
    while (e->size >= e->cap) {
      if (!block || e->closed) {
        // Staged edges that no longer fit are LOST (their features were
        // already credited; re-feeding would double-count) — account
        // them so kept + overflow == rows always holds.
        e->overflow_edges += (int64_t)(st_src.size() - i);
        e->cv_data.notify_all();
        return e->closed ? -1 : kept;
      }
      e->cv_space.wait(lk);
    }
    int64_t tail = (e->head + e->size) % e->cap;
    e->ring_src[tail] = st_src[i];
    e->ring_dst[tail] = st_dst[i];
    e->ring_y[tail] = st_y[i];
    e->size++;
    kept++;
    if ((kept & 0xFFF) == 0) e->cv_data.notify_all();
  }
  e->cv_data.notify_all();
  return kept;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Topology-path mapping (probe edges don't carry host features); same
// allocation/touch semantics as the download path.
int32_t oi_map_buckets(int64_t h, const float* buckets, int64_t n, double now,
                       int32_t* out) try {
  IngestPtr e = oi_get(h);
  if (!e) return -1;
  std::lock_guard<std::mutex> lk(e->mu);
  oi_map_locked(e.get(), buckets, n, now, out);
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Read-only probe (tests/diagnostics): current mapping, no allocation.
int32_t oi_lookup(int64_t h, const float* buckets, int64_t n, int32_t* out) try {
  IngestPtr e = oi_get(h);
  if (!e) return -1;
  std::lock_guard<std::mutex> lk(e->mu);
  for (int64_t i = 0; i < n; i++) {
    int64_t b = (int64_t)buckets[i];
    out[i] = (b < 0 || b >= e->n_buckets) ? -1 : e->id_table[b];
  }
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// All-or-nothing dispatch block: copies exactly `need` edges once enough
// have accumulated; 0 on timeout/eof-with-partial (the partial stays for
// a later taker — same leftover semantics as the Python queue path).
int64_t oi_take_edges(int64_t h, int64_t need, int32_t* src, int32_t* dst,
                      float* y, int64_t timeout_ms) try {
  IngestPtr e = oi_get(h);
  if (!e || need <= 0 || need > e->cap) return -1;
  std::unique_lock<std::mutex> lk(e->mu);
  // The timeout is an IDLE timeout (the Python queue path renews it per
  // arriving chunk): any progress since the last wake resets the clock,
  // so slow-but-steady ingest never ends the run mid-stream.
  // system_clock (not steady): keeps the wait on the TSAN-intercepted
  // pthread_cond_timedwait — see pf_complete for the full story.
  auto deadline = std::chrono::system_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  int64_t last_size = e->size;
  while (e->size < need && !e->eof && !e->closed) {
    if (e->size != last_size) {
      last_size = e->size;
      deadline = std::chrono::system_clock::now() +
                 std::chrono::milliseconds(timeout_ms);
    }
    if (e->cv_data.wait_until(lk, deadline) == std::cv_status::timeout) {
      if (e->size != last_size) continue;  // progress raced the timeout
      break;
    }
  }
  if (e->size < need) return 0;
  int64_t first = std::min(need, e->cap - e->head);
  memcpy(src, &e->ring_src[e->head], sizeof(int32_t) * first);
  memcpy(dst, &e->ring_dst[e->head], sizeof(int32_t) * first);
  memcpy(y, &e->ring_y[e->head], sizeof(float) * first);
  if (first < need) {
    memcpy(src + first, &e->ring_src[0], sizeof(int32_t) * (need - first));
    memcpy(dst + first, &e->ring_dst[0], sizeof(int32_t) * (need - first));
    memcpy(y + first, &e->ring_y[0], sizeof(float) * (need - first));
  }
  e->head = (e->head + need) % e->cap;
  e->size -= need;
  e->cv_space.notify_all();
  return need;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

void oi_eof(int64_t h) try {
  IngestPtr e = oi_get(h);
  if (!e) return;
  std::lock_guard<std::mutex> lk(e->mu);
  e->eof = true;
  e->cv_data.notify_all();
} catch (...) {
  // DF021: never unwind through the C boundary.
}

int32_t oi_node_features(int64_t h, float* out) try {
  IngestPtr e = oi_get(h);
  if (!e) return -1;
  std::lock_guard<std::mutex> lk(e->mu);
  for (int32_t id = 0; id < e->num_nodes; id++) {
    double c = e->feat_cnt[id] > 1.0 ? e->feat_cnt[id] : 1.0;
    const double* s = &e->feat_sum[(int64_t)id * e->feat_dim];
    float* o = out + (int64_t)id * e->feat_dim;
    for (int32_t j = 0; j < e->feat_dim; j++) o[j] = (float)(s[j] / c);
  }
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t oi_take_recycled(int64_t h, int32_t* out, int64_t cap) try {
  IngestPtr e = oi_get(h);
  if (!e) return -1;
  std::lock_guard<std::mutex> lk(e->mu);
  int64_t k = std::min<int64_t>(cap, e->pending_recycle.size());
  if (k > 0) memcpy(out, e->pending_recycle.data(), sizeof(int32_t) * k);
  e->pending_recycle.erase(e->pending_recycle.begin(),
                           e->pending_recycle.begin() + k);
  return k;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int64_t oi_pending_recycled(int64_t h) try {
  IngestPtr e = oi_get(h);
  if (!e) return -1;
  std::lock_guard<std::mutex> lk(e->mu);
  return (int64_t)e->pending_recycle.size();
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int32_t oi_stats(int64_t h, int64_t* overflow, int64_t* evicted,
                 int64_t* next_id, int64_t* rows_in) try {
  IngestPtr e = oi_get(h);
  if (!e) return -1;
  std::lock_guard<std::mutex> lk(e->mu);
  *overflow = e->overflow_edges;
  *evicted = e->evicted_nodes;
  *next_id = e->next_id;
  *rows_in = e->rows_in;
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

// Checkpoint export: refuses (-1) while recycled ids await their row reset
// — the trainer drains + applies, then retries, so a saved mapping can
// never outrun its embedding resets.  Returns the free-list length.
int64_t oi_export_state(int64_t h, int32_t* id_table, int64_t* bucket_of,
                        double* last_seen, int32_t* free_out, int64_t free_cap,
                        float* feat_sum, float* feat_cnt, int64_t* scalars) try {
  IngestPtr e = oi_get(h);
  if (!e) return -3;
  std::lock_guard<std::mutex> lk(e->mu);
  if (!e->pending_recycle.empty()) return -1;
  if ((int64_t)e->free_ids.size() > free_cap) return -2;
  memcpy(id_table, e->id_table.data(), sizeof(int32_t) * e->n_buckets);
  memcpy(bucket_of, e->bucket_of.data(), sizeof(int64_t) * e->num_nodes);
  memcpy(last_seen, e->last_seen.data(), sizeof(double) * e->num_nodes);
  if (!e->free_ids.empty())
    memcpy(free_out, e->free_ids.data(),
           sizeof(int32_t) * e->free_ids.size());
  for (int64_t i = 0; i < (int64_t)e->num_nodes * e->feat_dim; i++)
    feat_sum[i] = (float)e->feat_sum[i];
  for (int32_t i = 0; i < e->num_nodes; i++)
    feat_cnt[i] = (float)e->feat_cnt[i];
  scalars[0] = e->next_id;
  scalars[1] = e->overflow_edges;
  scalars[2] = e->evicted_nodes;
  return (int64_t)e->free_ids.size();
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int32_t oi_import_state(int64_t h, const int32_t* id_table,
                        const int64_t* bucket_of, const double* last_seen,
                        const int32_t* free_in, int64_t free_len,
                        const float* feat_sum, const float* feat_cnt,
                        int64_t next_id, int64_t overflow, int64_t evicted) try {
  IngestPtr e = oi_get(h);
  if (!e) return -1;
  std::lock_guard<std::mutex> lk(e->mu);
  // Value validation: restored ids become raw indices later — a corrupt
  // checkpoint must fail cleanly here, not heap-corrupt in the hot path.
  if (next_id < 0 || next_id > e->num_nodes || free_len > e->num_nodes)
    return -2;
  for (int64_t i = 0; i < free_len; i++)
    if (free_in[i] < 0 || free_in[i] >= e->num_nodes) return -2;
  for (int32_t i = 0; i < e->num_nodes; i++)
    if (bucket_of[i] < -1 || bucket_of[i] >= e->n_buckets) return -2;
  for (int64_t b = 0; b < e->n_buckets; b++)
    if (id_table[b] < -2 || id_table[b] >= e->num_nodes) return -2;
  memcpy(e->id_table.data(), id_table, sizeof(int32_t) * e->n_buckets);
  memcpy(e->bucket_of.data(), bucket_of, sizeof(int64_t) * e->num_nodes);
  memcpy(e->last_seen.data(), last_seen, sizeof(double) * e->num_nodes);
  e->free_ids.assign(free_in, free_in + (free_len > 0 ? free_len : 0));
  for (int64_t i = 0; i < (int64_t)e->num_nodes * e->feat_dim; i++)
    e->feat_sum[i] = feat_sum[i];
  for (int32_t i = 0; i < e->num_nodes; i++)
    e->feat_cnt[i] = feat_cnt[i];
  e->next_id = (int32_t)next_id;
  e->overflow_edges = overflow;
  e->evicted_nodes = evicted;
  e->pending_recycle.clear();
  e->last_scan = -1e300;
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

int32_t oi_destroy(int64_t h) try {
  IngestPtr e;
  {
    std::lock_guard<std::mutex> lk(g_oi_mu);
    auto it = g_oi.find(h);
    if (it == g_oi.end()) return -1;
    e = it->second;
    g_oi.erase(it);
  }
  {
    std::lock_guard<std::mutex> lk(e->mu);
    e->closed = true;
    e->cv_data.notify_all();
    e->cv_space.notify_all();
  }
  // Blocked feeders/takers hold their own shared_ptr; the engine frees
  // when the last of them returns.
  return 0;
} catch (...) {
  return kAbiTrap;  // DF021: never unwind through the C boundary
}

}  // extern "C"

// ---------------------------------------------------------------------------
// ABI manifest witness (DESIGN.md §30).
//
// DF_ABI_EXPORTS is the X-macro table of every exported symbol in the
// canonical type vocabulary shared with records/abi_contracts.py
// (i32/i64/u16/u32/f64/cstr/u8p/f32p/i32p/i64p/f64p/void; const dropped).
// It is expanded twice:
//
//  * compile time — a static_assert per symbol pins the REAL prototype
//    (via decltype) to the table entry, so the table cannot drift from
//    the definitions it describes;
//  * df_abi_manifest() — emits canonical JSON (sorted keys, compact
//    separators, the exact bytes of Python's
//    json.dumps(..., sort_keys=True, separators=(",", ":"))) carrying
//    the prototype table, compiler-computed sizeof/offsetof of every
//    packed record, and the shared-constant values.  utils/dfabi.py
//    renders the same JSON from the registry; tests/test_zz_abiwitness
//    requires the two byte-equal, so a compiler/padding surprise fails
//    even when both source texts agree.
//
// df_abi_probe_fetchdone() additionally round-trips a sentinel FetchDone
// record through the real struct layout (memcpy of the compiled struct,
// not a re-statement of offsets).
// ---------------------------------------------------------------------------

#define DF_ABI_EXPORTS(X)                                                    \
  X(i64, re_open, cstr, cstr, u32)                                           \
  X(i64, re_append, i64, f32p, i64)                                          \
  X(i32, re_flush, i64)                                                      \
  X(i64, re_rows, i64)                                                       \
  X(i32, re_close, i64)                                                      \
  X(i64, ps_open, cstr)                                                      \
  X(i32, ps_create_task, i64, cstr, u32, i64)                                \
  X(i32, ps_load_task, i64, cstr)                                            \
  X(i64, ps_write_piece, i64, cstr, u32, u8p, u32)                           \
  X(i64, ps_read_piece, i64, cstr, u32, u8p, u32, i32)                       \
  X(i64, ps_piece_count, i64, cstr)                                          \
  X(i32, ps_piece_bitmap, i64, cstr, u8p, u32)                               \
  X(i64, ps_task_bytes, i64, cstr)                                           \
  X(i64, ps_content_length, i64, cstr)                                       \
  X(i64, ps_piece_size, i64, cstr)                                           \
  X(i32, ps_delete_task, i64, cstr)                                          \
  X(i64, ps_serve, i64, cstr, u16, i32)                                      \
  X(i32, ps_serve_stop, i64)                                                 \
  X(i32, ps_serve_stats2, i64, i64p, i64p, i64p, i64p)                       \
  X(i32, ps_leak_stats, i64p, i64p)                                          \
  X(i32, ps_close, i64)                                                      \
  X(i64, pf_open, i64, i32, cstr)                                            \
  X(i32, pf_parent, i64, i32, cstr, u16)                                     \
  X(i32, pf_submit, i64, cstr, i32, u32, u32)                                \
  X(i32, pf_complete, i64, u8p, i32, i32)                                    \
  X(i64, pf_pending, i64)                                                    \
  X(i32, pf_close, i64)                                                      \
  X(i64, oi_create, i32, i64, i32, i32, f64, i64)                            \
  X(i64, oi_feed_download_rows, i64, f32p, i64, f64, i32)                    \
  X(i32, oi_map_buckets, i64, f32p, i64, f64, i32p)                          \
  X(i32, oi_lookup, i64, f32p, i64, i32p)                                    \
  X(i64, oi_take_edges, i64, i64, i32p, i32p, f32p, i64)                     \
  X(void, oi_eof, i64)                                                       \
  X(i32, oi_node_features, i64, f32p)                                        \
  X(i64, oi_take_recycled, i64, i32p, i64)                                   \
  X(i64, oi_pending_recycled, i64)                                           \
  X(i32, oi_stats, i64, i64p, i64p, i64p, i64p)                              \
  X(i64, oi_export_state, i64, i32p, i64p, f64p, i32p, i64, f32p, f32p,      \
    i64p)                                                                    \
  X(i32, oi_import_state, i64, i32p, i64p, f64p, i32p, i64, f32p, f32p,      \
    i64, i64, i64)                                                           \
  X(i32, oi_destroy, i64)                                                    \
  X(cstr, df_abi_manifest)                                                   \
  X(i32, df_abi_probe_fetchdone, u8p, u32)

// Shared integer constants re-emitted by the manifest (the string magics
// kMagic/kTaskMagic are added by hand below — different JSON rendering).
#define DF_ABI_CONSTANTS(X)                                                  \
  X(kAbiTrap) X(kBatchBytesMax) X(kBatchMax) X(kFetchBurstMax)               \
  X(kFetchStatusCommit) X(kFetchStatusConn) X(kFetchStatusOk)                \
  X(kFetchStatusProto) X(kFetchWorkersDefault) X(kFetchWorkersMax)           \
  X(kLongPollMaxMs) X(kMaxFetchBody) X(kParentSlotMax)                       \
  X(kPieceFlagCommitted) X(kPieceFlagVerified) X(kServeLimitDefault)

namespace dfabi {

// Canonical type vocabulary.  Pointer aliases are spelled without const;
// norm_fn below drops const from the real prototypes before comparison,
// so `const float*` in a definition still matches f32p.
using i32 = int32_t;
using i64 = int64_t;
using u16 = uint16_t;
using u32 = uint32_t;
using f64 = double;
using cstr = const char*;
using u8p = uint8_t*;
using f32p = float*;
using i32p = int32_t*;
using i64p = int64_t*;
using f64p = double*;

template <typename T>
struct norm_t {
  using type = T;
};
template <typename T>
struct norm_t<const T*> {
  using type = T*;
};
template <typename F>
struct norm_fn;
template <typename R, typename... A>
struct norm_fn<R (*)(A...)> {
  using type = typename norm_t<R>::type (*)(typename norm_t<A>::type...);
};

// {"k":v,...} from pre-rendered JSON values; std::map iterates sorted,
// which IS the canonical key order.
inline std::string json_obj(const std::map<std::string, std::string>& m) {
  std::string s = "{";
  bool first = true;
  for (const auto& kv : m) {
    if (!first) s += ",";
    first = false;
    s += "\"";
    s += kv.first;
    s += "\":";
    s += kv.second;
  }
  s += "}";
  return s;
}

// ["ret","arg",...] from the stringified X-macro entry ("i64, cstr, u32").
inline std::string json_sig(const char* ret, const char* args) {
  std::string s = "[\"";
  s += ret;
  s += "\"";
  std::string a(args);
  size_t i = 0;
  while (i < a.size()) {
    while (i < a.size() && (a[i] == ' ' || a[i] == ',')) i++;
    size_t j = i;
    while (j < a.size() && a[j] != ',' && a[j] != ' ') j++;
    if (j > i) {
      s += ",\"";
      s.append(a, i, j - i);
      s += "\"";
    }
    i = j;
  }
  s += "]";
  return s;
}

struct FieldInfo {
  const char* name;
  long long off;
  long long size;
};

// {"fields":[["name",off,size],...],"size":N} — field order is layout
// order, NOT sorted; "fields" < "size" keeps the object keys canonical.
inline std::string json_record(const FieldInfo* f, size_t n,
                               long long total) {
  std::string s = "{\"fields\":[";
  for (size_t i = 0; i < n; i++) {
    if (i) s += ",";
    s += "[\"";
    s += f[i].name;
    s += "\",";
    s += std::to_string(f[i].off);
    s += ",";
    s += std::to_string(f[i].size);
    s += "]";
  }
  s += "],\"size\":";
  s += std::to_string(total);
  s += "}";
  return s;
}

#define DF_ABI_FIELD(rec_t, fld)                                   \
  {#fld, (long long)offsetof(rec_t, fld),                          \
   (long long)sizeof(((rec_t*)nullptr)->fld)}

inline const std::string& manifest_json() {
  static const std::string out = [] {
    std::map<std::string, std::string> exports;
#define DF_ABI_EXPORT_JSON(ret, name, ...) \
  exports[#name] = json_sig(#ret, "" #__VA_ARGS__);
    DF_ABI_EXPORTS(DF_ABI_EXPORT_JSON)
#undef DF_ABI_EXPORT_JSON

    std::map<std::string, std::string> constants;
#define DF_ABI_CONST_JSON(name) \
  constants[#name] = std::to_string((long long)(name));
    DF_ABI_CONSTANTS(DF_ABI_CONST_JSON)
#undef DF_ABI_CONST_JSON
    constants["kMagic"] = std::string("\"") + kMagic + "\"";
    constants["kTaskMagic"] = std::string("\"") + kTaskMagic + "\"";

    std::map<std::string, std::string> records;
    {
      const FieldInfo f[] = {
          DF_ABI_FIELD(FetchDone, number), DF_ABI_FIELD(FetchDone, status),
          DF_ABI_FIELD(FetchDone, length), DF_ABI_FIELD(FetchDone, slot),
          DF_ABI_FIELD(FetchDone, cost_ns),
      };
      records["FetchDone"] = json_record(f, 5, (long long)sizeof(FetchDone));
    }
    {
      const FieldInfo f[] = {
          DF_ABI_FIELD(PieceMeta, number), DF_ABI_FIELD(PieceMeta, length),
          DF_ABI_FIELD(PieceMeta, offset), DF_ABI_FIELD(PieceMeta, crc),
          DF_ABI_FIELD(PieceMeta, flags),
      };
      records["PieceMeta"] = json_record(f, 5, (long long)sizeof(PieceMeta));
    }
    {
      const FieldInfo f[] = {
          DF_ABI_FIELD(TaskHeader, magic),
          DF_ABI_FIELD(TaskHeader, piece_size),
          DF_ABI_FIELD(TaskHeader, content_length),
      };
      records["TaskHeader"] =
          json_record(f, 3, (long long)sizeof(TaskHeader));
    }

    std::string s = "{\"constants\":";
    s += json_obj(constants);
    s += ",\"exports\":";
    s += json_obj(exports);
    s += ",\"records\":";
    s += json_obj(records);
    s += ",\"version\":1}";
    return s;
  }();
  return out;
}

}  // namespace dfabi

extern "C" {

// Self-description of the compiled ABI surface (canonical JSON; see the
// section comment).  The string is owned by a function-local static —
// valid for the life of the process, never freed through the ABI.
const char* df_abi_manifest() try {
  return dfabi::manifest_json().c_str();
} catch (...) {
  return nullptr;
}

// Fill `out` with a sentinel FetchDone record (memcpy of the compiled
// struct): every field carries a distinguishable value so the ctypes
// side can prove its unpack format reads each field from the right
// bytes.  Returns sizeof(FetchDone), or -1 when out_len is short.
int32_t df_abi_probe_fetchdone(uint8_t* out, uint32_t out_len) try {
  if (!out || out_len < sizeof(FetchDone)) return -1;
  FetchDone d{};
  d.number = 0xA1B2C3D4u;
  d.status = kFetchStatusProto;  // a real status constant crosses too
  d.length = 0x00C0FFEEu;
  d.slot = -7;
  d.cost_ns = 0x0102030405060708LL;
  memcpy(out, &d, sizeof(FetchDone));
  return (int32_t)sizeof(FetchDone);
} catch (...) {
  return kAbiTrap;
}

}  // extern "C"

// Compile-time prototype pinning: the table cannot drift from the real
// definitions (a changed parameter type here is a build break naming the
// symbol, before any test runs).
namespace dfabi {
#define DF_ABI_ASSERT(ret, name, ...)                                    \
  static_assert(                                                         \
      std::is_same<norm_fn<decltype(&::name)>::type,                     \
                   norm_fn<ret (*)(__VA_ARGS__)>::type>::value,          \
      "ABI drift: " #name " does not match the DF_ABI_EXPORTS table");
DF_ABI_EXPORTS(DF_ABI_ASSERT)
#undef DF_ABI_ASSERT
}  // namespace dfabi
