"""ctypes bindings for the native (C++) runtime.

``load()`` builds the shared library on first use (g++ via the Makefile —
pybind11 isn't available in this image, and ctypes keeps the ABI surface
explicit).  Services treat native as an optimization: ``available()``
gates it, and the Python implementations (records/columnar.py) remain the
spec & fallback.
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import subprocess
import threading
from typing import Optional

import numpy as np

from ..records import abi_contracts as _abi

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libdragonfly_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None

# Shared engine constants, sourced from the ABI registry so the Python
# side can never restate a value the C++ side has moved away from
# (records/abi_contracts.py is the single source; DF020 pins both sides
# to it).
BATCH_MAX = _abi.constant("kBatchMax")
BATCH_BYTES_MAX = _abi.constant("kBatchBytesMax")
FETCH_BURST_MAX = _abi.constant("kFetchBurstMax")
MAX_FETCH_BODY = _abi.constant("kMaxFetchBody")


def _declare(lib: ctypes.CDLL) -> None:
    i64, u32, i32 = ctypes.c_int64, ctypes.c_uint32, ctypes.c_int
    lib.re_open.restype = i64
    lib.re_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p, u32]
    lib.re_append.restype = i64
    lib.re_append.argtypes = [i64, ctypes.POINTER(ctypes.c_float), i64]
    lib.re_flush.restype = i32
    lib.re_flush.argtypes = [i64]
    lib.re_rows.restype = i64
    lib.re_rows.argtypes = [i64]
    lib.re_close.restype = i32
    lib.re_close.argtypes = [i64]

    p8 = ctypes.POINTER(ctypes.c_uint8)
    lib.ps_open.restype = i64
    lib.ps_open.argtypes = [ctypes.c_char_p]
    lib.ps_create_task.restype = i32
    lib.ps_create_task.argtypes = [i64, ctypes.c_char_p, u32, i64]
    lib.ps_load_task.restype = i32
    lib.ps_load_task.argtypes = [i64, ctypes.c_char_p]
    lib.ps_write_piece.restype = i64
    lib.ps_write_piece.argtypes = [i64, ctypes.c_char_p, u32, p8, u32]
    lib.ps_read_piece.restype = i64
    lib.ps_read_piece.argtypes = [i64, ctypes.c_char_p, u32, p8, u32, i32]
    lib.ps_piece_count.restype = i64
    lib.ps_piece_count.argtypes = [i64, ctypes.c_char_p]
    lib.ps_piece_bitmap.restype = i32
    lib.ps_piece_bitmap.argtypes = [i64, ctypes.c_char_p, p8, u32]
    lib.ps_task_bytes.restype = i64
    lib.ps_task_bytes.argtypes = [i64, ctypes.c_char_p]
    lib.ps_piece_size.restype = i64
    lib.ps_piece_size.argtypes = [i64, ctypes.c_char_p]
    lib.ps_content_length.restype = i64
    lib.ps_content_length.argtypes = [i64, ctypes.c_char_p]
    lib.ps_delete_task.restype = i32
    lib.ps_delete_task.argtypes = [i64, ctypes.c_char_p]
    lib.ps_close.restype = i32
    lib.ps_close.argtypes = [i64]
    lib.ps_serve.restype = i64
    lib.ps_serve.argtypes = [i64, ctypes.c_char_p, ctypes.c_uint16, i32]
    lib.ps_serve_stop.restype = i32
    lib.ps_serve_stop.argtypes = [i64]
    lib.ps_serve_stats2.restype = i32
    lib.ps_serve_stats2.argtypes = [
        i64, ctypes.POINTER(i64), ctypes.POINTER(i64),
        ctypes.POINTER(i64), ctypes.POINTER(i64)
    ]
    lib.ps_leak_stats.restype = i32
    lib.ps_leak_stats.argtypes = [ctypes.POINTER(i64), ctypes.POINTER(i64)]

    lib.pf_open.restype = i64
    lib.pf_open.argtypes = [i64, i32, ctypes.c_char_p]
    lib.pf_parent.restype = i32
    lib.pf_parent.argtypes = [i64, i32, ctypes.c_char_p, ctypes.c_uint16]
    lib.pf_submit.restype = i32
    lib.pf_submit.argtypes = [i64, ctypes.c_char_p, i32, u32, u32]
    lib.pf_complete.restype = i32
    lib.pf_complete.argtypes = [i64, p8, i32, i32]
    lib.pf_pending.restype = i64
    lib.pf_pending.argtypes = [i64]
    lib.pf_close.restype = i32
    lib.pf_close.argtypes = [i64]

    f32p = ctypes.POINTER(ctypes.c_float)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(i64)
    f64p = ctypes.POINTER(ctypes.c_double)
    dbl = ctypes.c_double
    lib.oi_create.restype = i64
    lib.oi_create.argtypes = [ctypes.c_int32, i64, ctypes.c_int32,
                              ctypes.c_int32, dbl, i64]
    lib.oi_destroy.restype = i32
    lib.oi_destroy.argtypes = [i64]
    lib.oi_feed_download_rows.restype = i64
    lib.oi_feed_download_rows.argtypes = [i64, f32p, i64, dbl, i32]
    lib.oi_map_buckets.restype = i32
    lib.oi_map_buckets.argtypes = [i64, f32p, i64, dbl, i32p]
    lib.oi_lookup.restype = i32
    lib.oi_lookup.argtypes = [i64, f32p, i64, i32p]
    lib.oi_take_edges.restype = i64
    lib.oi_take_edges.argtypes = [i64, i64, i32p, i32p, f32p, i64]
    lib.oi_eof.restype = None
    lib.oi_eof.argtypes = [i64]
    lib.oi_node_features.restype = i32
    lib.oi_node_features.argtypes = [i64, f32p]
    lib.oi_take_recycled.restype = i64
    lib.oi_take_recycled.argtypes = [i64, i32p, i64]
    lib.oi_pending_recycled.restype = i64
    lib.oi_pending_recycled.argtypes = [i64]
    lib.oi_stats.restype = i32
    lib.oi_stats.argtypes = [i64, i64p, i64p, i64p, i64p]
    lib.oi_export_state.restype = i64
    lib.oi_export_state.argtypes = [i64, i32p, i64p, f64p, i32p, i64,
                                    f32p, f32p, i64p]
    lib.oi_import_state.restype = i32
    lib.oi_import_state.argtypes = [i64, i32p, i64p, f64p, i32p, i64,
                                    f32p, f32p, i64, i64, i64]

    # ABI manifest witness (DESIGN.md §30): df_abi_manifest returns a
    # process-lifetime static string — c_char_p is safe (no free).
    lib.df_abi_manifest.restype = ctypes.c_char_p
    lib.df_abi_manifest.argtypes = []
    lib.df_abi_probe_fetchdone.restype = ctypes.c_int32
    lib.df_abi_probe_fetchdone.argtypes = [p8, u32]


def load(rebuild: bool = False) -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native library; None on failure."""
    global _lib, _build_error
    with _lock:
        if _lib is not None and not rebuild:
            return _lib
        if _build_error is not None and not rebuild:
            return None
        if rebuild or not os.path.exists(_LIB_PATH):
            try:
                subprocess.run(
                    ["make", "-C", _DIR, "-s"] + (["clean", "all"] if rebuild else []),
                    check=True,
                    capture_output=True,
                    text=True,
                    timeout=120,
                )
            except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError) as exc:
                _build_error = getattr(exc, "stderr", None) or str(exc)
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
        except AttributeError:
            # A prebuilt .so from an older source tree lacks newer
            # symbols — rebuild once instead of breaking the silent
            # fallback for every native consumer.
            if rebuild:
                _build_error = "stale library persists after rebuild"
                return None
            try:
                subprocess.run(
                    ["make", "-C", _DIR, "-s", "clean", "all"],
                    check=True, capture_output=True, text=True, timeout=120,
                )
                lib = ctypes.CDLL(_LIB_PATH)
                _declare(lib)
            except Exception as exc:  # noqa: BLE001 — fallback gate
                _build_error = getattr(exc, "stderr", None) or str(exc)
                return None
        except OSError as exc:
            _build_error = str(exc)
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def build_error() -> Optional[str]:
    return _build_error


def leaked_servers() -> tuple:
    """(leaked_servers, stuck_conns): process-wide wedged-shutdown counters.

    A ``ps_serve_stop`` that times out past its grace leaks the server
    struct rather than freeing memory live threads still reference; this
    surfaces the count so teardowns can ASSERT it stayed zero instead of
    scraping stderr.  (0, 0) when the library never loaded.
    """
    lib = load()
    if lib is None:
        return (0, 0)
    s = ctypes.c_int64(0)
    c = ctypes.c_int64(0)
    lib.ps_leak_stats(ctypes.byref(s), ctypes.byref(c))
    return (int(s.value), int(c.value))


# ---------------------------------------------------------------------------
# Pythonic wrappers
# ---------------------------------------------------------------------------


class NativeError(RuntimeError):
    pass


class NativeColumnarWriter:
    """Drop-in for records.columnar.ColumnarWriter backed by the C++ engine.

    Same on-disk format — ColumnarReader reads its files unchanged.
    """

    def __init__(self, path: str, columns, dtype: str = "float32"):
        if dtype != "float32":
            raise ValueError("native writer is float32-only")
        lib = load()
        if lib is None:
            raise NativeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self.path = path
        self.columns = tuple(columns)
        if os.path.exists(path) and os.path.getsize(path) > 0:
            # Same contract as the Python writer: appending to an existing
            # shard requires an identical column set (columnar.py:83-86).
            from ..records.columnar import read_header

            existing, _ = read_header(path)
            if existing.columns != self.columns:
                raise ValueError(
                    f"{path}: existing columns {existing.columns} != {self.columns}"
                )
        header = json.dumps(
            {"columns": list(self.columns), "dtype": "float32", "created_at_ns": 0}
        ).encode()
        self._h = lib.re_open(path.encode(), header, len(self.columns))
        if self._h < 0:
            raise NativeError(f"re_open({path}) -> {self._h}")

    def append(self, rows: np.ndarray) -> int:
        rows = np.ascontiguousarray(rows, dtype=np.float32)
        if rows.ndim == 1:
            rows = rows[None, :]
        if rows.shape[-1] != len(self.columns):
            raise ValueError(f"row width {rows.shape[-1]} != {len(self.columns)}")
        n = self._lib.re_append(
            self._h,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows.shape[0],
        )
        if n < 0:
            raise NativeError(f"re_append -> {n}")
        if n != rows.shape[0]:
            # Short write (disk full): silently dropped rows would corrupt
            # the shard for every downstream reader.
            raise NativeError(
                f"re_append wrote {n}/{rows.shape[0]} rows (disk full?)"
            )
        return int(n)

    def flush(self) -> None:
        self._lib.re_flush(self._h)

    def tell_rows(self) -> int:
        return int(self._lib.re_rows(self._h))

    def close(self) -> None:
        if self._h >= 0:
            self._lib.re_close(self._h)
            self._h = -1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativePieceStore:
    """The daemon's local piece store (C++ engine).

    Mirrors client/daemon/storage semantics: per-task metadata+data files,
    crc-verified reads, crash reload (re-open sees committed pieces).
    """

    def __init__(self, root: str):
        lib = load()
        if lib is None:
            raise NativeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self.root = root
        self._h = lib.ps_open(root.encode())
        if self._h < 0:
            raise NativeError(f"ps_open({root}) -> {self._h}")

    def create_task(self, task_id: str, piece_size: int, content_length: int) -> None:
        rc = self._lib.ps_create_task(self._h, task_id.encode(), piece_size, content_length)
        if rc != 0:
            raise NativeError(f"ps_create_task -> {rc}")

    def load_task(self, task_id: str) -> bool:
        """Open an existing task (crash reload); False if absent."""
        return self._lib.ps_load_task(self._h, task_id.encode()) == 0

    def write_piece(self, task_id: str, number: int, data: bytes) -> int:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        n = self._lib.ps_write_piece(self._h, task_id.encode(), number, buf, len(data))
        if n < 0:
            raise NativeError(f"ps_write_piece -> {n}")
        return int(n)

    def piece_size(self, task_id: str) -> int:
        return int(self._lib.ps_piece_size(self._h, task_id.encode()))

    def read_piece(self, task_id: str, number: int, *, max_len: Optional[int] = None, verify: bool = True) -> bytes:
        if max_len is None:
            # A committed piece is never longer than the task's piece size.
            ps = self.piece_size(task_id)
            max_len = ps if ps > 0 else 8 << 20
        buf = (ctypes.c_uint8 * max_len)()
        n = self._lib.ps_read_piece(
            self._h, task_id.encode(), number, buf, max_len, 1 if verify else 0
        )
        if n == -3:
            raise KeyError(f"piece {number} of {task_id} not present")
        if n == -6:
            raise NativeError(f"piece {number} of {task_id} failed crc verification")
        if n < 0:
            raise NativeError(f"ps_read_piece -> {n}")
        # string_at: one memcpy.  Slicing a ctypes array (`buf[:n]`)
        # materializes n Python ints first — measured 98 ms per 4 MiB
        # piece vs 1.8 ms for the whole python-engine read.
        return ctypes.string_at(buf, int(n))

    def piece_count(self, task_id: str) -> int:
        n = self._lib.ps_piece_count(self._h, task_id.encode())
        return max(int(n), 0)

    def piece_bitmap(self, task_id: str, n_pieces: int) -> np.ndarray:
        buf = (ctypes.c_uint8 * n_pieces)()
        rc = self._lib.ps_piece_bitmap(self._h, task_id.encode(), buf, n_pieces)
        if rc != 0:
            raise NativeError(f"ps_piece_bitmap -> {rc}")
        return np.frombuffer(bytes(buf), dtype=np.uint8)

    def task_bytes(self, task_id: str) -> int:
        return max(int(self._lib.ps_task_bytes(self._h, task_id.encode())), 0)

    def content_length(self, task_id: str) -> int:
        return int(self._lib.ps_content_length(self._h, task_id.encode()))

    def delete_task(self, task_id: str) -> None:
        self._lib.ps_delete_task(self._h, task_id.encode())

    def serve(self, host: str = "127.0.0.1", port: int = 0,
              *, concurrent_limit: int = 64) -> int:
        """Start the in-engine HTTP piece server (native.cpp ps_serve):
        piece/bitmap/range GETs served via sendfile, no GIL on the data
        path.  Returns the bound port."""
        p = self._lib.ps_serve(self._h, host.encode(), port, concurrent_limit)
        if p < 0:
            raise NativeError(f"ps_serve -> {p}")
        return int(p)

    def serve_stop(self) -> None:
        self._lib.ps_serve_stop(self._h)

    def serve_stats(self) -> tuple:
        """(pieces_served, bytes_served) while the server runs.

        Narrow view over ``serve_stats_full`` — the legacy two-pointer
        ``ps_serve_stats`` export is gone (one out-pointer list fewer to
        keep in sync with the ABI registry)."""
        full = self.serve_stats_full()
        return full["pieces"], full["bytes"]

    def serve_stats_full(self) -> dict:
        """Extended counters: adds the batched-burst piece count and the
        live connection-thread count (ps_serve_stats2)."""
        vals = [ctypes.c_int64(0) for _ in range(4)]
        rc = self._lib.ps_serve_stats2(
            self._h, *[ctypes.byref(v) for v in vals]
        )
        if rc != 0:
            return {"pieces": 0, "bytes": 0, "batched": 0, "conns": 0}
        return {
            "pieces": int(vals[0].value),
            "bytes": int(vals[1].value),
            "batched": int(vals[2].value),
            "conns": int(vals[3].value),
        }

    def close(self) -> None:
        if self._h >= 0:
            self._lib.ps_close(self._h)
            self._h = -1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativePieceFetcher:
    """The in-engine piece fetch loop (pf_* in native.cpp, DESIGN.md §28).

    Python keeps scheduling ownership — it registers parents into slots,
    submits (piece, slot) pairs, and drains a bounded completion queue;
    the engine runs the pooled keep-alive fetch → length check →
    crc+fsync commit per piece with zero Python per-piece overhead.
    Every non-zero completion status simply returns the piece to the
    ordinary Python retry/hedge path (conductor fetch_one is the spec).
    """

    # native.cpp FetchDone: u32 number, i32 status, u32 length,
    # i32 parent slot, i64 cost_ns — format and size come from the ABI
    # registry (DF020 + the runtime witness pin both to the compiled
    # struct).
    RECORD = _abi.record_format("FetchDone")
    RECORD_SIZE = _abi.record_size("FetchDone")
    MAX_DRAIN = 256

    def __init__(self, store: "NativePieceStore", *, workers: int = 4,
                 tenant: str = ""):
        lib = load()
        if lib is None:
            raise NativeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self._h = lib.pf_open(store._h, workers, tenant.encode())
        if self._h < 0:
            raise NativeError(f"pf_open -> {self._h}")
        self._buf = (ctypes.c_uint8 * (self.RECORD_SIZE * self.MAX_DRAIN))()

    def set_parent(self, slot: int, ip: str, port: int) -> None:
        rc = self._lib.pf_parent(self._h, slot, ip.encode(), port)
        if rc != 0:
            raise NativeError(f"pf_parent({slot}, {ip}:{port}) -> {rc}")

    # dflint: hotpath submit
    def submit(self, task_id: str, slot: int, number: int,
               expected_len: int) -> bool:
        return self._lib.pf_submit(
            self._h, task_id.encode(), slot, number, expected_len
        ) == 0

    # dflint: hotpath complete
    def complete(self, *, timeout_ms: int = 1000) -> list:
        """Drain completions: [(number, status, length, slot, cost_ns)].
        Blocks up to timeout_ms for the first record; [] on timeout."""
        n = self._lib.pf_complete(
            self._h, self._buf, self.MAX_DRAIN, timeout_ms
        )
        if n < 0:
            raise NativeError(f"pf_complete -> {n}")
        return list(struct.iter_unpack(
            self.RECORD, ctypes.string_at(self._buf, n * self.RECORD_SIZE)
        ))

    def pending(self) -> int:
        return max(int(self._lib.pf_pending(self._h)), 0)

    def close(self) -> None:
        """Release the engine handle.  Queued (not yet in-flight) jobs
        are DISCARDED, not fetched — by the time the conductor closes,
        its window deadline has already routed unfinished pieces to the
        Python retry path, so close never stalls on a wedged parent."""
        if self._h >= 0:
            self._lib.pf_close(self._h)
            self._h = -1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _fp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _ip(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _lp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _dp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


class NativeOnlineIngest:
    """The wire→trainer hot path in C++ (oi_* in native.cpp): bucket→id
    mapping with the TTL lifecycle, host-feature accumulation, and the
    dispatch-block edge ring — one GIL-free call per wire chunk.
    ``trainer.online_graph.WireIngestAdapter`` is the semantic spec and
    delegates here when the library is available."""

    def __init__(self, num_nodes: int, n_buckets: int, feat_dim: int,
                 row_width: int, node_ttl: float, ring_capacity: int):
        lib = load()
        if lib is None:
            raise NativeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self.num_nodes = int(num_nodes)
        self.n_buckets = int(n_buckets)
        self.feat_dim = int(feat_dim)
        self.row_width = int(row_width)
        self._h = lib.oi_create(num_nodes, n_buckets, feat_dim, row_width,
                                float(node_ttl), ring_capacity)
        if self._h < 0:
            raise NativeError(f"oi_create -> {self._h}")

    def feed_download_rows(self, rows: np.ndarray, now: float,
                           *, block: bool = True) -> int:
        rows = np.ascontiguousarray(rows, np.float32)
        if rows.ndim != 2 or rows.shape[1] != self.row_width:
            # The engine strides by ITS row_width — a mismatched shape
            # would be an out-of-bounds read, not an error.
            raise NativeError(
                f"rows shape {rows.shape} != [n, {self.row_width}]"
            )
        kept = self._lib.oi_feed_download_rows(
            self._h, _fp(rows), rows.shape[0], now, 1 if block else 0
        )
        if kept < 0:
            raise NativeError(f"oi_feed_download_rows -> {kept}")
        return int(kept)

    def map_buckets(self, buckets: np.ndarray, now: float) -> np.ndarray:
        b = np.ascontiguousarray(buckets, np.float32)
        out = np.empty(len(b), np.int32)
        rc = self._lib.oi_map_buckets(self._h, _fp(b), len(b), now, _ip(out))
        if rc != 0:
            raise NativeError(f"oi_map_buckets -> {rc}")
        return out

    def lookup(self, buckets: np.ndarray) -> np.ndarray:
        """Read-only mapping probe — never allocates ids."""
        b = np.ascontiguousarray(buckets, np.float32)
        out = np.empty(len(b), np.int32)
        rc = self._lib.oi_lookup(self._h, _fp(b), len(b), _ip(out))
        if rc != 0:
            raise NativeError(f"oi_lookup -> {rc}")
        return out

    def take_edges(self, need: int, timeout_s: float):
        """Exactly-`need` edges as (src, dst, y), or None on timeout/EOF
        with fewer than `need` buffered."""
        src = np.empty(need, np.int32)
        dst = np.empty(need, np.int32)
        y = np.empty(need, np.float32)
        got = self._lib.oi_take_edges(
            self._h, need, _ip(src), _ip(dst), _fp(y),
            max(int(timeout_s * 1000), 0),
        )
        if got < 0:
            raise NativeError(f"oi_take_edges -> {got}")
        if got == 0:
            return None
        return src, dst, y

    def eof(self) -> None:
        self._lib.oi_eof(self._h)

    def node_features(self) -> np.ndarray:
        out = np.empty((self.num_nodes, self.feat_dim), np.float32)
        rc = self._lib.oi_node_features(self._h, _fp(out))
        if rc != 0:
            raise NativeError(f"oi_node_features -> {rc}")
        return out

    def take_recycled(self, cap: int = 65536) -> np.ndarray:
        out = np.empty(cap, np.int32)
        n = self._lib.oi_take_recycled(self._h, _ip(out), cap)
        if n < 0:
            raise NativeError(f"oi_take_recycled -> {n}")
        return out[:n].copy()

    def pending_recycled(self) -> int:
        return int(self._lib.oi_pending_recycled(self._h))

    def stats(self) -> dict:
        vals = [ctypes.c_int64(0) for _ in range(4)]
        rc = self._lib.oi_stats(self._h, *[ctypes.byref(v) for v in vals])
        if rc != 0:
            raise NativeError(f"oi_stats -> {rc}")
        return {
            "overflow_edges": vals[0].value,
            "evicted_nodes": vals[1].value,
            "next_id": vals[2].value,
            "rows_in": vals[3].value,
        }

    def export_state(self):
        """Snapshot the mapping for a checkpoint; None while recycled ids
        still await their embedding-row reset (caller drains + retries)."""
        id_table = np.empty(self.n_buckets, np.int32)
        bucket_of = np.empty(self.num_nodes, np.int64)
        last_seen = np.empty(self.num_nodes, np.float64)
        free = np.empty(self.num_nodes, np.int32)
        feat_sum = np.empty((self.num_nodes, self.feat_dim), np.float32)
        feat_cnt = np.empty(self.num_nodes, np.float32)
        scalars = np.zeros(3, np.int64)
        n = self._lib.oi_export_state(
            self._h, _ip(id_table), _lp(bucket_of), _dp(last_seen),
            _ip(free), self.num_nodes, _fp(feat_sum), _fp(feat_cnt),
            _lp(scalars),
        )
        if n == -1:
            return None
        if n < 0:
            raise NativeError(f"oi_export_state -> {n}")
        return {
            "id_table": id_table,
            "bucket_of": bucket_of,
            "last_seen": last_seen,
            "free": free[:n].copy(),
            "feat_sum": feat_sum,
            "feat_cnt": feat_cnt,
            "next_id": int(scalars[0]),
            "overflow_edges": int(scalars[1]),
            "evicted_nodes": int(scalars[2]),
        }

    def import_state(self, id_table, bucket_of, last_seen, free,
                     feat_sum, feat_cnt, next_id, overflow, evicted) -> None:
        id_table = np.ascontiguousarray(id_table, np.int32)
        bucket_of = np.ascontiguousarray(bucket_of, np.int64)
        last_seen = np.ascontiguousarray(last_seen, np.float64)
        free = np.ascontiguousarray(free, np.int32)
        feat_sum = np.ascontiguousarray(feat_sum, np.float32)
        feat_cnt = np.ascontiguousarray(feat_cnt, np.float32)
        # The engine memcpys its OWN sizes out of these buffers — a
        # shape mismatch would be an out-of-bounds read, not an error.
        if (
            len(id_table) != self.n_buckets
            or len(bucket_of) != self.num_nodes
            or len(last_seen) != self.num_nodes
            or len(feat_cnt) != self.num_nodes
            or feat_sum.size != self.num_nodes * self.feat_dim
        ):
            raise NativeError(
                f"import_state shape mismatch: engine has num_nodes="
                f"{self.num_nodes}/n_buckets={self.n_buckets}"
            )
        rc = self._lib.oi_import_state(
            self._h, _ip(id_table), _lp(bucket_of), _dp(last_seen),
            _ip(free), len(free), _fp(feat_sum), _fp(feat_cnt),
            int(next_id), int(overflow), int(evicted),
        )
        if rc != 0:
            raise NativeError(
                f"oi_import_state -> {rc} (corrupt adapter state?)"
            )

    def close(self) -> None:
        if self._h >= 0:
            self._lib.oi_destroy(self._h)
            self._h = -1

    def __del__(self):  # belt & suspenders; close() is the contract
        try:
            self.close()
        except Exception:  # dflint: disable=DF001 — __del__ during
            pass          # interpreter teardown must never raise or log
