"""Back-to-source protocol clients (reference: pkg/source/).

A registry of scheme → client (the reference loads http, s3, oss, hdfs,
oci clients via pkg/source/loader); each client answers content length
and range reads, and ``PieceSourceFetcher`` adapts any client to the
conductor's piece interface.

Shipped clients: ``file`` (local paths; also the e2e fixture transport)
and ``http/https`` (urllib range GETs).  Object-store schemes register at
deploy time the way the reference's plugin loader does.
"""

from .client import (  # noqa: F401
    FileSourceClient,
    HTTPSourceClient,
    PieceSourceFetcher,
    SourceClient,
    SourceRegistry,
    default_registry,
)
