"""Back-to-source protocol clients (reference: pkg/source/).

A registry of scheme → client (the reference loads http, s3, oss, hdfs,
oci clients via pkg/source/loader); each client answers content length
and range reads, and ``PieceSourceFetcher`` adapts any client to the
conductor's piece interface.

Shipped clients: ``file`` (local paths; also the e2e fixture transport),
``http/https`` (urllib range GETs), ``s3`` (SigV4-signed, endpoint-
overridable), ``oss`` (header-signed), ``hdfs`` (WebHDFS REST), and
``oras``/``oci`` (harbor-style token → manifest → blob).  The cloud
schemes need credentials/endpoints, so they register through
``configure_sources`` at deploy time the way the reference's plugin
loader does.
"""

from typing import Optional

from .client import (  # noqa: F401
    FileSourceClient,
    HTTPSourceClient,
    PieceSourceFetcher,
    SourceClient,
    SourceRegistry,
    default_registry,
)
from .hdfs import HDFSSourceClient  # noqa: F401
from .oci import ORASSourceClient  # noqa: F401
from .oss import OSSSourceClient  # noqa: F401
from .s3 import S3SourceClient  # noqa: F401


def configure_sources(
    source_cfg: dict, registry: Optional[SourceRegistry] = None
) -> SourceRegistry:
    """Register cloud scheme clients from a config mapping.

    ``source_cfg`` is the daemon config's ``source:`` section, e.g.::

        source:
          s3:  {access_key: "...", secret_key: "...", region: "...",
                endpoint: "..."}
          oss: {access_key_id: "...", access_key_secret: "...",
                endpoint: "..."}
          hdfs: {user: "hadoop"}
          oras: {auth_header: "Basic ...", insecure_http: false}
    """
    reg = registry or default_registry
    if "s3" in source_cfg:
        reg.register("s3", S3SourceClient(**source_cfg["s3"]))
    if "oss" in source_cfg:
        reg.register("oss", OSSSourceClient(**source_cfg["oss"]))
    if "hdfs" in source_cfg:
        reg.register("hdfs", HDFSSourceClient(**source_cfg["hdfs"]))
    # oras and oci may target different registries with different creds:
    # each block configures its own scheme; a lone block serves both.
    oras_client = (
        ORASSourceClient(**source_cfg["oras"]) if "oras" in source_cfg else None
    )
    oci_client = (
        ORASSourceClient(**source_cfg["oci"]) if "oci" in source_cfg else None
    )
    if oras_client is not None:
        reg.register("oras", oras_client)
        reg.register("oci", oci_client or oras_client)
    elif oci_client is not None:
        reg.register("oci", oci_client)
        reg.register("oras", oci_client)
    return reg
