"""hdfs:// source client over the WebHDFS REST API.

Reference (pkg/source/clients/hdfsprotocol) speaks the native Hadoop RPC
protocol via colinmarc/hdfs.  The TPU build deliberately uses WebHDFS —
plain HTTP with offset/length reads maps 1:1 onto the piece-range access
pattern and needs no protocol library.  URL form stays
``hdfs://<namenode>:<port>/<path>`` with the port interpreted as the
WebHDFS (HTTP) port.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Callable, Optional

from .client import default_transport


class HDFSSourceClient:
    def __init__(
        self,
        *,
        user: str = "",
        timeout: float = 30.0,
        transport: Optional[Callable] = None,
    ) -> None:
        self.user = user
        self.timeout = timeout
        self.transport = transport or default_transport

    def _rest_url(self, url: str, op: str, **params) -> str:
        parsed = urllib.parse.urlsplit(url)
        qs = {"op": op, **params}
        if self.user:
            qs["user.name"] = self.user
        return (
            f"http://{parsed.netloc}/webhdfs/v1"
            f"{urllib.parse.quote(parsed.path)}?{urllib.parse.urlencode(qs)}"
        )

    def content_length(self, url: str) -> int:
        req = urllib.request.Request(self._rest_url(url, "GETFILESTATUS"))
        try:
            with self.transport(req, self.timeout) as resp:
                status = json.loads(resp.read()).get("FileStatus", {})
                return int(status.get("length", -1))
        except (OSError, ValueError):
            # OSError covers URLError/HTTPError AND network-level failures
            # (DNS, connection refused) — all answer "size unknown".
            return -1

    def read_range(self, url: str, start: int, length: int) -> bytes:
        # WebHDFS OPEN redirects namenode→datanode; urllib follows it.
        req = urllib.request.Request(
            self._rest_url(url, "OPEN", offset=start, length=length)
        )
        with self.transport(req, self.timeout) as resp:
            return resp.read()

    def exists(self, url: str) -> bool:
        return self.content_length(url) >= 0
