"""oss:// source client (reference: pkg/source/clients/ossprotocol).

URL form ``oss://<bucket>/<key>`` (ossprotocol uses the aliyun SDK with
per-request endpoint/accessKeyID/accessKeySecret headers).  Signing is
the public OSS header scheme: HMAC-SHA1 over
``VERB\\nContent-MD5\\nContent-Type\\nDate\\n<canonicalized-oss-headers>
<canonicalized-resource>`` carried as ``Authorization: OSS <id>:<sig>``.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
import urllib.parse
import urllib.request
from email.utils import formatdate
from typing import Callable, Optional

from .client import RangedHTTPClient, default_transport


def sign_oss(
    secret: str,
    method: str,
    *,
    date: str,
    bucket: str,
    key: str,
    content_md5: str = "",
    content_type: str = "",
    oss_headers: Optional[dict] = None,
    resource: Optional[str] = None,
    header_prefix: str = "x-oss-",
) -> str:
    """``resource`` overrides the default ``/{bucket}/{key}`` canonical
    resource — service-level requests (list buckets) sign the bare "/"
    that the bucket/key form cannot express.  ``header_prefix`` selects
    the vendor header namespace: Huawei OBS uses the SAME HMAC-SHA1
    canonical scheme with ``x-obs-`` headers (one signer for both,
    objectstorage.go:179-212 dispatch parity)."""
    canon_headers = ""
    if oss_headers:
        lower = {
            k.lower(): v for k, v in oss_headers.items()
            if k.lower().startswith(header_prefix)
        }
        canon_headers = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))
    if resource is None:
        resource = f"/{bucket}/{key}"
    to_sign = (
        f"{method}\n{content_md5}\n{content_type}\n{date}\n"
        f"{canon_headers}{resource}"
    )
    mac = hmac.new(secret.encode(), to_sign.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


class OSSSourceClient(RangedHTTPClient):
    def __init__(
        self,
        *,
        access_key_id: str = "",
        access_key_secret: str = "",
        endpoint: str = "",
        timeout: float = 30.0,
        transport: Optional[Callable] = None,
    ) -> None:
        self.access_key_id = access_key_id
        self.access_key_secret = access_key_secret
        # e.g. "http://127.0.0.1:9001" (fixture) or
        # "https://oss-cn-hangzhou.aliyuncs.com"
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout
        self.transport = transport or default_transport

    def _request(self, url: str, method: str, extra_headers=None):
        parsed = urllib.parse.urlsplit(url)
        bucket, key = parsed.netloc, parsed.path.lstrip("/")
        http_url = f"{self.endpoint}/{bucket}/{urllib.parse.quote(key)}"
        headers = dict(extra_headers or {})
        if self.access_key_id:
            date = formatdate(time.time(), usegmt=True)
            headers["Date"] = date
            sig = sign_oss(
                self.access_key_secret, method, date=date, bucket=bucket, key=key
            )
            headers["Authorization"] = f"OSS {self.access_key_id}:{sig}"
        req = urllib.request.Request(http_url, headers=headers, method=method)
        return self.transport(req, self.timeout)
