"""oras:// / oci:// source client (reference: pkg/source/clients/orasprotocol).

Flow mirrors oras_source_client.go: fetch a bearer token
(`/service/token/?scope=repository:<path>:pull&service=harbor-registry`,
:360), fetch the manifest (`/v2/<path>/manifests/<tag>` with the OCI
accept header, :282) taking the LAST layer's digest (:296-298), then
read the blob (`/v2/<path>/blobs/<digest>`, :306).  The TPU build adds
what the piece engine needs and the reference lacked: the layer *size*
from the manifest (so content_length is one manifest fetch, not a full
blob download) and Range reads against the blob endpoint.

URL form: ``oras://<registry>/<repository>:<tag>``.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from .client import _ranged_body, default_transport

OCI_MANIFEST_ACCEPT = "application/vnd.oci.image.manifest.v1+json"


def parse_oras_url(url: str) -> Tuple[str, str, str]:
    """oras://host/repo/path:tag → (host, repo/path, tag)."""
    parsed = urllib.parse.urlsplit(url)
    path = parsed.path.lstrip("/")
    if ":" not in path:
        raise ValueError(f"oras URL missing ':tag': {url}")
    repo, tag = path.rsplit(":", 1)
    return parsed.netloc, repo, tag


class ORASSourceClient:
    def __init__(
        self,
        *,
        auth_header: str = "",
        insecure_http: bool = False,
        timeout: float = 30.0,
        transport: Optional[Callable] = None,
    ) -> None:
        self.auth_header = auth_header  # e.g. "Basic <b64>" for token fetch
        self.scheme = "http" if insecure_http else "https"
        self.timeout = timeout
        self.transport = transport or default_transport
        self._mu = threading.Lock()
        # url → (token, layer_digest, layer_size): one token+manifest
        # round-trip serves every subsequent piece read.
        self._resolved: Dict[str, Tuple[str, str, int]] = {}

    def _get(self, http_url: str, headers: dict):
        req = urllib.request.Request(http_url, headers=headers)
        return self.transport(req, self.timeout)

    def _resolve(self, url: str) -> Tuple[str, str, int]:
        with self._mu:
            hit = self._resolved.get(url)
        if hit is not None:
            return hit
        host, repo, tag = parse_oras_url(url)
        token_url = (
            f"{self.scheme}://{host}/service/token/"
            f"?scope=repository:{repo}:pull&service=harbor-registry"
        )
        headers = {"Accept": "application/json"}
        if self.auth_header:
            headers["Authorization"] = self.auth_header
        with self._get(token_url, headers) as resp:
            token = str(json.loads(resp.read()).get("token", ""))

        manifest_url = f"{self.scheme}://{host}/v2/{repo}/manifests/{tag}"
        with self._get(
            manifest_url,
            {"Accept": OCI_MANIFEST_ACCEPT, "Authorization": f"Bearer {token}"},
        ) as resp:
            manifest = json.loads(resp.read())
        layers = manifest.get("layers") or []
        if not layers:
            raise ValueError(f"manifest is empty for {url}")
        layer = layers[-1]  # reference keeps the last layer's digest
        resolved = (token, layer["digest"], int(layer.get("size", -1)))
        with self._mu:
            self._resolved[url] = resolved
        return resolved

    def _blob_url(self, url: str, digest: str) -> str:
        host, repo, _ = parse_oras_url(url)
        return f"{self.scheme}://{host}/v2/{repo}/blobs/{digest}"

    # -- SourceClient protocol ----------------------------------------------

    def content_length(self, url: str) -> int:
        try:
            _, _, size = self._resolve(url)
            return size
        except (OSError, ValueError, KeyError):
            return -1

    def _blob_read(
        self, url: str, token: str, digest: str, start: int, length: int
    ) -> bytes:
        with self._get(
            self._blob_url(url, digest),
            {
                "Accept": OCI_MANIFEST_ACCEPT,
                "Authorization": f"Bearer {token}",
                "Range": f"bytes={start}-{start + length - 1}",
            },
        ) as resp:
            return _ranged_body(resp, start, length)

    def read_range(self, url: str, start: int, length: int) -> bytes:
        token, digest, _ = self._resolve(url)
        try:
            return self._blob_read(url, token, digest, start, length)
        except urllib.error.HTTPError as e:
            if e.code not in (401, 403):
                raise
            # Registry tokens are short-lived (Harbor ~30 min): drop the
            # cached resolution, re-auth once, retry the read.
            with self._mu:
                self._resolved.pop(url, None)
            token, digest, _ = self._resolve(url)
            return self._blob_read(url, token, digest, start, length)

    def exists(self, url: str) -> bool:
        return self.content_length(url) >= 0
