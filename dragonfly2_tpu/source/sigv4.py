"""AWS Signature Version 4 request signing, dependency-free.

The reference delegates this to the AWS SDK
(pkg/source/clients/s3protocol/s3_source_client.go:78 — credentials are
carried per-request and handed to aws-sdk-go).  The TPU build has no SDK,
so the public SigV4 algorithm is implemented directly: canonical request
→ string-to-sign → derived signing key → hex signature.
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from typing import Dict, Tuple

EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def canonical_request(
    method: str,
    url: str,
    headers: Dict[str, str],
    payload_sha256: str,
) -> Tuple[str, str]:
    """Returns (canonical_request, signed_headers)."""
    parsed = urllib.parse.urlsplit(url)
    # Canonical URI: percent-encoded path, '/' preserved.
    path = urllib.parse.quote(urllib.parse.unquote(parsed.path or "/"), safe="/~")
    # Canonical query: sorted by key, strictly encoded.
    pairs = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    canon_query = "&".join(
        f"{urllib.parse.quote(k, safe='~')}={urllib.parse.quote(v, safe='~')}"
        for k, v in sorted(pairs)
    )
    lower = {k.lower().strip(): " ".join(v.split()) for k, v in headers.items()}
    signed_headers = ";".join(sorted(lower))
    canon_headers = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))
    req = "\n".join(
        [method.upper(), path, canon_query, canon_headers, signed_headers,
         payload_sha256]
    )
    return req, signed_headers


def string_to_sign(
    amz_date: str, region: str, service: str, canon_request: str
) -> Tuple[str, str]:
    """Returns (string_to_sign, credential_scope)."""
    scope = f"{amz_date[:8]}/{region}/{service}/aws4_request"
    sts = "\n".join(
        ["AWS4-HMAC-SHA256", amz_date, scope,
         hashlib.sha256(canon_request.encode()).hexdigest()]
    )
    return sts, scope


def signing_key(secret_key: str, date: str, region: str, service: str) -> bytes:
    k = _hmac(("AWS4" + secret_key).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def sign_request(
    method: str,
    url: str,
    headers: Dict[str, str],
    *,
    access_key: str,
    secret_key: str,
    region: str,
    service: str = "s3",
    amz_date: str,
    payload_sha256: str = EMPTY_SHA256,
) -> str:
    """Returns the value for the Authorization header.

    `headers` must already contain every header to be signed (including
    host and x-amz-date — the caller owns what gets signed).
    """
    canon, signed = canonical_request(method, url, headers, payload_sha256)
    sts, scope = string_to_sign(amz_date, region, service, canon)
    key = signing_key(secret_key, amz_date[:8], region, service)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    return (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}"
    )
