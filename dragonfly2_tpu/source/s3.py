"""s3:// source client (reference: pkg/source/clients/s3protocol).

URL form matches the reference: ``s3://<bucket>/<key>`` with the
bucket as the URL host (s3_source_client.go:104).  Credentials, region
and endpoint are constructor config here (the reference smuggles them in
per-request headers because its interface is request-shaped); an
injectable ``transport`` lets tests run against a local fixture server
that *re-derives* the SigV4 signature.
"""

from __future__ import annotations

import time
import urllib.parse
import urllib.request
from typing import Callable, Optional

from . import sigv4
from .client import RangedHTTPClient, default_transport


class S3SourceClient(RangedHTTPClient):
    def __init__(
        self,
        *,
        access_key: str = "",
        secret_key: str = "",
        session_token: str = "",
        region: str = "us-east-1",
        endpoint: str = "",
        force_path_style: bool = True,
        timeout: float = 30.0,
        transport: Optional[Callable] = None,
    ) -> None:
        self.access_key = access_key
        self.secret_key = secret_key
        self.session_token = session_token
        self.region = region
        # endpoint e.g. "http://127.0.0.1:9000" (minio/test fixture) or
        # "" → https://<bucket>.s3.<region>.amazonaws.com virtual-host.
        self.endpoint = endpoint.rstrip("/")
        self.force_path_style = force_path_style
        self.timeout = timeout
        self.transport = transport or default_transport

    # -- request plumbing ---------------------------------------------------

    def _http_url(self, url: str) -> str:
        parsed = urllib.parse.urlsplit(url)
        bucket, key = parsed.netloc, parsed.path.lstrip("/")
        if self.endpoint:
            if self.force_path_style:
                return f"{self.endpoint}/{bucket}/{urllib.parse.quote(key)}"
            scheme, host = self.endpoint.split("://", 1)
            return f"{scheme}://{bucket}.{host}/{urllib.parse.quote(key)}"
        return (
            f"https://{bucket}.s3.{self.region}.amazonaws.com/"
            f"{urllib.parse.quote(key)}"
        )

    def _request(self, url: str, method: str, extra_headers=None):
        http_url = self._http_url(url)
        headers = dict(extra_headers or {})
        if self.access_key:
            amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            signed = {
                "host": urllib.parse.urlsplit(http_url).netloc,
                "x-amz-date": amz_date,
                "x-amz-content-sha256": sigv4.EMPTY_SHA256,
            }
            if self.session_token:
                signed["x-amz-security-token"] = self.session_token
            headers.update(signed)
            headers["Authorization"] = sigv4.sign_request(
                method, http_url, signed,
                access_key=self.access_key, secret_key=self.secret_key,
                region=self.region, service="s3", amz_date=amz_date,
            )
            headers.pop("host")  # urllib sets Host itself, identically
        req = urllib.request.Request(http_url, headers=headers, method=method)
        return self.transport(req, self.timeout)
