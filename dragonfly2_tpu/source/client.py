"""Source clients + registry (reference: pkg/source/source_client.go,
clients/httpprotocol, loader/*.go)."""

from __future__ import annotations

import logging
import os
import threading
import urllib.parse
import urllib.request
from typing import Dict, Optional, Protocol


class SourceClient(Protocol):
    def content_length(self, url: str) -> int:
        """Total bytes; -1 when the origin won't say."""
        ...

    def read_range(self, url: str, start: int, length: int) -> bytes:
        ...


def default_transport(req: urllib.request.Request, timeout: float):
    """The injectable-transport default shared by the cloud clients
    (tests swap in local fixture servers)."""
    from ..utils import faultinject

    faultinject.fire("source.transport")
    return urllib.request.urlopen(req, timeout=timeout)


def _accepts_headers(fn) -> bool:
    """True when `fn` takes a `headers` kwarg (or **kwargs).  Inspected
    once per callable — a genuine TypeError raised INSIDE a headers-aware
    call must propagate, never silently retry without auth."""
    try:
        cached = fn.__dict__.get("_df_accepts_headers")
    except AttributeError:
        cached = None
    if cached is not None:
        return cached
    import inspect

    try:
        sig = inspect.signature(fn)
        ok = "headers" in sig.parameters or any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in sig.parameters.values()
        )
    except (ValueError, TypeError):
        ok = False
    try:
        fn.__dict__["_df_accepts_headers"] = ok
    except AttributeError:
        pass  # bound methods / builtins: re-inspect next time
    return ok


def call_with_optional_headers(fn, *args, headers=None):
    """Invoke `fn(*args, headers=headers)` when supported, else
    `fn(*args)` — but ONLY based on the signature: headers are never
    dropped because of an exception."""
    if headers and _accepts_headers(fn):
        return fn(*args, headers=headers)
    return fn(*args)


class RangedHTTPClient:
    """Shared HEAD-length / range-GET / exists over a ``_request`` hook.

    Subclasses implement ``_request(url, method, extra_headers)`` doing
    their own URL mapping and signing.  Errors in content_length are
    answered with -1 across the board — including network-level OSError
    (DNS, refused), not just HTTP status errors.
    """

    def _request(self, url: str, method: str, extra_headers=None):
        raise NotImplementedError

    def content_length(self, url: str) -> int:
        try:
            with self._request(url, "HEAD") as resp:
                cl = resp.headers.get("Content-Length")
                return int(cl) if cl is not None else -1
        except (OSError, ValueError):
            return -1

    def read_range(self, url: str, start: int, length: int) -> bytes:
        with self._request(
            url, "GET", {"Range": f"bytes={start}-{start + length - 1}"}
        ) as resp:
            return _ranged_body(resp, start, length)

    def exists(self, url: str) -> bool:
        return self.content_length(url) >= 0


def _ranged_body(resp, start: int, length: int) -> bytes:
    """Range responses are optional for some origins (e.g. OCI blob
    endpoints): a 200 carries the WHOLE object from byte 0 (a
    range-honoring origin answers 206), so extract the piece rather than
    storing the full blob as one corrupt piece.  The prefix is read in
    chunks and discarded — never the whole object buffered — and the
    tail past the piece is simply not read (the connection closes)."""
    status = getattr(resp, "status", None) or getattr(resp, "code", 206)
    if status != 200:
        return resp.read()
    remaining = start
    while remaining > 0:
        skipped = resp.read(min(remaining, 1 << 20))
        if not skipped:
            return b""  # object shorter than `start`
        remaining -= len(skipped)
    out = b""
    while len(out) < length:
        chunk = resp.read(length - len(out))
        if not chunk:
            break
        out += chunk
    return out


class FileSourceClient:
    """file:// and bare paths — the test/e2e fixture origin."""

    def _path(self, url: str) -> str:
        parsed = urllib.parse.urlsplit(url)
        if parsed.scheme == "file":
            # Writers quote paths so '#'/'?' survive urlsplit — but raw
            # unquoted URLs whose filenames contain literal '%' predate
            # that convention, so prefer the decoded path only when it
            # actually exists (or the raw one doesn't).
            decoded = urllib.parse.unquote(parsed.path)
            if decoded != parsed.path and not os.path.exists(decoded) \
                    and os.path.exists(parsed.path):
                return parsed.path
            return decoded
        return url

    def content_length(self, url: str) -> int:
        try:
            return os.path.getsize(self._path(url))
        except OSError:
            return -1

    def read_range(self, url: str, start: int, length: int) -> bytes:
        with open(self._path(url), "rb") as f:
            f.seek(start)
            return f.read(length)


class HTTPSourceClient:
    """http(s):// via urllib range GETs (clients/httpprotocol).

    ``headers`` (per call) carry request auth — preheat of private
    registry blobs rides the pull token through here.
    """

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def content_length(self, url: str, headers: Optional[dict] = None) -> int:
        from ..utils import faultinject

        req = urllib.request.Request(url, headers=headers or {}, method="HEAD")
        try:
            faultinject.fire("source.content_length")
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                cl = resp.headers.get("Content-Length")
                return int(cl) if cl is not None else -1
        except Exception as exc:  # noqa: BLE001 — origin won't say → -1
            logging.getLogger(__name__).debug("HEAD %s: %s", url, exc)
            return -1

    def read_range(
        self, url: str, start: int, length: int,
        headers: Optional[dict] = None,
    ) -> bytes:
        from ..utils import faultinject

        all_headers = {"Range": f"bytes={start}-{start + length - 1}"}
        all_headers.update(headers or {})
        faultinject.fire("source.read_range")
        with urllib.request.urlopen(
            urllib.request.Request(url, headers=all_headers),
            timeout=self.timeout,
        ) as resp:
            return _ranged_body(resp, start, length)


class SourceRegistry:
    """scheme → client (pkg/source Register/ResourceClient)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._clients: Dict[str, SourceClient] = {}

    def register(self, scheme: str, client: SourceClient) -> None:
        with self._mu:
            self._clients[scheme.lower()] = client

    def client_for(self, url: str) -> SourceClient:
        scheme = urllib.parse.urlsplit(url).scheme.lower() or "file"
        with self._mu:
            client = self._clients.get(scheme)
        if client is None:
            raise KeyError(f"no source client for scheme {scheme!r}")
        return client


default_registry = SourceRegistry()
default_registry.register("file", FileSourceClient())
default_registry.register("", FileSourceClient())
default_registry.register("http", HTTPSourceClient())
default_registry.register("https", HTTPSourceClient())


class PieceSourceFetcher:
    """Adapts a SourceClient registry to the conductor's SourceFetcher."""

    def __init__(self, registry: Optional[SourceRegistry] = None):
        self.registry = registry or default_registry

    def content_length(self, url: str, headers: Optional[dict] = None) -> int:
        client = self.registry.client_for(url)
        return call_with_optional_headers(
            client.content_length, url, headers=headers
        )

    def fetch(
        self, url: str, number: int, piece_size: int,
        headers: Optional[dict] = None,
    ) -> bytes:
        from ..utils import faultinject

        # Back-to-source chaos seam: every origin scheme funnels through
        # here, so one site covers http/s3/oss/oci/hdfs/file alike.
        faultinject.fire("source.fetch")
        client = self.registry.client_for(url)
        data = call_with_optional_headers(
            client.read_range, url, number * piece_size, piece_size,
            headers=headers,
        )
        return faultinject.fire("source.fetch.body", data)
