"""Scheduler-side rollout reporter: turn the replay log into reports.

Owns the evaluate-and-report cycle (DESIGN.md §15): drain the shadow
worker, read the replay log, join it against the record store's
completed Downloads (the realized outcomes), compute both arms' ranking
quality (rollout/evaluation.py), post the report through the rollout
client, and apply whatever the controller decided by refreshing the
model subscriber (which installs/uninstalls shadow and canary state on
the evaluator).  Tests and drills drive ``run_once`` synchronously; the
CLI runs ``serve`` on an interval thread.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from .evaluation import evaluate_shadow, load_replay_rows

logger = logging.getLogger(__name__)


class RolloutReporter:
    def __init__(
        self,
        subscriber,
        storage,
        client,
        *,
        interval_s: float = 60.0,
        regret_k: int = 4,
    ) -> None:
        self.subscriber = subscriber
        self.storage = storage
        self.client = client
        self.interval_s = interval_s
        self.regret_k = regret_k
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_once(self) -> Optional[dict]:
        """One evaluate→report→apply cycle; returns {report, decision}
        or None when there is nothing to report (no shadow installed, no
        rollout registered, or the manager is unreachable — the
        subscriber's own poll handles pinning in that last case)."""
        shadow = getattr(self.subscriber.evaluator, "shadow", None)
        if shadow is None:
            return None
        shadow.drain()
        shadow_rows = shadow.replay_rows()
        if not shadow_rows.shape[0]:
            return None
        download_rows = load_replay_rows(self.storage.download_columnar_paths())
        psi = shadow.psi()
        report = evaluate_shadow(
            shadow_rows,
            download_rows,
            k=self.regret_k,
            psi_max=float(psi.max()) if psi is not None and psi.size else None,
        )
        report["shadow"] = shadow.stats()
        # Report against the key actually under evaluation: with an
        # idc-scoped subscriber the candidate may be the regional
        # specialization (model_loader.candidate_name), whose rollout
        # row the controller keys by the composed name.
        report_name = getattr(
            self.subscriber, "candidate_name", self.subscriber.model_name
        )
        try:
            decision = self.client.report(
                self.subscriber.scheduler_id, report_name, report
            )
        except KeyError:
            logger.debug("no rollout registered for this candidate yet")
            return None
        except Exception as exc:  # noqa: BLE001 — manager outage: report next cycle
            logger.warning("rollout report failed: %s", exc)
            return None
        # Apply the decision: the subscriber's candidate poll moves the
        # evaluator between shadow/canary/active/none states.
        self.subscriber.refresh()
        return {"report": report, "decision": decision}

    def serve(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001
                    logger.exception("rollout report cycle failed")

        self._thread = threading.Thread(
            target=loop, name="rollout-reporter", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
