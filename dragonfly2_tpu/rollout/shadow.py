"""Shadow scoring: re-rank a sampled slice of announces with a candidate
model, off the announce hot path (DESIGN.md §15).

The serving path already paid for everything a candidate evaluation
needs: ``MLEvaluator._featurize_batch`` built the feature matrix out of
``HostFeatureCache`` rows and the active scorer produced its scores.
``ShadowScorer.offer`` takes exactly those arrays — zero extra
featurization — so shadow mode's marginal cost is one deterministic
hash draw, one bounded-queue append, and (on a worker thread) one
candidate forward pass per sampled announce.

Hot-path contract:

- **deterministic sampling** — announce N of child C is sampled iff
  ``crc32(f"{C}:{n}") % 10000 < rate*10000`` where ``n`` is this
  shadow's own offer counter: replaying the same announce sequence
  shadows the same announces, whatever the thread interleaving did to
  wall time (same coin style as utils/faultinject.py).
- **never blocks, never fails an announce** — the queue is bounded;
  when the worker falls behind, offers are *dropped* (counted), and any
  exception inside ``offer`` is caught and counted.  The arrays handed
  in are the evaluator's freshly-built private copies, safe to score on
  another thread.

The worker scores the candidate on the same rows, computes both
rankings, appends one row per candidate edge to a columnar **replay
log** (records/columnar.py — the same fixed-width format the trainer
ingests), and folds the feature rows into per-feature drift histograms
against the training-snapshot bin stats stamped into the candidate blob
by trainer/export.py (``psi()`` reads them out).
"""

from __future__ import annotations

import itertools
import logging
import threading
import zlib
from collections import deque
from typing import List, Optional

import numpy as np

from ..scheduler import metrics as sched_metrics

logger = logging.getLogger(__name__)

# One replay-log row per candidate edge of a shadowed announce.  All
# values are float32-exact: buckets < 2^20, ranks/counts small ints,
# the digest is folded to 24 bits.
SHADOW_COLUMNS = (
    "announce_seq",
    "candidate_version",
    "active_version",
    "src_bucket",
    "dst_bucket",
    "feature_digest",
    "active_score",
    "candidate_score",
    "active_rank",
    "candidate_rank",
)

_SAMPLE_MOD = 10_000


def sampled(child_id: str, seq: int, rate: float) -> bool:
    """The deterministic shadow coin (exposed for tests/bench)."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = zlib.crc32(f"{child_id}:{seq}".encode("utf-8"))
    return h % _SAMPLE_MOD < int(rate * _SAMPLE_MOD)


def feature_digest(feats: np.ndarray, src_buckets: np.ndarray) -> float:
    """24-bit content digest of the scored inputs (float32-exact); lets
    replay tooling detect featurization skew between log and re-run."""
    base = feats if feats.size else np.ascontiguousarray(src_buckets)
    return float(zlib.crc32(np.ascontiguousarray(base).tobytes()) & 0xFFFFFF)


class _Sample:
    __slots__ = ("seq", "feats", "src", "dst", "active_scores")

    def __init__(self, seq, feats, src, dst, active_scores) -> None:
        self.seq = seq
        self.feats = feats
        self.src = src
        self.dst = dst
        self.active_scores = active_scores


class ShadowScorer:
    """Candidate-vs-active comparison engine for one candidate version.

    Immutable per candidate: a new candidate version gets a NEW
    ShadowScorer (the subscriber swaps the whole object atomically),
    so the worker never races a scorer swap mid-sample.
    """

    def __init__(
        self,
        candidate,
        *,
        candidate_version: int,
        active_version: int = 0,
        sample_rate: float = 0.1,
        log_path: Optional[str] = None,
        max_queue: int = 256,
        max_memory_rows: int = 200_000,
        batch_linger_s: float = 0.02,
    ) -> None:
        self.candidate = candidate
        self.candidate_version = int(candidate_version)
        self.active_version = int(active_version)
        self.sample_rate = float(sample_rate)
        self.log_path = log_path
        self.max_queue = int(max_queue)
        self._max_memory_rows = int(max_memory_rows)
        # How long the worker lets samples pile up after the first one
        # before draining: bigger batches mean fewer GIL-held scoring
        # segments stealing announce throughput (tools/bench_shadow.py);
        # shadow is off the hot path, so 20 ms of staleness is free.
        self.batch_linger_s = float(batch_linger_s)
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._stopped = False
        self._idle = threading.Event()
        self._idle.set()
        # The announce sequence: itertools.count is C-implemented and
        # GIL-atomic, so the 90 %-unsampled offer path draws a UNIQUE
        # seq without touching any lock (the per-announce cv acquire +
        # metric inc showed as contention in tools/bench_shadow.py).
        self._seq = itertools.count()
        # ``offered``/``sampled_out`` are observability counters bumped
        # lock-free on the hot path: a preemption between load and store
        # can rarely lose an increment, which is acceptable for counts
        # that gate nothing (replay seqs come from _seq, never these).
        # scored/dropped/errors/logged mutate under _cv (low-rate paths).
        self.offered = 0
        self.scored_announces = 0
        self.sampled_out = 0
        self.dropped = 0
        self.errors = 0
        self.logged_rows = 0
        self._sampled_out_pushed = 0  # prometheus high-water (stats())
        # In-memory replay rows when no log_path (tests, embedded runs).
        self._rows: List[np.ndarray] = []
        self._writer = None
        if log_path is not None:
            import os

            from ..records.columnar import ColumnarReader, ColumnarWriter

            if os.path.exists(log_path) and os.path.getsize(log_path) > 0:
                # Resuming onto an existing log (scheduler restart,
                # shadow re-attach): start the offer counter past every
                # logged announce_seq so replay groups stay unique.
                # (Read BEFORE the writer opens — its header write is
                # buffered until the first flush.)
                existing = ColumnarReader(log_path)
                if len(existing):
                    start = int(existing.to_array()[:, 0].max()) + 1
                    self._seq = itertools.count(start)
                    self.offered = start
            self._writer = ColumnarWriter(log_path, SHADOW_COLUMNS)
        # Drift accounting against the candidate's training snapshot
        # (trainer/export.py stamps bin edges + expected fractions).
        edges = getattr(candidate, "train_bin_edges", None)
        fracs = getattr(candidate, "train_bin_fracs", None)
        if edges is not None and fracs is not None and len(edges):
            self._bin_edges = np.asarray(edges, np.float64)
            self._bin_fracs = np.asarray(fracs, np.float64)
            self._bin_counts = np.zeros_like(self._bin_fracs, dtype=np.int64)
        else:
            self._bin_edges = self._bin_fracs = self._bin_counts = None
        self._thread = threading.Thread(
            target=self._worker, name="shadow-scorer", daemon=True
        )
        self._thread.start()

    # -- the hot-path surface (called from MLEvaluator.evaluate_parents) -----

    def offer(self, child_id, feats, src_buckets, dst_buckets, active_scores) -> bool:
        """Maybe enqueue one announce's already-built serving arrays for
        shadow evaluation.  Returns True when the announce was sampled
        AND queued.  Never raises, never blocks — and the (common)
        sampled-out path is LOCK-FREE: one atomic seq draw, one crc, two
        racy counter bumps; prometheus totals batch-sync in stats()."""
        try:
            seq = next(self._seq)
            self.offered += 1
            if not sampled(child_id, seq, self.sample_rate):
                self.sampled_out += 1
                return False
            with self._cv:
                if self._stopped or len(self._queue) >= self.max_queue:
                    self.dropped += 1
                    sched_metrics.SHADOW_ANNOUNCES_TOTAL.inc(result="dropped")
                    return False
                self._queue.append(
                    _Sample(seq, feats, src_buckets, dst_buckets, active_scores)
                )
                self._idle.clear()
                self._cv.notify()
            return True
        except Exception:  # noqa: BLE001 — shadow must never fail an announce
            logger.exception("shadow offer failed")
            with self._cv:
                self.errors += 1
            sched_metrics.SHADOW_ANNOUNCES_TOTAL.inc(result="error")
            return False

    # -- worker ---------------------------------------------------------------

    def _worker(self) -> None:
        import time

        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    self._idle.set()
                    # Bounded wait + loop (DF008 timeout sweep): offers
                    # still wake the worker immediately; the timeout only
                    # keeps an idle drain visible to watchdog stack dumps.
                    self._cv.wait(30.0)
                if not self._queue and self._stopped:
                    self._idle.set()
                    return
            # Bounded linger OUTSIDE the lock: let concurrent announces
            # pile onto the queue so one drain scores many samples.
            if self.batch_linger_s > 0 and not self._stopped:
                time.sleep(self.batch_linger_s)
            with self._cv:
                # Drain the WHOLE queue per wake-up: under announce load
                # the candidate forward pass, drift binning and the log
                # append then run once over all pending samples — far
                # fewer GIL-held Python segments stealing time from the
                # announcer threads (measured in tools/bench_shadow.py).
                samples = list(self._queue)
                self._queue.clear()
                if not samples:
                    continue
            try:
                rows = self._score_batch(samples)
                self._log_rows(rows)
                with self._cv:
                    self.scored_announces += len(samples)
                    self.logged_rows += rows.shape[0]
                sched_metrics.SHADOW_ANNOUNCES_TOTAL.inc(
                    len(samples), result="scored"
                )
            except Exception:  # noqa: BLE001 — one bad batch must not kill the worker
                logger.exception("shadow scoring failed")
                with self._cv:
                    self.errors += len(samples)
                sched_metrics.SHADOW_ANNOUNCES_TOTAL.inc(
                    len(samples), result="error"
                )

    def _score_batch(self, samples: List[_Sample]) -> np.ndarray:
        """Score a drain's worth of announces in ONE candidate call.
        Safe per the batched-score contract (trainer/export.py
        EdgeScorer): every row scores from that row alone, so rows from
        unrelated announces cannot bleed into each other — the same
        property ScorerBatcher relies on."""
        if len(samples) == 1:
            s = samples[0]
            return self._assemble_rows(
                s,
                np.asarray(
                    self.candidate.score(
                        s.feats, src_buckets=s.src, dst_buckets=s.dst
                    ),
                    dtype=np.float64,
                ),
                drift_feats=s.feats,
            )
        widths = {s.feats.shape[1] for s in samples}
        if len(widths) != 1:
            # Mixed feature widths (scorer-family swap mid-queue): rare
            # enough to score per sample.
            return np.concatenate(
                [self._score_batch([s]) for s in samples], axis=0
            )
        k = len(samples)
        feats = np.concatenate([s.feats for s in samples], axis=0)
        src = np.concatenate([np.asarray(s.src) for s in samples])
        dst = np.concatenate([np.asarray(s.dst) for s in samples])
        cand_scores = np.asarray(
            self.candidate.score(feats, src_buckets=src, dst_buckets=dst),
            dtype=np.float64,
        )
        active_scores = np.concatenate(
            [np.asarray(s.active_scores, dtype=np.float64) for s in samples]
        )
        lens = np.fromiter((len(s.active_scores) for s in samples), np.int64, k)
        groups = np.repeat(np.arange(k), lens)
        starts = np.zeros(k, dtype=np.int64)
        starts[1:] = np.cumsum(lens)[:-1]
        n_total = len(active_scores)
        pos = np.arange(n_total, dtype=np.int64)

        def ranks(scores: np.ndarray) -> np.ndarray:
            # Per-announce rank positions in ONE stable lexsort over the
            # whole drain (same stable-tie order as the per-sample
            # argsort(kind="stable") the serving path uses).
            order = np.lexsort((-scores, groups))
            r = np.empty(n_total, dtype=np.int64)
            r[order] = pos - starts[groups[order]]
            return r

        out = np.empty((n_total, len(SHADOW_COLUMNS)), dtype=np.float32)
        out[:, 0] = np.repeat(
            np.fromiter((s.seq for s in samples), np.float64, k), lens
        )
        out[:, 1] = float(self.candidate_version)
        out[:, 2] = float(self.active_version)
        out[:, 3] = src
        out[:, 4] = dst
        out[:, 5] = np.repeat(
            np.fromiter(
                (feature_digest(s.feats, s.src) for s in samples),
                np.float64, k,
            ),
            lens,
        )
        out[:, 6] = active_scores
        out[:, 7] = cand_scores
        out[:, 8] = ranks(active_scores)
        out[:, 9] = ranks(cand_scores)
        self._accumulate_drift(feats)
        return out

    def _assemble_rows(
        self, sample: _Sample, cand_scores: np.ndarray, *, drift_feats
    ) -> np.ndarray:
        active_scores = np.asarray(sample.active_scores, dtype=np.float64)
        n = len(active_scores)
        # rank[i] = position of edge i in the arm's ordering (0 = best),
        # stable ties like the serving argsort.
        active_rank = np.empty(n, dtype=np.int64)
        active_rank[np.argsort(-active_scores, kind="stable")] = np.arange(n)
        cand_rank = np.empty(n, dtype=np.int64)
        cand_rank[np.argsort(-cand_scores, kind="stable")] = np.arange(n)
        out = np.empty((n, len(SHADOW_COLUMNS)), dtype=np.float32)
        out[:, 0] = float(sample.seq)
        out[:, 1] = float(self.candidate_version)
        out[:, 2] = float(self.active_version)
        out[:, 3] = np.asarray(sample.src, dtype=np.float64)
        out[:, 4] = np.asarray(sample.dst, dtype=np.float64)
        out[:, 5] = feature_digest(sample.feats, sample.src)
        out[:, 6] = active_scores
        out[:, 7] = cand_scores
        out[:, 8] = active_rank
        out[:, 9] = cand_rank
        if drift_feats is not None:
            self._accumulate_drift(drift_feats)
        return out

    def _accumulate_drift(self, feats: np.ndarray) -> None:
        if self._bin_edges is None or not feats.size:
            return
        if getattr(self.candidate, "post_hoc_masked", False):
            # The snapshot stats were computed over rows prepared exactly
            # as trained (post-hoc columns zeroed) — bin the served rows
            # under the same mask or those columns read as pure drift.
            from ..records.features import mask_post_hoc

            feats = mask_post_hoc(feats)
        d = min(feats.shape[1], self._bin_edges.shape[0])
        fresh = np.zeros_like(self._bin_counts)
        for j in range(d):  # per-FEATURE (32 fixed), worker thread only
            idx = np.searchsorted(
                self._bin_edges[j, 1:-1], feats[:, j].astype(np.float64)
            )
            fresh[j] = np.bincount(idx, minlength=fresh.shape[1])
        with self._cv:
            self._bin_counts += fresh

    def _log_rows(self, rows: np.ndarray) -> None:
        if self._writer is not None:
            self._writer.append(rows)
            self._writer.flush()
            return
        with self._cv:
            self._rows.append(rows)
            # Bounded memory: drop the OLDEST rows past the cap.
            total = sum(r.shape[0] for r in self._rows)
            while total > self._max_memory_rows and len(self._rows) > 1:
                total -= self._rows.pop(0).shape[0]

    # -- read side (reporter / tests) ----------------------------------------

    def replay_rows(self) -> np.ndarray:
        """Every logged row as one array (memory mode) or the log file's
        contents (disk mode — readable after ``close`` too)."""
        if self.log_path is not None:
            from ..records.columnar import ColumnarReader

            return ColumnarReader(self.log_path).to_array()
        with self._cv:
            rows = list(self._rows)
        if not rows:
            return np.zeros((0, len(SHADOW_COLUMNS)), dtype=np.float32)
        out = np.zeros(
            (sum(r.shape[0] for r in rows), len(SHADOW_COLUMNS)), np.float32
        )
        off = 0
        for r in rows:  # shard reassembly, not per-item growth
            out[off : off + r.shape[0]] = r
            off += r.shape[0]
        return out

    def psi(self) -> Optional[np.ndarray]:
        """Per-feature Population Stability Index of served features vs
        the candidate's training snapshot; None when the blob carries no
        snapshot (old artifacts, identity-only scorers)."""
        if self._bin_edges is None:
            return None
        with self._cv:
            counts = self._bin_counts.astype(np.float64).copy()
        totals = counts.sum(axis=1, keepdims=True)
        if not totals.any():
            return np.zeros(counts.shape[0])
        eps = 1e-4
        observed = np.maximum(counts / np.maximum(totals, 1.0), eps)
        expected = np.maximum(self._bin_fracs, eps)
        return ((observed - expected) * np.log(observed / expected)).sum(axis=1)

    def stats(self) -> dict:
        with self._cv:
            # Batch-sync the hot-path sampled_out count into prometheus
            # (the per-announce inc was measurable lock contention).
            delta = self.sampled_out - self._sampled_out_pushed
            if delta > 0:
                sched_metrics.SHADOW_ANNOUNCES_TOTAL.inc(
                    delta, result="sampled_out"
                )
                self._sampled_out_pushed = self.sampled_out
            out = {
                "candidate_version": self.candidate_version,
                "active_version": self.active_version,
                "sample_rate": self.sample_rate,
                "offered": self.offered,
                "scored_announces": self.scored_announces,
                "sampled_out": self.sampled_out,
                "dropped": self.dropped,
                "errors": self.errors,
                "logged_rows": self.logged_rows,
            }
        psi = self.psi()
        out["psi_max"] = float(psi.max()) if psi is not None and psi.size else None
        return out

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until every queued sample has been scored (reporter
        flush point before evaluation reads the log)."""
        return self._idle.wait(timeout)

    def close(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=10.0)
        if self._writer is not None:
            self._writer.close()
            self._writer = None
