"""Rollout-plane clients: how a scheduler talks to the rollout
controller.

Two implementations of one small surface:

- ``candidate(scheduler_id, name)`` — the version under evaluation (a
  ``CandidateInfo`` with the model row, rollout phase and canary
  percent), or None;
- ``report(scheduler_id, name, payload)`` — post one evaluation report
  (rollout/evaluation.py ``evaluate_shadow`` output) and get the
  controller's decision back;
- ``begin(model_id)`` — start the evidence-gated rollout for a freshly
  registered version (CANDIDATE → SHADOW), the lifecycle daemon's
  zero-human entry into the promotion plane (lifecycle/daemon.py).

``LocalRolloutClient`` wraps an in-process ``RolloutController`` (tests,
embedded runs, deploy/e2e_loop).  ``RolloutRESTClient`` rides the
manager's REST surface with the same retry/translate discipline as
rpc/registry_client.py, and fires the ``rollout.fetch`` /
``rollout.report`` / ``rollout.begin`` chaos seams (DF004
REQUIRED_SEAMS) so the drills can cut the quality plane
deterministically.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Optional

from ..manager.registry import Model
from ..rpc.retry import retry_call


@dataclass
class CandidateInfo:
    model: Model
    phase: str                # "shadow" | "canary"
    canary_percent: int


class LocalRolloutClient:
    """In-process controller + registry (same process as the manager)."""

    def __init__(self, controller) -> None:
        self.controller = controller
        self.registry = controller.registry

    def candidate(self, scheduler_id: str, name: str) -> Optional[CandidateInfo]:
        model = self.registry.candidate_model(scheduler_id, name)
        if model is None:
            return None
        rollout = self.controller.get(scheduler_id, name)
        return CandidateInfo(
            model=model,
            phase=model.state.value,
            canary_percent=rollout.canary_percent if rollout else 0,
        )

    def report(self, scheduler_id: str, name: str, payload: dict) -> dict:
        return self.controller.report(scheduler_id, name, payload)

    def begin(self, model_id: str, *, canary_percent: Optional[int] = None) -> dict:
        return self.controller.to_json(
            self.controller.begin(model_id, canary_percent=canary_percent)
        )

    def load_artifact(self, model: Model) -> bytes:
        return self.registry.load_artifact(model)


class RolloutRESTClient:
    """The wire form (manager/rest.py rollout routes).  ``base_url``
    accepts a replica list / shared ``ManagerEndpoints`` like
    ``RemoteRegistry`` — candidate polls and evaluation reports fail
    over to the surviving manager replica."""

    def __init__(
        self, base_url, *, timeout: float = 15.0, token: Optional[str] = None
    ) -> None:
        from ..rpc.resolver import ManagerEndpoints

        self.endpoints = ManagerEndpoints.of(base_url, client="rollout")
        self.timeout = timeout
        self.token = token

    @property
    def base_url(self) -> str:
        return self.endpoints.current()

    def _headers(self) -> dict:
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    def candidate(self, scheduler_id: str, name: str) -> Optional[CandidateInfo]:
        from ..rpc.registry_client import _model_from_json
        from ..utils import faultinject

        def one_endpoint(base: str):
            faultinject.fire("rollout.fetch")
            url = (
                base
                + "/api/v1/models:candidate?"
                + urllib.parse.urlencode(
                    {"scheduler_id": scheduler_id, "name": name}
                )
            )
            try:
                with urllib.request.urlopen(url, timeout=self.timeout) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                if exc.code == 404:
                    return None
                if exc.code == 503:
                    raise  # standby replica: endpoints.call fails over
                raise RuntimeError(f"manager: HTTP {exc.code}") from exc

        def once():
            return self.endpoints.call(one_endpoint)

        data = retry_call(
            once, retry_on=(ConnectionError, TimeoutError, OSError)
        )
        if data is None:
            return None
        return CandidateInfo(
            model=_model_from_json(data["model"]),
            phase=data["phase"],
            canary_percent=int(data.get("canary_percent", 0)),
        )

    def report(self, scheduler_id: str, name: str, payload: dict) -> dict:
        from ..utils import faultinject

        def one_endpoint(base: str):
            faultinject.fire("rollout.report")
            req = urllib.request.Request(
                base + "/api/v1/rollouts:report",
                data=json.dumps(
                    {
                        "scheduler_id": scheduler_id,
                        "name": name,
                        "report": payload,
                    }
                ).encode(),
                headers=self._headers(),
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                if exc.code == 404:
                    raise KeyError(f"no rollout for {scheduler_id}:{name}") from exc
                if exc.code == 503:
                    raise  # standby replica: endpoints.call fails over
                raise RuntimeError(f"manager: HTTP {exc.code}") from exc

        def once():
            return self.endpoints.call(one_endpoint)

        return retry_call(
            once, retry_on=(ConnectionError, TimeoutError, OSError)
        )

    def begin(self, model_id: str, *, canary_percent: Optional[int] = None) -> dict:
        from ..utils import faultinject

        def one_endpoint(base: str):
            faultinject.fire("rollout.begin")
            body: dict = {}
            if canary_percent is not None:
                body["canary_percent"] = int(canary_percent)
            req = urllib.request.Request(
                base + f"/api/v1/models/{model_id}:rollout",
                data=json.dumps(body).encode(),
                headers=self._headers(),
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                if exc.code == 404:
                    raise KeyError(model_id) from exc
                if exc.code == 400:
                    raise ValueError(f"rollout begin refused: {model_id}") from exc
                if exc.code == 503:
                    raise  # standby replica: endpoints.call fails over
                raise RuntimeError(f"manager: HTTP {exc.code}") from exc

        def once():
            return self.endpoints.call(one_endpoint)

        return retry_call(
            once, retry_on=(ConnectionError, TimeoutError, OSError)
        )
