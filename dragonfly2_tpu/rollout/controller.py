"""Rollout controller: evidence-gated promotion with automatic rollback
(DESIGN.md §15).

The registry alone moves a version from CREATE straight to ACTIVE on
operator say-so.  This controller inserts the gates: a per
(scheduler_id, name) state machine

    CANDIDATE → SHADOW → CANARY(p%) → ACTIVE
                  ↓          ↓          ↓
              ROLLED_BACK (candidate deactivated / last-good re-activated)

driven entirely by the scheduler's shadow/canary evaluation reports
(rollout/evaluation.py payloads posted through rollout/client.py).
Guardrails are explicit and configurable: a minimum joined-sample count
before any judgement, a regret-ratio ceiling vs the active arm, an
inversion-rate ceiling, and a PSI drift ceiling.  Breach ⇒ rollback;
clean evidence past the sample floor ⇒ advance.  Post-promotion reports
keep being judged: a regression after ACTIVE re-activates the recorded
last-good version (``previous_active_id``) — the auto-rollback leg.

Rows persist through the manager's StateBackend (table ``rollouts``) so
a restart resumes every in-flight rollout exactly where it was, and the
``rollout_state`` gauge (rollout/metrics.py) mirrors each machine for
scrapes and drills.
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import TYPE_CHECKING, Dict, List, Optional

from ..manager.registry import ModelState

if TYPE_CHECKING:  # wiring-time types (no runtime import cycle)
    from ..manager.registry import ModelRegistry
    from ..manager.state import StateBackend
from . import metrics

logger = logging.getLogger(__name__)


class RolloutPhase(str, enum.Enum):
    SHADOW = "shadow"
    CANARY = "canary"
    ACTIVE = "active"
    ROLLED_BACK = "rolled_back"


@dataclass
class RolloutGuardrails:
    """Promotion/rollback thresholds (config: manager rollout section)."""

    min_shadow_samples: int = 200      # joined edges before any shadow verdict
    min_canary_samples: int = 200      # further joined edges in canary
    max_regret_ratio: float = 1.10     # candidate regret ≤ active·ratio + slack
    regret_slack: float = 0.02         # absolute slack (both regrets near 0)
    max_inversion_ratio: float = 1.10  # same shape for pairwise inversions
    max_psi: float = 0.25              # industry-standard "significant shift"
    canary_percent: int = 10           # % of announces bucketed to candidate


@dataclass
class Rollout:
    """One (scheduler_id, name) rollout row."""

    scheduler_id: str
    name: str
    model_id: str
    version: int
    phase: str = RolloutPhase.SHADOW.value
    previous_active_id: str = ""       # last-good, for post-ACTIVE rollback
    canary_percent: int = 10
    reports: int = 0
    # Reports carry CUMULATIVE joined-edge counts (the reporter evaluates
    # the whole replay log); per-phase progress is measured against the
    # count captured when the phase began.
    joined_edges: int = 0
    phase_baseline: int = 0
    last_report: dict = field(default_factory=dict)
    reason: str = ""
    started_at: float = field(default_factory=time.time)
    updated_at: float = field(default_factory=time.time)

    @property
    def key(self) -> str:
        return f"{self.scheduler_id}:{self.name}"

    def phase_samples(self) -> int:
        return max(self.joined_edges - self.phase_baseline, 0)


def _state_code(phase: str) -> int:
    return metrics.STATE_CODES.get(phase, 0)


class RolloutController:
    """The manager-side brain: owns rollout rows, judges reports, and
    drives the registry's SHADOW/CANARY/ACTIVE transitions."""

    def __init__(
        self,
        registry: "ModelRegistry",
        *,
        guardrails: Optional[RolloutGuardrails] = None,
        backend: "Optional[StateBackend]" = None,
    ) -> None:
        self.registry = registry
        self.guardrails = guardrails or RolloutGuardrails()
        self._mu = threading.RLock()
        self._rollouts: Dict[str, Rollout] = {}
        self._table = None
        if backend is not None:
            self._table = backend.table("rollouts")
            for key, doc in self._table.load_all().items():
                r = Rollout(**doc)
                self._rollouts[key] = r
                metrics.ROLLOUT_STATE.set(
                    _state_code(r.phase), scheduler_id=r.scheduler_id, name=r.name
                )
        # Crash-between-rows recovery (DF014): the registry flip and the
        # rollout row live in different tables, so a crash between the
        # two commits can leave them disagreeing.  The registry is the
        # source of truth — reconcile the rows to it on every load.
        self._reconcile()

    def _reconcile(self) -> None:
        """Repair rollout rows against the registry after a restart.

        Covers every tear a crash between the registry's transactional
        flip and the rollout-row put can leave:

        - a candidate model (SHADOW/CANARY) with NO rollout row (crash
          in ``begin``/after a lost row): the row is ADOPTED — without
          it, every evaluation report would KeyError forever;
        - a row whose model is gone (crash inside ``delete_model``
          between the child and parent deletes): the row is dropped;
        - a row whose phase disagrees with the model state (crash in
          ``_advance``/``_rollback`` after the registry commit): the
          phase follows the registry.
        """
        with self._mu:
            for key, rollout in list(self._rollouts.items()):
                model = self.registry.get(rollout.model_id)
                if model is None:
                    # Parent row deleted; drop the dangling child.
                    del self._rollouts[key]
                    if self._table is not None:
                        self._table.delete(key)
                    logger.warning(
                        "rollout %s: model %s gone; dropped dangling row",
                        key, rollout.model_id,
                    )
                    continue
                state = model.state.value
                if rollout.phase in (
                    RolloutPhase.SHADOW.value, RolloutPhase.CANARY.value,
                    RolloutPhase.ACTIVE.value,
                ) and rollout.phase != state:
                    if state in (
                        RolloutPhase.SHADOW.value, RolloutPhase.CANARY.value,
                        RolloutPhase.ACTIVE.value,
                    ):
                        # The registry committed an advance the row missed.
                        rollout.phase = state
                        rollout.phase_baseline = rollout.joined_edges
                        rollout.reason = "phase reconciled to registry after restart"
                    else:
                        # Candidate was demoted (rollback committed to the
                        # registry only).
                        rollout.phase = RolloutPhase.ROLLED_BACK.value
                        rollout.reason = (
                            "rolled back during crash recovery: registry "
                            f"shows {state!r}"
                        )
                    self._persist(rollout)
                    logger.warning("rollout %s: %s", key, rollout.reason)
            for model in self.registry.list():
                if model.state.value not in (
                    RolloutPhase.SHADOW.value, RolloutPhase.CANARY.value,
                ):
                    continue
                key = f"{model.scheduler_id}:{model.name}"
                if key in self._rollouts and self._rollouts[key].phase != \
                        RolloutPhase.ROLLED_BACK.value:
                    continue
                previous = self.registry.active_model(
                    model.scheduler_id, model.name
                )
                adopted = Rollout(
                    scheduler_id=model.scheduler_id,
                    name=model.name,
                    model_id=model.id,
                    version=model.version,
                    phase=model.state.value,
                    previous_active_id=previous.id if previous else "",
                    canary_percent=self.guardrails.canary_percent,
                    reason="adopted during crash recovery",
                )
                self._rollouts[key] = adopted
                self._persist(adopted)
                logger.warning(
                    "rollout %s v%d: adopted orphan %s candidate after "
                    "restart", key, adopted.version, model.state.value,
                )

    def _persist(self, rollout: Rollout) -> None:
        rollout.updated_at = time.time()
        if self._table is not None:
            self._table.put(rollout.key, asdict(rollout))
        metrics.ROLLOUT_STATE.set(
            _state_code(rollout.phase),
            scheduler_id=rollout.scheduler_id,
            name=rollout.name,
        )

    # -- lifecycle ------------------------------------------------------------

    def begin(
        self, model_id: str, *, canary_percent: Optional[int] = None
    ) -> Rollout:
        """Start a rollout for a registered version: records the current
        active as last-good and flips the candidate to SHADOW."""
        with self._mu:
            model = self.registry.get(model_id)
            if model is None:
                raise KeyError(model_id)
            if model.state is ModelState.ACTIVE:
                raise ValueError(f"{model_id} is already active")
            previous = self.registry.active_model(model.scheduler_id, model.name)
            rollout = Rollout(
                scheduler_id=model.scheduler_id,
                name=model.name,
                model_id=model.id,
                version=model.version,
                previous_active_id=previous.id if previous else "",
                canary_percent=(
                    self.guardrails.canary_percent
                    if canary_percent is None
                    else int(canary_percent)
                ),
            )
            self.registry.set_state(model.id, ModelState.SHADOW)
            self._rollouts[rollout.key] = rollout
            self._persist(rollout)
            metrics.ROLLOUT_TRANSITIONS_TOTAL.inc(to=RolloutPhase.SHADOW.value)
            logger.info(
                "rollout %s v%d → shadow (last-good %s)",
                rollout.key, rollout.version, rollout.previous_active_id or "none",
            )
            return rollout

    def delete_model(self, model_id: str) -> None:
        """The ONLY legal model-delete entry (DF014 foreign key
        models→rollouts, records/state_contracts.py): rollout rows
        referencing the model are dropped BEFORE the registry row, so a
        crash between the two deletes leaves at worst a model without
        rollout rows — never a rollout row pointing at a deleted model
        (and even that tear is repaired by ``_reconcile`` on reload)."""
        with self._mu:
            for key, rollout in list(self._rollouts.items()):
                if rollout.model_id != model_id:
                    continue
                del self._rollouts[key]
                if self._table is not None:
                    self._table.delete(key)
                metrics.ROLLOUT_STATE.set(
                    0, scheduler_id=rollout.scheduler_id, name=rollout.name
                )
            self.registry.delete(model_id)

    def get(self, scheduler_id: str, name: str) -> Optional[Rollout]:
        with self._mu:
            return self._rollouts.get(f"{scheduler_id}:{name}")

    def list(self) -> List[Rollout]:
        with self._mu:
            return sorted(self._rollouts.values(), key=lambda r: r.key)

    # -- judgement ------------------------------------------------------------

    def _breach(self, report: dict) -> Optional[str]:
        """First guardrail the report breaches, or None."""
        g = self.guardrails
        psi = report.get("psi_max")
        if psi is not None and psi > g.max_psi:
            return f"feature drift: psi_max {psi:.3f} > {g.max_psi}"
        regret = report.get("regret_at_k") or {}
        cand, active = regret.get("candidate", 0.0), regret.get("active", 0.0)
        if cand > active * g.max_regret_ratio + g.regret_slack:
            return (
                f"regret@{regret.get('k', '?')} regression: candidate "
                f"{cand:.4f} vs active {active:.4f}"
            )
        inv = report.get("inversion_rate") or {}
        icand, iactive = inv.get("candidate", 0.0), inv.get("active", 0.0)
        if icand > iactive * g.max_inversion_ratio + g.regret_slack:
            return (
                f"inversion regression: candidate {icand:.4f} vs active "
                f"{iactive:.4f}"
            )
        return None

    def report(self, scheduler_id: str, name: str, report: dict) -> dict:
        """Judge one evaluation report; returns the decision the
        scheduler acts on: {decision, phase, canary_percent, reason}."""
        with self._mu:
            rollout = self._rollouts.get(f"{scheduler_id}:{name}")
            if rollout is None:
                raise KeyError(f"no rollout for {scheduler_id}:{name}")
            if rollout.phase == RolloutPhase.ROLLED_BACK.value:
                return self._decision(rollout, "rolled_back")
            rollout.reports += 1
            rollout.joined_edges = max(
                rollout.joined_edges, int(report.get("joined_edges", 0))
            )
            rollout.last_report = dict(report)
            g = self.guardrails
            needed = (
                g.min_canary_samples
                if rollout.phase == RolloutPhase.CANARY.value
                else g.min_shadow_samples
            )
            if rollout.phase_samples() < needed and rollout.phase != RolloutPhase.ACTIVE.value:
                self._persist(rollout)
                return self._decision(
                    rollout, "hold",
                    reason=f"{rollout.phase_samples()}/{needed} joined samples",
                )
            breach = self._breach(report)
            if breach is not None:
                self._rollback(rollout, breach)
                return self._decision(rollout, "rollback", reason=breach)
            if rollout.phase == RolloutPhase.SHADOW.value:
                self._advance(rollout, RolloutPhase.CANARY)
                return self._decision(rollout, "advance")
            if rollout.phase == RolloutPhase.CANARY.value:
                self._advance(rollout, RolloutPhase.ACTIVE)
                return self._decision(rollout, "promote")
            # Already ACTIVE and still clean: keep watching.
            self._persist(rollout)
            return self._decision(rollout, "hold", reason="post-promotion watch")

    def _decision(self, rollout: Rollout, decision: str, reason: str = "") -> dict:
        metrics.ROLLOUT_REPORTS_TOTAL.inc(decision=decision)
        return {
            "decision": decision,
            "phase": rollout.phase,
            "model_id": rollout.model_id,
            "version": rollout.version,
            "canary_percent": rollout.canary_percent,
            "reason": reason or rollout.reason,
        }

    def _advance(self, rollout: Rollout, to: RolloutPhase) -> None:
        from ..utils.tracing import default_tracer

        # Transition span: controller decisions are exactly the moments
        # an operator wants on the flight recorder next to the request
        # that triggered them (DESIGN.md §21).
        with default_tracer.span(
            "rollout/transition",
            scheduler_id=rollout.scheduler_id, model_name=rollout.name,
            from_phase=rollout.phase, to_phase=to.value,
            version=rollout.version,
        ):
            if to is RolloutPhase.CANARY:
                self.registry.set_state(rollout.model_id, ModelState.CANARY)
            elif to is RolloutPhase.ACTIVE:
                # activate() owns the single-active flip (old active →
                # INACTIVE, candidate → ACTIVE) in one persisted
                # transaction.
                self.registry.activate(rollout.model_id)
            rollout.phase = to.value
            rollout.phase_baseline = rollout.joined_edges
            self._persist(rollout)
        metrics.ROLLOUT_TRANSITIONS_TOTAL.inc(to=to.value)
        logger.info("rollout %s v%d → %s", rollout.key, rollout.version, to.value)

    def _rollback(self, rollout: Rollout, reason: str) -> None:
        from ..utils.tracing import default_tracer

        with default_tracer.span(
            "rollout/transition",
            scheduler_id=rollout.scheduler_id, model_name=rollout.name,
            from_phase=rollout.phase,
            to_phase=RolloutPhase.ROLLED_BACK.value,
            version=rollout.version, reason=reason,
        ):
            self._rollback_traced(rollout, reason)

    def _rollback_traced(self, rollout: Rollout, reason: str) -> None:
        promoted = rollout.phase == RolloutPhase.ACTIVE.value
        if promoted and rollout.previous_active_id:
            # The regression shipped: re-activate the recorded last-good
            # (one transactional flip demotes the bad version).
            try:
                self.registry.activate(rollout.previous_active_id)
            except KeyError:
                # Last-good deleted since: all we can do is demote.
                logger.warning(
                    "rollout %s: last-good %s gone; deactivating %s only",
                    rollout.key, rollout.previous_active_id, rollout.model_id,
                )
                self.registry.set_state(rollout.model_id, ModelState.INACTIVE)
        else:
            self.registry.set_state(rollout.model_id, ModelState.INACTIVE)
        rollout.phase = RolloutPhase.ROLLED_BACK.value
        rollout.reason = reason
        self._persist(rollout)
        metrics.ROLLOUT_TRANSITIONS_TOTAL.inc(to=RolloutPhase.ROLLED_BACK.value)
        logger.warning(
            "rollout %s v%d ROLLED BACK: %s", rollout.key, rollout.version, reason
        )

    def to_json(self, rollout: Rollout) -> dict:
        return asdict(rollout)
