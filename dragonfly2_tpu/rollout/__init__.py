"""Model rollout & quality plane (DESIGN.md §15).

Closes the trainer→scheduler loop with evidence instead of operator
fiat: shadow scoring re-ranks a sampled slice of live announces with
the candidate model off the hot path (shadow.py), replay evaluation
joins those counterfactual rankings against realized Download outcomes
(evaluation.py), and a manager-side controller walks each candidate
through CANDIDATE→SHADOW→CANARY→ACTIVE behind guardrails, rolling back
to the last-good version on regression (controller.py).  The scheduler
side reports through client.py/reporter.py; canary serving itself lives
on the evaluator (scheduler/evaluator.py + scheduler/microbatch.py).
"""

from .client import CandidateInfo, LocalRolloutClient, RolloutRESTClient  # noqa: F401
from .controller import (  # noqa: F401
    Rollout,
    RolloutController,
    RolloutGuardrails,
    RolloutPhase,
)
from .evaluation import (  # noqa: F401
    evaluate_shadow,
    join_outcomes,
    load_replay_rows,
    pairwise_inversion_rate,
    population_stability_index,
    regret_at_k,
)
from .reporter import RolloutReporter  # noqa: F401
from .shadow import SHADOW_COLUMNS, ShadowScorer  # noqa: F401
