"""Replay evaluation: join shadow decisions with realized outcomes and
score both arms' rankings (DESIGN.md §15).

The shadow replay log (rollout/shadow.py) records, per sampled announce,
every candidate edge with both arms' scores and rank positions.  The
scheduler's record store (records/storage.py) later captures what
actually happened: each completed Download row carries the realized
bandwidth per parent edge (the training target).  Joining the two on
(src_bucket, dst_bucket) turns counterfactual rankings into measurable
quality:

- **regret@k** — per announce, the mean realized bandwidth of the k
  edges an arm ranked best, relative to the best achievable k (ideal
  ranking over the same outcome-bearing edges).  ``1 - achieved/ideal``,
  0 = perfect, higher = worse.
- **pairwise inversion rate** — fraction of outcome-bearing edge pairs
  within an announce that an arm ordered against the realized-bandwidth
  order (ties in outcome excluded).  The rank-correlation view of the
  same question, robust to bandwidth scale.

Everything is numpy over the whole log: group reductions ride one
lexsort + bincount sweeps, never a Python loop per edge.  Per-feature
drift (PSI) is accumulated online by ShadowScorer against the
training-snapshot bins in the candidate blob; ``evaluate_shadow`` folds
its ``psi_max`` into the report the rollout controller judges.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from ..records.features import NUM_HASH_BUCKETS
from .shadow import SHADOW_COLUMNS

_COL = {name: i for i, name in enumerate(SHADOW_COLUMNS)}


def load_replay_rows(paths: Sequence[str]) -> np.ndarray:
    """Concatenate shadow replay shards (ColumnarReader over each)."""
    import os

    from ..records.columnar import ColumnarReader

    arrays = [
        ColumnarReader(p).to_array()
        for p in paths
        if os.path.exists(p) and os.path.getsize(p) > 0
    ]
    arrays = [a for a in arrays if a.shape[0] > 0]
    if not arrays:
        return np.zeros((0, len(SHADOW_COLUMNS)), dtype=np.float32)
    return np.concatenate(arrays, axis=0)


def _pair_keys(src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    return src.astype(np.int64) * NUM_HASH_BUCKETS + dst.astype(np.int64)


def join_outcomes(
    shadow_rows: np.ndarray, download_rows: np.ndarray
) -> np.ndarray:
    """Realized log-bandwidth per shadow row, NaN where no Download
    record covers that (parent, child) edge.  Multiple realized
    transfers of one edge average (the scheduler may re-announce the
    same pair across the evaluation window)."""
    out = np.full(shadow_rows.shape[0], np.nan)
    if not shadow_rows.shape[0] or not download_rows.shape[0]:
        return out
    # Download columnar layout (records/features.DOWNLOAD_COLUMNS):
    # col 0 src_bucket, col 1 dst_bucket, last col target_log_bw.
    dl_keys = _pair_keys(download_rows[:, 0], download_rows[:, 1])
    targets = download_rows[:, -1].astype(np.float64)
    uniq, inverse = np.unique(dl_keys, return_inverse=True)
    sums = np.bincount(inverse, weights=targets, minlength=len(uniq))
    counts = np.bincount(inverse, minlength=len(uniq))
    means = sums / np.maximum(counts, 1)
    sh_keys = _pair_keys(
        shadow_rows[:, _COL["src_bucket"]], shadow_rows[:, _COL["dst_bucket"]]
    )
    idx = np.searchsorted(uniq, sh_keys)
    idx_c = np.clip(idx, 0, len(uniq) - 1)
    hit = uniq[idx_c] == sh_keys
    out[hit] = means[idx_c[hit]]
    return out


def _group_index(shadow_rows: np.ndarray) -> np.ndarray:
    """Dense announce-group ids over the log: one group per
    (candidate_version, announce_seq) — seq counters restart per
    candidate, so the version disambiguates concatenated logs."""
    keys = (
        shadow_rows[:, _COL["candidate_version"]].astype(np.int64) << 32
    ) + shadow_rows[:, _COL["announce_seq"]].astype(np.int64)
    _, groups = np.unique(keys, return_inverse=True)
    return groups


def _topk_mean_per_group(
    groups: np.ndarray, order_key: np.ndarray, values: np.ndarray, k: int,
    n_groups: int,
) -> np.ndarray:
    """Mean of ``values`` over each group's k smallest ``order_key``
    rows — one lexsort + bincount, no per-group loop."""
    order = np.lexsort((order_key, groups))
    g_sorted = groups[order]
    # Position within group = global position - group start.
    starts = np.zeros(n_groups, dtype=np.int64)
    counts = np.bincount(g_sorted, minlength=n_groups)
    starts[1:] = np.cumsum(counts)[:-1]
    pos = np.arange(len(g_sorted)) - starts[g_sorted]
    top = pos < k
    sums = np.bincount(
        g_sorted[top], weights=values[order][top], minlength=n_groups
    )
    taken = np.bincount(g_sorted[top], minlength=n_groups)
    return sums / np.maximum(taken, 1)


def regret_at_k(
    shadow_rows: np.ndarray, realized: np.ndarray, *, k: int = 4
) -> Dict[str, float]:
    """Mean regret@k for both arms over announces with ≥2 outcome-bearing
    edges.  Realized values compare in linear bytes/sec (expm1 of the
    logged target)."""
    valid = ~np.isnan(realized)
    rows = shadow_rows[valid]
    bw = np.expm1(realized[valid])
    if not rows.shape[0]:
        return {"announces": 0, "candidate": 0.0, "active": 0.0}
    groups = _group_index(rows)
    n_groups = int(groups.max()) + 1
    sizes = np.bincount(groups, minlength=n_groups)
    scorable = sizes >= 2
    ideal = _topk_mean_per_group(groups, -bw, bw, k, n_groups)
    out: Dict[str, float] = {"announces": int(scorable.sum())}
    for arm in ("candidate", "active"):
        achieved = _topk_mean_per_group(
            groups, rows[:, _COL[f"{arm}_rank"]], bw, k, n_groups
        )
        with np.errstate(invalid="ignore", divide="ignore"):
            regret = 1.0 - achieved / np.maximum(ideal, 1e-9)
        regret = regret[scorable & (ideal > 0)]
        out[arm] = float(regret.mean()) if regret.size else 0.0
    return out


def pairwise_inversion_rate(
    shadow_rows: np.ndarray, realized: np.ndarray
) -> Dict[str, float]:
    """Fraction of outcome-bearing edge pairs (within an announce) each
    arm ranked against the realized-bandwidth order."""
    valid = ~np.isnan(realized)
    rows = shadow_rows[valid]
    bw = realized[valid]
    out = {"pairs": 0, "candidate": 0.0, "active": 0.0}
    if not rows.shape[0]:
        return out
    groups = _group_index(rows)
    inv = {"candidate": 0, "active": 0}
    pairs = 0
    order = np.argsort(groups, kind="stable")
    bounds = np.flatnonzero(np.diff(groups[order])) + 1
    for seg in np.split(order, bounds):  # per-ANNOUNCE; inner math is n×n numpy
        if len(seg) < 2:
            continue
        d_bw = bw[seg][:, None] - bw[seg][None, :]
        upper = np.triu(np.ones((len(seg), len(seg)), dtype=bool), k=1)
        decided = upper & (d_bw != 0.0)
        pairs += int(decided.sum())
        for arm in ("candidate", "active"):
            r = rows[seg, _COL[f"{arm}_rank"]]
            d_rank = r[:, None] - r[None, :]
            # Better outcome (d_bw > 0) should mean better (smaller) rank
            # (d_rank < 0); same-sign products are inversions.
            inv[arm] += int((decided & ((d_bw * d_rank) > 0)).sum())
    out["pairs"] = pairs
    if pairs:
        out["candidate"] = inv["candidate"] / pairs
        out["active"] = inv["active"] / pairs
    return out


def population_stability_index(
    expected_fracs: np.ndarray, observed_counts: np.ndarray
) -> np.ndarray:
    """PSI per feature row: sum((o-e)·ln(o/e)) with epsilon clamps (the
    same formula ShadowScorer.psi applies to its online accumulators)."""
    counts = np.asarray(observed_counts, np.float64)
    totals = counts.sum(axis=-1, keepdims=True)
    eps = 1e-4
    observed = np.maximum(counts / np.maximum(totals, 1.0), eps)
    expected = np.maximum(np.asarray(expected_fracs, np.float64), eps)
    return ((observed - expected) * np.log(observed / expected)).sum(axis=-1)


def evaluate_shadow(
    shadow_rows: np.ndarray,
    download_rows: np.ndarray,
    *,
    k: int = 4,
    psi_max: Optional[float] = None,
) -> Dict:
    """The report payload the scheduler posts to the rollout controller
    (rollout/client.py ``report``): outcome-joined ranking quality for
    both arms + the drift headline."""
    realized = join_outcomes(shadow_rows, download_rows)
    joined = int((~np.isnan(realized)).sum())
    regret = regret_at_k(shadow_rows, realized, k=k)
    inversion = pairwise_inversion_rate(shadow_rows, realized)
    versions = shadow_rows[:, _COL["candidate_version"]] if shadow_rows.size else np.zeros(0)
    return {
        "shadow_rows": int(shadow_rows.shape[0]),
        "joined_edges": joined,
        "announces": regret["announces"],
        "candidate_version": int(versions.max()) if versions.size else 0,
        "regret_at_k": {
            "k": k,
            "candidate": regret["candidate"],
            "active": regret["active"],
        },
        "inversion_rate": {
            "pairs": inversion["pairs"],
            "candidate": inversion["candidate"],
            "active": inversion["active"],
        },
        "psi_max": psi_max,
    }
