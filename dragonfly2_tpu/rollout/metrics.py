"""Rollout-plane metrics (manager controller side).

``rollout_state`` is the drill-visible series: one gauge per
(scheduler_id, name) carrying the numeric state code, so "the candidate
was rolled back" / "the canary froze at ACTIVE v3" is a scrape, not a
log grep.  Scheduler-side serving metrics (shadow/canary counters) live
in scheduler/metrics.py with the rest of the announce-path series.
"""

from __future__ import annotations

from ..utils.metrics import default_registry as _reg

# Numeric codes for the rollout_state gauge (DESIGN.md §15).
STATE_CODES = {
    "none": 0,
    "candidate": 1,
    "shadow": 2,
    "canary": 3,
    "active": 4,
    "rolled_back": 5,
}

ROLLOUT_STATE = _reg.gauge(
    "rollout_state",
    "Rollout state per (scheduler, model name): 0 none, 1 candidate, "
    "2 shadow, 3 canary, 4 active, 5 rolled_back",
    ["scheduler_id", "name"],
)
ROLLOUT_TRANSITIONS_TOTAL = _reg.counter(
    "rollout_transitions_total", "Rollout state-machine transitions", ["to"]
)
ROLLOUT_REPORTS_TOTAL = _reg.counter(
    "rollout_reports_total", "Shadow/canary evaluation reports received",
    ["decision"],
)
