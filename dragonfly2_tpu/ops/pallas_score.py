"""Pallas TPU kernel: fused slot-row gather + mask-folded MLP scoring.

The columnar host store (scheduler/featcache.py, DESIGN.md §18) keys
serving state by SLOT ID — so the scorer no longer needs host-side
feature-matrix assembly at all.  This kernel takes the slot matrix, the
candidate/child slot-id vectors, and the per-edge feature block, and
produces scores in ONE device dispatch per batcher flush:

- **gather in kernel** — per candidate block, the parent and child rows
  are DMA'd out of the HBM-resident slot matrix by slot id (scalar
  prefetch + ``pltpu.make_async_copy``, the embedding-lookup pattern;
  precedent: ``ops/pallas_segment.py`` prefetches its block index the
  same way).  No ``[n, 2H+E]`` feature matrix ever exists — the concat
  is algebraically folded away:
- **split first layer** — ``x @ W0`` over the concatenated layout
  ``[child | parent | edge]`` is exactly
  ``child @ W0c + parent @ W0p + edge @ W0e`` with W0 row-partitioned,
  so the kernel runs three small MXU matmuls into one accumulator and
  never materializes x;
- **mask folded** — post-hoc feature masking is zeroed W0 rows (the PR-3
  bit-identity argument, trainer/export.py ``_serving_weights``), folded
  host-side once at scorer construction;
- **gelu chain in VMEM** — the remaining dense stack (the exported
  serving MLP is 32→64→64→1) runs on the block without leaving VMEM.

``FusedMLPScorer`` wraps the kernel behind the ``EdgeScorer`` surface
with ``static_shapes = True`` so ``ScorerBatcher`` pads flushes up its
bucket ladder — TPU serving is one dispatch per flush, no recompiles on
the steady state.  It keeps a device mirror of the slot matrix, synced
against the store's ``_row_version`` (one locked snapshot per stale
flush).  A pure-jnp fallback (``use_pallas=False``, the default off-TPU)
runs the same split-matmul algebra as one jit — CPU serving and the
ordering-equivalence tests use it; interpret mode exercises the real
kernel on CPU.

``rule_weighted_sum`` is the rule path's arm of the same story: the
evaluator's 6 pre-scaled component columns reduce to one ``[n, 6] @
[6, 1]`` matvec, provided as a (trivial) pallas kernel + jit wrapper for
TPU-serving parity.

Scores are float32 device math: orderings are property-tested equal to
the numpy reference scorer (tests/test_ops.py, test_sched_vectorized),
score values agree to float tolerance (sum order differs across the
three partial matmuls — same envelope as any XLA vs numpy reduction).
"""

from __future__ import annotations

import functools
import threading
from typing import TYPE_CHECKING, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..records.features import EDGE_FEATURE_DIM, HOST_FEATURE_DIM

if TYPE_CHECKING:  # lock-graph resolver type (§16): store lock nests
    from ..scheduler.featcache import HostFeatureCache

# The exported serving MLP depth the kernel hand-unrolls (32→64→64→1);
# other depths run the jnp fallback.
_KERNEL_LAYERS = 3

# Rule-evaluator component weights in evaluator.evaluate term order:
# piece, upload-success, free-upload, host-type, idc, location.
RULE_COMPONENT_WEIGHTS = (0.2, 0.2, 0.15, 0.15, 0.15, 0.15)


def _gelu(x):
    """gelu (tanh approx) — the scorer's exact serving formula
    (trainer/export._np_gelu): x*x*x, never x**3."""
    x3 = x * x * x
    return 0.5 * x * (1.0 + jnp.tanh(0.7978845608 * (x + 0.044715 * x3)))


def fold_post_hoc_weights(
    weights: List[Tuple[np.ndarray, np.ndarray]],
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Zero the post-hoc feature ROWS of W0 (bit-identical to zeroing
    the feature columns — both make the dot terms exact 0.0)."""
    from ..records.features import POST_HOC_FEATURE_IDX

    w0, b0 = weights[0]
    w0 = np.array(w0, dtype=np.float32, copy=True)
    w0[list(POST_HOC_FEATURE_IDX), :] = 0.0
    return [(w0, np.asarray(b0, np.float32))] + [
        (np.asarray(w, np.float32), np.asarray(b, np.float32))
        for w, b in weights[1:]
    ]


def split_first_layer(
    w0: np.ndarray, host_dim: int = HOST_FEATURE_DIM
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Row-partition W0 over the ``[child | parent | edge]`` feature
    layout: (W0c [H, D1], W0p [H, D1], W0e [E, D1])."""
    return (
        np.ascontiguousarray(w0[:host_dim]),
        np.ascontiguousarray(w0[host_dim : 2 * host_dim]),
        np.ascontiguousarray(w0[2 * host_dim :]),
    )


# ---------------------------------------------------------------------------
# The kernel
# ---------------------------------------------------------------------------


def _fused_score_kernel(
    slots_ref,    # scalar prefetch [n_pad] int32 — parent slot per row
    dslots_ref,   # scalar prefetch [n_pad] int32 — child slot per row
    mat_ref,      # [S, H] f32, HBM (ANY) — the slot matrix mirror
    edge_ref,     # [CB, E] f32
    w0c_ref, w0p_ref, w0e_ref, b0_ref,   # first layer, row-partitioned
    w1_ref, b1_ref, w2_ref, b2_ref,      # gelu stack + scalar head
    out_ref,      # [CB, 1] f32
    prow_vmem,    # scratch [CB, H]
    crow_vmem,    # scratch [CB, H]
    sem,          # DMA semaphore
    *,
    cand_block: int,
):
    i = pl.program_id(0)
    base = i * cand_block

    def gather(j, _):
        s = slots_ref[base + j]
        d = dslots_ref[base + j]
        cp = pltpu.make_async_copy(
            mat_ref.at[pl.ds(s, 1), :], prow_vmem.at[pl.ds(j, 1), :], sem
        )
        cp.start()
        cp.wait()
        cp = pltpu.make_async_copy(
            mat_ref.at[pl.ds(d, 1), :], crow_vmem.at[pl.ds(j, 1), :], sem
        )
        cp.start()
        cp.wait()
        return 0

    jax.lax.fori_loop(0, cand_block, gather, 0)
    # First layer as three partial matmuls — the concat never exists.
    x = (
        jnp.dot(crow_vmem[:], w0c_ref[:], preferred_element_type=jnp.float32)
        + jnp.dot(prow_vmem[:], w0p_ref[:], preferred_element_type=jnp.float32)
        + jnp.dot(edge_ref[:], w0e_ref[:], preferred_element_type=jnp.float32)
        + b0_ref[:]
    )
    x = _gelu(x)
    x = jnp.dot(x, w1_ref[:], preferred_element_type=jnp.float32) + b1_ref[:]
    x = _gelu(x)
    out_ref[:] = (
        jnp.dot(x, w2_ref[:], preferred_element_type=jnp.float32) + b2_ref[:]
    )


def _fused_score_call(
    matrix, slots, dslots, edge, parts, *, cand_block: int, use_pallas: bool,
    interpret: bool,
):
    """One traced dispatch: gather + score.  ``parts`` is the weight
    pytree [(w0c, w0p, w0e, b0), (w1, b1), ..., (wk, bk)].
    ``use_pallas`` is partial-bound static and only ever True for the
    ``_KERNEL_LAYERS`` depth (decided at scorer construction)."""
    n_pad = edge.shape[0]
    if not use_pallas:
        # Split-matmul jnp fallback — identical algebra, XLA-fused
        # gather, arbitrary depth.
        w0c, w0p, w0e, b0 = parts[0]
        x = (
            jnp.take(matrix, dslots, axis=0) @ w0c
            + jnp.take(matrix, slots, axis=0) @ w0p
            + edge @ w0e
            + b0
        )
        for w, b in parts[1:]:
            x = _gelu(x)
            x = x @ w + b
        return x[:, 0]
    w0c, w0p, w0e, b0 = parts[0]
    w1, b1 = parts[1]
    w2, b2 = parts[2]
    d1 = w0c.shape[1]
    d2 = w1.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_pad // cand_block,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # slot matrix stays in HBM
            pl.BlockSpec((cand_block, EDGE_FEATURE_DIM), lambda i, s, d: (i, 0)),
            pl.BlockSpec((HOST_FEATURE_DIM, d1), lambda i, s, d: (0, 0)),
            pl.BlockSpec((HOST_FEATURE_DIM, d1), lambda i, s, d: (0, 0)),
            pl.BlockSpec((EDGE_FEATURE_DIM, d1), lambda i, s, d: (0, 0)),
            pl.BlockSpec((1, d1), lambda i, s, d: (0, 0)),
            pl.BlockSpec((d1, d2), lambda i, s, d: (0, 0)),
            pl.BlockSpec((1, d2), lambda i, s, d: (0, 0)),
            pl.BlockSpec((d2, 1), lambda i, s, d: (0, 0)),
            pl.BlockSpec((1, 1), lambda i, s, d: (0, 0)),
        ],
        out_specs=pl.BlockSpec((cand_block, 1), lambda i, s, d: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((cand_block, HOST_FEATURE_DIM), jnp.float32),
            pltpu.VMEM((cand_block, HOST_FEATURE_DIM), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    kernel = functools.partial(_fused_score_kernel, cand_block=cand_block)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        interpret=interpret,
    )(
        slots, dslots, matrix, edge,
        w0c, w0p, w0e, b0, w1, b1, w2, b2,
    )
    return out[:, 0]


# ---------------------------------------------------------------------------
# EdgeScorer wrapper: the serving form
# ---------------------------------------------------------------------------


class FusedMLPScorer:
    """EdgeScorer over slot ids (scheduler/evaluator.py ``wants_slots``
    protocol): ``score(edge_block, src_buckets=parent_slots,
    dst_buckets=child_slots)`` — the host rows come out of the kernel's
    device mirror of the columnar store's slot matrix.

    ``static_shapes = True`` engages the batcher's pad ladder; this
    class additionally pads to its candidate-block multiple, so the
    device sees a handful of static shapes.  The mirror re-uploads only
    when the store's row version moved (one locked snapshot per stale
    flush — on TPU this piggybacks the dispatch; on CPU jit it is a
    zero-copy asarray).

    Standardized artifacts (``feat_mean`` set) are not supported — the
    post-hoc mask cannot fold into W1 there (trainer/export.py), so the
    fused first-layer split would not be mask-correct.
    """

    static_shapes = True
    wants_features = True
    wants_slots = True

    def __init__(
        self,
        store: "HostFeatureCache",
        weights: List[Tuple[np.ndarray, np.ndarray]],
        *,
        post_hoc_masked: bool = True,
        cand_block: int = 128,
        use_pallas: Optional[bool] = None,
        interpret: bool = False,
    ) -> None:
        from ..trainer.export import MLPScorer

        self._store = store
        self.cand_block = int(cand_block)
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        served = (
            fold_post_hoc_weights(weights) if post_hoc_masked
            else [
                (np.asarray(w, np.float32), np.asarray(b, np.float32))
                for w, b in weights
            ]
        )
        w0c, w0p, w0e = split_first_layer(served[0][0])
        parts = [(jnp.asarray(w0c), jnp.asarray(w0p), jnp.asarray(w0e),
                  jnp.asarray(served[0][1].reshape(1, -1)))]
        for w, b in served[1:]:
            parts.append((jnp.asarray(w), jnp.asarray(b.reshape(1, -1))))
        self._parts = parts
        # Reference path: the numpy serving scorer over assembled rows —
        # byte-identical to the non-fused serving path; used when the
        # store served uncached (no slots) or a shadow engine needs the
        # full feature matrix (scheduler/evaluator.py).
        self._ref = MLPScorer(weights=weights, post_hoc_masked=post_hoc_masked)
        # ONE cached trace per scorer (DF010): statics bound via partial.
        # The kernel hand-unrolls exactly the exported serving depth;
        # other depths take the split-matmul jnp path — decided HERE so
        # the traced body never branches on the weight pytree.
        self._score_jit = jax.jit(
            functools.partial(
                _fused_score_call,
                cand_block=self.cand_block,
                use_pallas=bool(use_pallas) and len(parts) == _KERNEL_LAYERS,
                interpret=bool(interpret),
            )
        )
        self._mirror_mu = threading.Lock()
        self._mat_dev = None
        self._mat_version = None

    @classmethod
    def from_scorer(cls, store, scorer, **kw) -> "FusedMLPScorer":
        """Build from an exported ``MLPScorer`` artifact."""
        if scorer.feat_mean is not None:
            raise ValueError(
                "standardized artifacts cannot serve fused: the post-hoc "
                "mask does not fold through (x-mean)/std (export.py)"
            )
        return cls(
            store, scorer.weights, post_hoc_masked=scorer.post_hoc_masked, **kw
        )

    def _sync_mirror(self):
        ver = self._store._row_version
        if ver == self._mat_version:
            return self._mat_dev
        with self._mirror_mu:
            if self._store._row_version != self._mat_version:
                version, snap = self._store.matrix_snapshot()
                self._mat_dev = jnp.asarray(snap)
                self._mat_version = version
            return self._mat_dev

    def score(self, features, *, src_buckets=None, dst_buckets=None) -> np.ndarray:  # dflint: hotpath
        """[n, EDGE_FEATURE_DIM] edge block + parent/child SLOT ids →
        [n] scores, one device dispatch (row-independent: padded rows
        and co-batched strangers cannot bleed — the batched-score
        contract)."""
        if src_buckets is None or dst_buckets is None:
            raise ValueError("FusedMLPScorer needs parent/child slot ids")
        edge = np.asarray(features, dtype=np.float32)
        n = edge.shape[0]
        cb = self.cand_block
        n_pad = -(-n // cb) * cb
        mat = self._sync_mirror()
        if n_pad != n:
            e = np.zeros((n_pad, edge.shape[1]), dtype=np.float32)
            e[:n] = edge
            s = np.zeros(n_pad, dtype=np.int32)
            s[:n] = src_buckets
            d = np.zeros(n_pad, dtype=np.int32)
            d[:n] = dst_buckets
        else:
            e = edge
            s = np.asarray(src_buckets, dtype=np.int32)
            d = np.asarray(dst_buckets, dtype=np.int32)
        out = self._score_jit(
            mat, jnp.asarray(s), jnp.asarray(d), jnp.asarray(e), self._parts
        )
        return np.asarray(out)[:n]

    def score_rows(self, features, **buckets) -> np.ndarray:
        """Assembled-row fallback: byte-identical to the plain numpy
        serving scorer."""
        return self._ref.score(features, **buckets)


# ---------------------------------------------------------------------------
# Rule arm: the weighted sum as one matvec
# ---------------------------------------------------------------------------


def _rule_sum_kernel(comp_ref, w_ref, out_ref):
    out_ref[:] = jnp.dot(
        comp_ref[:], w_ref[:], preferred_element_type=jnp.float32
    )


def _rule_sum_call(components, weights, *, use_pallas: bool, interpret: bool):
    if not use_pallas:
        return (components @ weights)[:, 0]
    n = components.shape[0]
    k = components.shape[1]
    out = pl.pallas_call(
        _rule_sum_kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((n, k), lambda i: (0, 0)),
            pl.BlockSpec((k, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((n, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        interpret=interpret,
    )(components, weights)
    return out[:, 0]


# Canonical cached traces (DF010: construct once at module scope, never
# per call) — one per execution mode.
_rule_sum_jit = jax.jit(
    functools.partial(_rule_sum_call, use_pallas=False, interpret=False)
)
_rule_sum_pallas_jit = jax.jit(
    functools.partial(_rule_sum_call, use_pallas=True, interpret=False)
)
_rule_sum_interpret_jit = jax.jit(
    functools.partial(_rule_sum_call, use_pallas=True, interpret=True)
)


def rule_weighted_sum(  # dflint: hotpath
    components: np.ndarray,
    weights=RULE_COMPONENT_WEIGHTS,
    *,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> np.ndarray:
    """[n, 6] rule component matrix → [n] scores on device: the rule
    path's arm of the fused dispatch (component columns gather off the
    columnar store; the weighted sum is one MXU matvec).  Pads rows to a
    lane multiple so the jit sees a bucket ladder of shapes."""
    comp = np.asarray(components, dtype=np.float32)
    n, k = comp.shape
    n_pad = max(-(-n // 128) * 128, 128)
    if n_pad != n:
        c = np.zeros((n_pad, k), dtype=np.float32)
        c[:n] = comp
    else:
        c = comp
    w = np.asarray(weights, dtype=np.float32).reshape(-1, 1)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if interpret:
        fn = _rule_sum_interpret_jit
    elif use_pallas:
        fn = _rule_sum_pallas_jit
    else:
        fn = _rule_sum_jit
    return np.asarray(fn(jnp.asarray(c), jnp.asarray(w)))[:n]
