"""Pallas TPU kernel: segment-sum as one-hot MXU matmuls.

Edge→node scatter-add is the op XLA lowers worst on TPU (scatter
serializes; sort+segmented-scan burns VPU cycles).  The TPU-native trick:
a block of E edges writing into a block of N nodes is exactly

    out[NB, D] += onehot[NB, EB] @ values[EB, D]

— a matmul the MXU eats.  The kernel tiles the edge stream into blocks
pre-bucketed by destination node block (host prep pads each node block's
edge run), prefetches the per-block output index + first-visit flag as
scalars, and accumulates in VMEM across sequential grid steps that revisit
the same output block.

Status (measured on v5e-1, 1M edges × 128 feats): correctness matches the
XLA oracle to 4e-6, but XLA's sort-based segment_sum lowering is currently
~10× faster — the one-hot formulation spends node_block× redundant FLOPs
per edge and the f32-HIGHEST 128×128 tiles underfeed the MXU.  XLA remains
the default (ops/aggregate); this kernel is the scaffold for the bf16 /
larger-tile / double-buffered variant.

Correctness oracle: ops/aggregate.segment_sum.  CPU tests run the same
kernel in interpreter mode.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def bucket_edges_by_block(
    segment_ids: np.ndarray,
    num_segments: int,
    *,
    node_block: int = 128,
    edge_block: int = 128,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host prep: bucket the edge stream by destination node block.

    Returns (perm, dst_local, weight, block_node, is_first):
    - perm      [E_pad] — edge index into the original stream (0 for pads)
    - dst_local [E_pad] — destination offset within its node block
    - weight    [E_pad] — 1.0 real edge / 0.0 padding
    - block_node[n_edge_blocks] — node-block index each edge block writes
    - is_first  [n_edge_blocks] — 1 on the first edge block of a node block
    """
    segment_ids = np.asarray(segment_ids)
    order = np.argsort(segment_ids, kind="stable")
    n_node_blocks = (num_segments + node_block - 1) // node_block
    sorted_ids = segment_ids[order]
    # Edge run boundaries per node block.
    bounds = np.searchsorted(
        sorted_ids, np.arange(n_node_blocks + 1) * node_block
    )
    perm_parts, dstl_parts, w_parts = [], [], []
    block_node, is_first = [], []
    for j in range(n_node_blocks):
        lo, hi = bounds[j], bounds[j + 1]
        run = order[lo:hi]
        n = len(run)
        # A node block with no edges still needs one all-padding block so
        # its (is_first) visit zero-initializes the output tile.
        n_pad = max(((n + edge_block - 1) // edge_block) * edge_block, edge_block)
        pad = n_pad - n
        perm_parts.append(np.concatenate([run, np.zeros(pad, dtype=run.dtype)]))
        dstl = segment_ids[run] - j * node_block
        dstl_parts.append(
            np.concatenate([dstl, np.zeros(pad, dtype=dstl.dtype)])
        )
        w_parts.append(
            np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
        )
        n_blocks_j = n_pad // edge_block
        block_node.extend([j] * n_blocks_j)
        is_first.extend([1] + [0] * (n_blocks_j - 1))
    return (
        np.concatenate(perm_parts).astype(np.int32),
        np.concatenate(dstl_parts).astype(np.int32),
        np.concatenate(w_parts),
        np.asarray(block_node, np.int32),
        np.asarray(is_first, np.int32),
    )


def _segment_kernel(
    block_node_ref,  # scalar prefetch [n_edge_blocks]
    is_first_ref,    # scalar prefetch [n_edge_blocks]
    vals_ref,        # [EB, D]
    dstl_ref,        # [EB, 1] int32
    w_ref,           # [EB, 1] f32
    out_ref,         # [NB, D] f32 — revisited across blocks of one node block
    *,
    node_block: int,
    edge_block: int,
):
    i = pl.program_id(0)

    @pl.when(is_first_ref[i] == 1)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    dstl = dstl_ref[:].reshape(1, edge_block)            # [1, EB]
    w = w_ref[:].reshape(1, edge_block)                  # [1, EB]
    rows = jax.lax.broadcasted_iota(jnp.int32, (node_block, edge_block), 0)
    onehot = jnp.where(rows == dstl, w, 0.0)             # [NB, EB]
    # HIGHEST keeps the f32 accumulate exact (the TPU default matmul
    # precision is bf16, which injects ~1e-2 error into the segment sums).
    out_ref[:] += jnp.dot(
        onehot,
        vals_ref[:].astype(jnp.float32),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def segment_sum_pallas(
    values: jax.Array,
    segment_ids: np.ndarray,
    num_segments: int,
    *,
    node_block: int = 128,
    edge_block: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Segment-sum [E, D] by dst id → [num_segments, D] on the MXU.

    ``segment_ids`` is host-side (numpy): bucketing runs once per graph
    snapshot and is reused across training steps (the graph changes far
    slower than the weights).  ``values`` may be traced.
    """
    perm, dstl, w, block_node, is_first = bucket_edges_by_block(
        segment_ids, num_segments, node_block=node_block, edge_block=edge_block
    )
    d = values.shape[-1]
    n_node_blocks = (num_segments + node_block - 1) // node_block
    n_edge_blocks = len(block_node)

    vals = jnp.take(values, jnp.asarray(perm), axis=0)   # [E_pad, D]
    dstl_d = jnp.asarray(dstl).reshape(-1, 1)
    w_d = jnp.asarray(w).reshape(-1, 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_edge_blocks,),
        in_specs=[
            pl.BlockSpec((edge_block, d), lambda i, bn, fi: (i, 0)),
            pl.BlockSpec((edge_block, 1), lambda i, bn, fi: (i, 0)),
            pl.BlockSpec((edge_block, 1), lambda i, bn, fi: (i, 0)),
        ],
        out_specs=pl.BlockSpec((node_block, d), lambda i, bn, fi: (bn[i], 0)),
    )
    kernel = functools.partial(
        _segment_kernel, node_block=node_block, edge_block=edge_block
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (n_node_blocks * node_block, d), jnp.float32
        ),
        interpret=interpret,
    )(jnp.asarray(block_node), jnp.asarray(is_first), vals, dstl_d, w_d)
    return out[:num_segments]
