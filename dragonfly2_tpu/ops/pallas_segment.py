"""Pallas TPU kernel: segment-sum as one-hot MXU matmuls.

Edge→node scatter-add is the op XLA lowers worst on TPU (scatter
serializes; sort+segmented-scan burns VPU cycles).  The TPU-native trick:
a block of E edges writing into a block of N nodes is exactly

    out[NB, D] += onehot[NB, EB] @ values[EB, D]

— a matmul the MXU eats.  The kernel tiles the edge stream into blocks
pre-bucketed by destination node block (host prep pads each node block's
edge run), prefetches the per-block output index + first-visit flag as
scalars, and accumulates in VMEM across sequential grid steps that revisit
the same output block.

Status (measured on v5e-1, 1M edges × 128 feats → 100k segments,
chained-slope timing; run-to-run variance on the relay setup is ~±25%):
**~10-12.5 ms vs XLA's sort-based ~19 ms (1.6-1.9×)** at the default
512-edge × 256-node blocks.  Precision mode is timing-neutral here (the
op is grid/memory-bound, not MXU-bound), so ``exact=True`` f32-HIGHEST
accumulation (~4e-6 vs oracle) is the default; ``exact=False`` runs
native bf16 MXU passes (rel err ~2e-3) for gradient traffic.  The
round-1 scaffold (128×128 blocks) measured ~210 ms — the grid is one
sequential step per edge block, so narrow blocks drown in grid
overhead; 2048-wide blocks regress again (VMEM pressure).  Full numbers
and the gather-VJP A/B (not adopted in the GAT step) in BENCHMARKS.md.

Correctness oracle: ops/aggregate.segment_sum.  CPU tests run the same
kernel in interpreter mode.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def bucket_edges_by_block(
    segment_ids: np.ndarray,
    num_segments: int,
    *,
    node_block: int = 128,
    edge_block: int = 128,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host prep: bucket the edge stream by destination node block.

    Returns (perm, dst_local, weight, block_node, is_first):
    - perm      [E_pad] — edge index into the original stream (0 for pads)
    - dst_local [E_pad] — destination offset within its node block
    - weight    [E_pad] — 1.0 real edge / 0.0 padding
    - block_node[n_edge_blocks] — node-block index each edge block writes
    - is_first  [n_edge_blocks] — 1 on the first edge block of a node block
    """
    segment_ids = np.asarray(segment_ids)
    order = np.argsort(segment_ids, kind="stable")
    n_node_blocks = (num_segments + node_block - 1) // node_block
    sorted_ids = segment_ids[order]
    # Edge run boundaries per node block.
    bounds = np.searchsorted(
        sorted_ids, np.arange(n_node_blocks + 1) * node_block
    )
    perm_parts, dstl_parts, w_parts = [], [], []
    block_node, is_first = [], []
    for j in range(n_node_blocks):
        lo, hi = bounds[j], bounds[j + 1]
        run = order[lo:hi]
        n = len(run)
        # A node block with no edges still needs one all-padding block so
        # its (is_first) visit zero-initializes the output tile.
        n_pad = max(((n + edge_block - 1) // edge_block) * edge_block, edge_block)
        pad = n_pad - n
        perm_parts.append(np.concatenate([run, np.zeros(pad, dtype=run.dtype)]))
        dstl = segment_ids[run] - j * node_block
        dstl_parts.append(
            np.concatenate([dstl, np.zeros(pad, dtype=dstl.dtype)])
        )
        w_parts.append(
            np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
        )
        n_blocks_j = n_pad // edge_block
        block_node.extend([j] * n_blocks_j)
        is_first.extend([1] + [0] * (n_blocks_j - 1))
    return (
        np.concatenate(perm_parts).astype(np.int32),
        np.concatenate(dstl_parts).astype(np.int32),
        np.concatenate(w_parts),
        np.asarray(block_node, np.int32),
        np.asarray(is_first, np.int32),
    )


def _segment_kernel(
    block_node_ref,  # scalar prefetch [n_edge_blocks]
    is_first_ref,    # scalar prefetch [n_edge_blocks]
    vals_ref,        # [EB, D]
    dstl_ref,        # [EB, 1] int32
    w_ref,           # [EB, 1] f32
    out_ref,         # [NB, D] f32 — revisited across blocks of one node block
    *,
    node_block: int,
    edge_block: int,
    exact: bool,
):
    i = pl.program_id(0)

    @pl.when(is_first_ref[i] == 1)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    dstl = dstl_ref[:].reshape(1, edge_block)            # [1, EB]
    w = w_ref[:].reshape(1, edge_block)                  # [1, EB]
    rows = jax.lax.broadcasted_iota(jnp.int32, (node_block, edge_block), 0)
    onehot = jnp.where(rows == dstl, w, 0.0)             # [NB, EB]
    if exact:
        # HIGHEST keeps the f32 accumulate exact (6-pass f32 emulation on
        # the MXU — ~8× the matmul time of the native path).
        out_ref[:] += jnp.dot(
            onehot,
            vals_ref[:].astype(jnp.float32),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    else:
        # Native MXU pass: bf16 multiplicands, f32 accumulate.  The
        # one-hot matrix is exact in bf16 (0/1 weights), so the only
        # rounding is the bf16 cast of the values — the right trade for
        # gradient traffic (the gather VJP), which is bf16 upstream
        # anyway.
        out_ref[:] += jnp.dot(
            onehot.astype(jnp.bfloat16),
            vals_ref[:].astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )


def segment_sum_pallas(
    values: jax.Array,
    segment_ids: np.ndarray,
    num_segments: int,
    *,
    node_block: int = 256,
    edge_block: int = 512,
    exact: bool = True,
    presorted: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Segment-sum [E, D] by dst id → [num_segments, D] on the MXU.

    ``segment_ids`` is host-side (numpy): bucketing runs once per graph
    snapshot and is reused across training steps (the graph changes far
    slower than the weights).  ``values`` may be traced.

    ``edge_block`` is the throughput lever: the grid is one sequential
    step per edge block, so 128-wide blocks drown in grid overhead
    (~8k steps for 1M edges); 1024-wide blocks amortize it 8×.
    ``exact=False`` switches to native bf16 MXU passes with f32
    accumulate (~4× faster, rel err ~2e-3) — the right trade for
    gradient traffic; the default keeps f32-exact sums.
    ``presorted=True`` means values are ALREADY in the BUCKETED layout —
    ``vals[perm]`` for the perm from ``bucket_edges_by_block`` with the
    SAME block sizes, interior per-block padding included (build the
    edge stream in this layout at dataset prep to skip the [E, D]
    permutation gather per step).  A merely destination-sorted stream is
    NOT this layout; the length check below rejects it.
    """
    perm, dstl, w, block_node, is_first = bucket_edges_by_block(
        segment_ids, num_segments, node_block=node_block, edge_block=edge_block
    )
    if presorted:
        if values.shape[0] != len(perm):
            raise ValueError(
                f"presorted values must be in the bucketed layout "
                f"(len {len(perm)}, interior pads included); got "
                f"{values.shape[0]} rows — apply vals[perm] from "
                f"bucket_edges_by_block with the same block sizes"
            )
        vals = values
    elif values.shape[0] == 0:
        # Zero edges: every bucketed slot is padding (weight 0), but the
        # pad perm indexes row 0, which doesn't exist — jnp.take would
        # refuse.  The kernel still runs one all-padding block per node
        # block so the is_first visit zero-inits every output tile.
        vals = jnp.zeros((len(perm),) + tuple(values.shape[1:]), values.dtype)
    else:
        vals = jnp.take(values, jnp.asarray(perm), axis=0)   # [E_pad, D]
    return _segment_sum_bucketed(
        vals, jnp.asarray(dstl), jnp.asarray(w),
        jnp.asarray(block_node), jnp.asarray(is_first), num_segments,
        node_block=node_block, edge_block=edge_block, exact=exact,
        interpret=interpret,
    )


def _segment_sum_bucketed(
    vals: jax.Array,       # [E_pad, D] already in bucketed order
    dstl: jax.Array,       # [E_pad]
    w: jax.Array,          # [E_pad]
    block_node: jax.Array, # [n_edge_blocks]
    is_first: jax.Array,   # [n_edge_blocks]
    num_segments: int,
    *,
    node_block: int,
    edge_block: int,
    exact: bool,
    interpret: bool = False,
) -> jax.Array:
    """Device half: kernel launch against prebuilt buckets (reused across
    training steps — the VJP path calls this directly)."""
    d = vals.shape[-1]
    n_node_blocks = (num_segments + node_block - 1) // node_block
    n_edge_blocks = block_node.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_edge_blocks,),
        in_specs=[
            pl.BlockSpec((edge_block, d), lambda i, bn, fi: (i, 0)),
            pl.BlockSpec((edge_block, 1), lambda i, bn, fi: (i, 0)),
            pl.BlockSpec((edge_block, 1), lambda i, bn, fi: (i, 0)),
        ],
        out_specs=pl.BlockSpec((node_block, d), lambda i, bn, fi: (bn[i], 0)),
    )
    kernel = functools.partial(
        _segment_kernel, node_block=node_block, edge_block=edge_block,
        exact=exact,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (n_node_blocks * node_block, d), jnp.float32
        ),
        interpret=interpret,
    )(block_node, is_first, vals, dstl.reshape(-1, 1), w.reshape(-1, 1))
    return out[:num_segments]


def make_neighbor_gather(
    indices: np.ndarray,
    num_nodes: int,
    *,
    node_block: int = 256,
    edge_block: int = 512,
    interpret: bool = False,
):
    """→ gather(table [N, D]) → [N, K, D] whose backward scatter-add runs
    on the MXU segment kernel instead of XLA's sort-based lowering
    (measured 19 → 7 ms at [1.6M rows → 100k nodes], BENCHMARKS.md §2).

    ``indices`` is the HOST-side neighbor table ([N, K] numpy): bucketing
    happens once per graph snapshot, and the returned callable closes
    over the device-resident bucket arrays.  Padded slots (index 0 with
    mask 0) contribute garbage gradient rows exactly like jnp.take's
    backward would — masks zero them upstream either way.
    """
    indices = np.asarray(indices)
    flat_ids = indices.reshape(-1).astype(np.int64)
    perm, dstl, w, block_node, is_first = bucket_edges_by_block(
        flat_ids, num_nodes, node_block=node_block, edge_block=edge_block
    )
    idx_dev = jnp.asarray(indices, dtype=jnp.int32)
    perm_dev = jnp.asarray(perm)
    dstl_dev = jnp.asarray(dstl)
    w_dev = jnp.asarray(w)
    bn_dev = jnp.asarray(block_node)
    first_dev = jnp.asarray(is_first)

    @jax.custom_vjp
    def gather(table: jax.Array) -> jax.Array:
        return jnp.take(table, idx_dev, axis=0)

    def fwd(table):
        # Residuals must be jax types: an empty array carries the primal
        # dtype for the cotangent cast.
        return gather(table), jnp.zeros((0,), table.dtype)

    def bwd(res, g):
        dt = res.dtype
        flat = g.reshape(-1, g.shape[-1])
        vals = jnp.take(flat, perm_dev, axis=0)
        grad = _segment_sum_bucketed(
            vals, dstl_dev, w_dev, bn_dev, first_dev, num_nodes,
            node_block=node_block, edge_block=edge_block, exact=False,
            interpret=interpret,
        )
        return (grad.astype(dt),)

    gather.defvjp(fwd, bwd)
    return gather
