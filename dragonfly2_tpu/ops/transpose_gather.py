"""Scatter-free neighbor gather: the VJP is a gather over the transpose graph.

The backward of ``h[idx]`` ([N, D] table, [N, K] indices) is a scatter-add
of the [N, K, D] cotangent into the table — the op XLA lowers worst on TPU
(sort-based, ~20 ms at [100k, 16, 128]).  But the scatter IS a gather over
the *transpose* graph: for each node ``m``,

    grad_h[m] = sum over { flat edge positions e : idx.flat[e] == m } ct.flat[e]

and that edge set is static (the graph changes far slower than the
weights).  So we precompute, host-side, a transpose table listing each
node's out-edge positions padded to ``K_out`` slots, and the VJP becomes
one [N, K_out, D] gather + masked sum — sequential writes, no sort, no
serialization.  Over-degree nodes beyond ``K_out`` spill to a tiny COO
tail handled with one (small) scatter so the gradient stays exact.

Padding slots of the *forward* table (mask 0) are excluded from the
transpose table: their cotangents are identically zero (masked attention
and -inf logits cut the gradient upstream), so dropping them is exact —
and it keeps node 0 (the conventional pad target) from collecting every
pad slot as a fake out-edge.

Compare ``ops.pallas_segment.make_neighbor_gather`` (MXU segment-sum VJP):
that path needs a [E, D] permutation gather of the cotangent per step,
which regressed the full train step (BENCHMARKS.md).  Here the
permutation is folded into the precomputed transpose table itself.

Reference seam: this is the TPU replacement for the aggregation gradients
the reference never built (trainer/training/training.go:82-99 stub).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class TransposeTable(NamedTuple):
    """Static transpose adjacency: for each node, its out-edge positions.

    tidx  [N, K_out] int32 — flat positions into the [N*K] edge stream
    tmask [N, K_out] f32   — 1.0 real, 0.0 padding
    over_pos [M] int32     — spilled flat positions (over-degree tail)
    over_dst [M] int32     — node each spilled position belongs to
    """

    tidx: jax.Array
    tmask: jax.Array
    over_pos: jax.Array
    over_dst: jax.Array


def build_transpose_table(
    indices: np.ndarray,
    mask: np.ndarray,
    num_nodes: Optional[int] = None,
    *,
    cap: Optional[int] = None,
    spill_percentile: float = 99.5,
) -> TransposeTable:
    """Host prep, vectorized (no Python loop over nodes).

    ``cap`` fixes K_out; by default it is the ``spill_percentile`` of the
    out-degree distribution rounded up to a multiple of 8, so the dense
    gather covers ~everything and the COO tail stays tiny.
    """
    indices = np.asarray(indices)
    mask = np.asarray(mask)
    n = num_nodes or indices.shape[0]
    flat_src = indices.reshape(-1).astype(np.int64)
    real = mask.reshape(-1) > 0
    pos = np.nonzero(real)[0]
    srcs = flat_src[real]

    order = np.argsort(srcs, kind="stable")
    pos_s, srcs_s = pos[order], srcs[order]
    counts = np.bincount(srcs_s, minlength=n)
    if cap is None:
        k_out = int(np.percentile(counts, spill_percentile)) if len(counts) else 1
        k_out = max(8, ((max(k_out, 1) + 7) // 8) * 8)
    else:
        k_out = cap
    starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    rank = np.arange(len(srcs_s), dtype=np.int64) - starts[srcs_s]

    keep = rank < k_out
    tidx = np.zeros((n, k_out), dtype=np.int64)
    tmask = np.zeros((n, k_out), dtype=np.float32)
    tidx[srcs_s[keep], rank[keep]] = pos_s[keep]
    tmask[srcs_s[keep], rank[keep]] = 1.0
    return TransposeTable(
        tidx=jnp.asarray(tidx, jnp.int32),
        tmask=jnp.asarray(tmask),
        over_pos=jnp.asarray(pos_s[~keep], jnp.int32),
        over_dst=jnp.asarray(srcs_s[~keep], jnp.int32),
    )


def make_transpose_gather(
    indices: np.ndarray,
    mask: np.ndarray,
    num_nodes: Optional[int] = None,
    *,
    cap: Optional[int] = None,
):
    """→ ``gather(table [N, D]) → [N, K, D]`` with a scatter-free backward.

    Build once per graph snapshot from the HOST-side neighbor table (the
    same [N, K] ``indices``/``mask`` as the NeighborTable handed to the
    model); the callable closes over device-resident transpose arrays and
    plugs into ``GNNConfig(gather_fn=...)``.
    """
    indices = np.asarray(indices)
    n = num_nodes or indices.shape[0]
    tt = build_transpose_table(indices, mask, n, cap=cap)
    idx_dev = jnp.asarray(indices, jnp.int32)
    has_spill = int(tt.over_pos.shape[0]) > 0

    @jax.custom_vjp
    def gather(table: jax.Array) -> jax.Array:
        return jnp.take(table, idx_dev, axis=0)

    def fwd(table):
        # Residual: an empty array carrying the primal dtype only.
        return gather(table), jnp.zeros((0,), table.dtype)

    def bwd(res, g):
        flat = g.reshape(-1, g.shape[-1])                 # [N*K, D]
        rows = jnp.take(flat, tt.tidx, axis=0)            # [N, K_out, D]
        grad = (rows * tt.tmask[..., None].astype(rows.dtype)).sum(axis=1)
        if has_spill:
            extra = jnp.take(flat, tt.over_pos, axis=0)   # [M, D] — tiny
            grad = grad.at[tt.over_dst].add(extra)
        return (grad.astype(res.dtype),)

    gather.defvjp(fwd, bwd)
    return gather
