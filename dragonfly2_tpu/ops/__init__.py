"""Graph aggregation ops — the GNN's hot path, XLA + pallas.

The reference has no tensor ops (its "aggregation" is Go loops over Redis
lists, scheduler/networktopology/probes.go).  Here neighbor aggregation is
the FLOPs-heavy core of the trainer, with three implementations:

- ``aggregate``      — XLA reference ops: padded-table masked mean (one
  gather + reduce) and sorted-edge segment ops.  Always available; the
  numerics oracle for the kernel tests.
- ``pallas_segment`` — TPU pallas kernel computing edge→node segment-sum
  as a sequence of one-hot MXU matmuls over bucketed edge blocks (the
  TPU-native way to scatter-accumulate: the MXU does the reduction,
  no serialized scatter).
- ``pallas_score``   — the scheduler serving plane's fused slot-row
  gather + mask-folded MLP scoring kernel over the columnar host
  store's slot matrix (DESIGN.md §18), plus the rule path's
  weighted-sum matvec arm.
- ``parallel.graph_sharding`` (sibling package) — shard_map-partitioned
  aggregation for graphs larger than one chip.
"""

from .aggregate import (  # noqa: F401
    masked_mean_aggregate,
    segment_mean,
    segment_sum,
)
from .pallas_segment import (  # noqa: F401
    bucket_edges_by_block,
    make_neighbor_gather,
    segment_sum_pallas,
)
from .pallas_score import (  # noqa: F401
    FusedMLPScorer,
    fold_post_hoc_weights,
    rule_weighted_sum,
    split_first_layer,
)
from .transpose_gather import (  # noqa: F401
    build_transpose_table,
    make_transpose_gather,
)
