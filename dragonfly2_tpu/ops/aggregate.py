"""XLA reference implementations of the aggregation ops.

These are the semantics the pallas kernels must match and the fallback
when pallas is unavailable (CPU tests, non-TPU backends).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_mean_aggregate(
    h: jax.Array, indices: jax.Array, mask: jax.Array
) -> jax.Array:
    """Padded-table neighbor mean: [N, D], [N, K], [N, K] → [N, D].

    The models' SAGELayer inlines this; exposed here as the canonical op.
    """
    nbr = jnp.take(h, indices, axis=0)                 # [N, K, D]
    m = mask[..., None].astype(h.dtype)                # [N, K, 1]
    denom = jnp.maximum(m.sum(axis=1), 1.0)
    return (nbr * m).sum(axis=1) / denom


def segment_sum(values: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    """Edge→node scatter-add: [E, D], [E] → [num_segments, D]."""
    return jax.ops.segment_sum(values, segment_ids, num_segments=num_segments)


def segment_mean(values: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    total = segment_sum(values, segment_ids, num_segments)
    counts = jax.ops.segment_sum(
        jnp.ones((values.shape[0],), values.dtype), segment_ids, num_segments=num_segments
    )
    return total / jnp.maximum(counts[:, None], 1.0)
