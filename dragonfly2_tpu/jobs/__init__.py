"""Async job system (reference: internal/job + manager/job + scheduler/job).

The reference runs machinery (Redis-brokered task queue) with group jobs:
the manager fans a preheat out to scheduler clusters and aggregates group
state (preheat.go:126-167, internal/job/job.go:48-147).  Here the broker
is an in-process queue bus with the same model — named queues, workers,
group jobs with aggregated state — and the preheat job drives seed-peer
downloads through the real scheduler/daemon stack.
"""

from .queue import GroupJob, JobQueue, JobState, Worker  # noqa: F401
from .preheat import PreheatJob, preheat, preheat_image  # noqa: F401
from .image import ImageResolver, parse_manifest_url  # noqa: F401
from .sync_peers import SyncPeers, make_sync_peers_handler  # noqa: F401
