"""sync_peers: manager pulls each scheduler's live host inventory.

Reference: manager/job/sync_peers.go — on an interval, the manager sends
a sync_peers job to every active scheduler; the scheduler's job worker
answers with every host in its host manager (scheduler/job/job.go:285-297)
and the manager merges the results into its peer table (upsert live
hosts, mark vanished ones inactive).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .queue import JobQueue, JobState

SYNC_PEERS = "sync_peers"


def make_sync_peers_handler(resource):
    """Scheduler-side handler: dump the host manager (job.go:285-297)."""

    def handler(args: Dict) -> List[Dict]:
        return [
            {
                "id": h.id,
                "hostname": h.hostname,
                "ip": h.ip,
                "port": h.port,
                "download_port": h.download_port,
                "type": int(h.type),
                "peer_count": h.peer_count(),
            }
            for h in resource.host_manager.items()
        ]

    return handler


@dataclass
class PeerRecord:
    """One known daemon host as the manager sees it (models.Peer)."""

    id: str
    scheduler_id: str
    hostname: str = ""
    ip: str = ""
    port: int = 0
    download_port: int = 0
    type: int = 0
    active: bool = True
    peer_count: int = 0
    updated_at: float = field(default_factory=time.time)


class SyncPeers:
    """Manager-side runner: fan sync_peers jobs to schedulers, merge."""

    def __init__(
        self,
        broker: JobQueue,
        clusters,
        *,
        interval_s: float = 60.0,
        job_timeout_s: float = 30.0,
        prune_age_s: Optional[float] = None,
    ) -> None:
        self.broker = broker
        self.clusters = clusters
        self.interval_s = interval_s
        self.job_timeout_s = job_timeout_s
        # Terminal job records older than ~10 rounds are history.
        self.prune_age_s = (
            prune_age_s if prune_age_s is not None else max(interval_s * 10, 60.0)
        )
        self._mu = threading.Lock()
        # (scheduler_id, host_id) → PeerRecord
        self.peers: Dict[tuple, PeerRecord] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one round (sync_peers.go Run) --------------------------------------

    def run_once(self) -> int:
        """→ number of schedulers that answered.

        All jobs are fanned out FIRST and collected under one shared
        deadline — N dead schedulers cost one timeout, not N.  Peers of
        schedulers that fell OUT of the active set (keepalive expiry)
        flip inactive too: a crashed scheduler must not leave its
        inventory reported live forever."""
        deadline = time.time() + self.job_timeout_s
        active = self.clusters.active_schedulers()
        pending = [
            (sched.id, self.broker.enqueue(
                SYNC_PEERS, {}, queue_name=f"scheduler:{sched.id}",
                expires_at=deadline,
            ))
            for sched in active
        ]
        answered = 0
        while pending and time.time() < deadline:
            still = []
            for sched_id, job in pending:
                if job.state in (JobState.PENDING, JobState.STARTED):
                    still.append((sched_id, job))
                elif job.state is JobState.SUCCESS:
                    answered += 1
                    self._merge(sched_id, job.result or [])
            pending = still
            if pending:
                time.sleep(0.01)
        active_ids = {s.id for s in active}
        now = time.time()
        with self._mu:
            for (sched_id, _), rec in self.peers.items():
                if sched_id not in active_ids and rec.active:
                    rec.active = False
                    rec.updated_at = now
        self.broker.prune(max_age_s=self.prune_age_s)
        from ..rpc.metrics import SYNC_PEERS_ACTIVE, SYNC_PEERS_ROUNDS_TOTAL

        SYNC_PEERS_ROUNDS_TOTAL.inc()
        SYNC_PEERS_ACTIVE.set(len(self.list_peers(active_only=True)))
        return answered

    def _merge(self, scheduler_id: str, hosts: List[Dict]) -> None:
        """Upsert live hosts; hosts previously known under this scheduler
        but absent from the answer flip inactive (mergePeers)."""
        seen = set()
        now = time.time()
        with self._mu:
            for h in hosts:
                key = (scheduler_id, h["id"])
                seen.add(key)
                self.peers[key] = PeerRecord(
                    id=h["id"], scheduler_id=scheduler_id,
                    hostname=h.get("hostname", ""), ip=h.get("ip", ""),
                    port=h.get("port", 0),
                    download_port=h.get("download_port", 0),
                    type=h.get("type", 0), active=True,
                    peer_count=h.get("peer_count", 0), updated_at=now,
                )
            for key, rec in self.peers.items():
                if key[0] == scheduler_id and key not in seen:
                    rec.active = False
                    rec.updated_at = now

    def list_peers(
        self, scheduler_id: Optional[str] = None, *, active_only: bool = False
    ) -> List[PeerRecord]:
        with self._mu:
            records = list(self.peers.values())
        if scheduler_id is not None:
            records = [r for r in records if r.scheduler_id == scheduler_id]
        if active_only:
            records = [r for r in records if r.active]
        return sorted(records, key=lambda r: (r.scheduler_id, r.id))

    # -- ticker (sync_peers.go Serve) ---------------------------------------

    def serve(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                self.run_once()

        self._thread = threading.Thread(
            target=loop, name="sync-peers", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
