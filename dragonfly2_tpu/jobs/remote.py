"""Cross-process job fan-out: the machinery-over-Redis wire, HTTP shape.

Reference: the manager fans preheat/sync_peers group jobs to scheduler
clusters through machinery queues on a shared Redis broker
(manager/job/preheat.go:126-167, internal/job/job.go:48-147); each
scheduler's worker polls ITS queue and reports results.

Here the MANAGER process hosts the broker (jobs/queue.JobQueue) and
exposes it on its REST port (manager/rest.py):

    POST /api/v1/jobs           {type, args, queues:[...]} → {group_id,...}
    GET  /api/v1/jobs/<gid>     group + per-job states
    POST /api/v1/jobs:poll      {queue, timeout_s?} → job | 204
    POST /api/v1/jobs/<id>:result  {state, result?, error?}

``RemoteJobWorker`` is the scheduler-side consumer: long-polls its
queue over the wire, runs registered handlers (the same handler
functions the in-process Worker uses — make_preheat_handler,
make_sync_peers_handler), and reports results back.  A manager outage
degrades to retrying polls; jobs enqueued meanwhile are delivered when
it returns (broker state lives with the manager).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)


class RemoteJobClient:
    """Producer/observer side (manager CLI, tests, consoles).

    ``manager_url`` may be a single URL, a comma-separated replica list,
    or a shared ``ManagerEndpoints`` — calls fail over to the next
    manager replica on connection errors and on a standby's 503
    (rpc/resolver.ManagerEndpoints), so keepalives, job polls, and
    preheat submissions survive a leader bounce mid-flight."""

    def __init__(self, manager_url, *, token: Optional[str] = None,
                 timeout: float = 10.0) -> None:
        from ..rpc.resolver import ManagerEndpoints

        self.endpoints = ManagerEndpoints.of(manager_url, client="jobs")
        self.token = token
        self.timeout = timeout

    @property
    def base(self) -> str:
        return self.endpoints.current()

    def call(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        """Bearer-authed JSON request against the manager REST surface —
        the ONE urllib wrapper shared by the job wire and the cluster
        registration wire (rpc/cluster_client.py)."""
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"

        def once(base: str) -> dict:
            from ..utils import faultinject

            faultinject.fire("jobs.remote.call")
            req = urllib.request.Request(
                base + path, data=data, headers=headers, method=method
            )
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                if resp.status == 204:
                    return {}
                return json.loads(resp.read() or b"{}")

        return self.endpoints.call(once)

    def create_group(self, type: str, args: Dict[str, Any], queues) -> dict:
        return self.call(
            "POST", "/api/v1/jobs",
            {"type": type, "args": args, "queues": list(queues)},
        )

    def group_state(self, group_id: str) -> dict:
        return self.call("GET", f"/api/v1/jobs/{group_id}")


class RemoteJobWorker:
    """Scheduler-side consumer: poll → run handler → report."""

    def __init__(
        self,
        manager_url: str,
        queue_name: str,
        *,
        token: Optional[str] = None,
        poll_timeout_s: float = 5.0,
        error_backoff_s: float = 2.0,
    ) -> None:
        self.client = RemoteJobClient(manager_url, token=token,
                                      timeout=poll_timeout_s + 10.0)
        self.queue_name = queue_name
        self.poll_timeout_s = poll_timeout_s
        self.error_backoff_s = error_backoff_s
        self._handlers: Dict[str, Callable[[Dict[str, Any]], Any]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.jobs_done = 0
        self.jobs_failed = 0

    def register(self, job_type: str, handler: Callable[[Dict[str, Any]], Any]) -> None:
        self._handlers[job_type] = handler

    # -- one cycle (tests call this directly; serve() loops it) -------------

    def poll_once(self) -> bool:
        """Poll, run, report.  True iff a job was processed."""
        try:
            job = self.client.call(
                "POST", "/api/v1/jobs:poll",
                {"queue": self.queue_name, "timeout_s": self.poll_timeout_s},
            )
        except urllib.error.HTTPError as exc:
            if exc.code in (401, 403):
                # Not transient: a bad/absent token leaves fan-out jobs
                # PENDING forever with no other symptom — make it loud.
                logger.warning(
                    "job poll on queue %s unauthorized (HTTP %d): check "
                    "manager token/role", self.queue_name, exc.code,
                )
            else:
                logger.debug("job poll failed: %s", exc)
            raise ConnectionError(str(exc)) from exc
        except (urllib.error.URLError, OSError, ValueError) as exc:
            logger.debug("job poll failed: %s", exc)
            raise ConnectionError(str(exc)) from exc
        if not job or "id" not in job:
            return False
        handler = self._handlers.get(job["type"])
        result: Any = None
        error = ""
        if handler is None:
            error = f"no handler for job type {job['type']!r}"
        else:
            try:
                result = handler(job.get("args") or {})
            except Exception as exc:  # noqa: BLE001 — reported on the job record
                error = f"{type(exc).__name__}: {exc}"
        state = "FAILURE" if error else "SUCCESS"
        reported = False
        for attempt in range(3):
            try:
                self.client.call(
                    "POST", f"/api/v1/jobs/{job['id']}:result",
                    {"state": state, "result": result, "error": error},
                )
                reported = True
                break
            except (urllib.error.URLError, OSError) as exc:
                logger.warning(
                    "job %s result report attempt %d failed: %s",
                    job["id"], attempt + 1, exc,
                )
                self._stop.wait(self.error_backoff_s)
        if error or not reported:
            # An unreported job is NOT done: the broker's visibility
            # window will requeue it for another worker pass.
            self.jobs_failed += 1
        else:
            self.jobs_done += 1
        return True

    def serve(self) -> None:
        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except ConnectionError:
                    # Manager unreachable: keep knocking — the broker
                    # holds our queue and delivers on return.
                    self._stop.wait(self.error_backoff_s)

        self._thread = threading.Thread(
            target=loop, name=f"job-worker-{self.queue_name}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_timeout_s + 2)
