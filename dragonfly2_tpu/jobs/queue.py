"""Queue bus + workers + group-job state aggregation.

Semantics from internal/job: jobs land on named queues (GLOBAL, per
scheduler, per host — queue.go); workers consume concurrently; a group
job's state is SUCCESS only when every member succeeded, FAILURE as soon
as any member failed (job.go:111-147 GetGroupJobState).
"""

from __future__ import annotations

import enum
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Callable, Dict, List, Optional

if TYPE_CHECKING:  # state seam type (no runtime import needed)
    from ..manager.state import StateBackend

GLOBAL_QUEUE = "global"


class JobState(str, enum.Enum):
    PENDING = "PENDING"
    STARTED = "STARTED"
    SUCCESS = "SUCCESS"
    FAILURE = "FAILURE"


@dataclass
class Job:
    id: str
    type: str
    queue: str
    args: Dict[str, Any] = field(default_factory=dict)
    group_id: Optional[str] = None
    state: JobState = JobState.PENDING
    result: Any = None
    error: str = ""
    created_at: float = field(default_factory=time.time)
    # Workers skip (FAILURE "expired") jobs past this wall-clock time —
    # a late-attaching consumer must not replay a backlog of stale
    # interval jobs whose results nobody reads.  0 = never expires.
    expires_at: float = 0.0
    # Set when a wire worker pops the job (poll): STARTED jobs older than
    # the visibility window get requeued (at-least-once — the worker may
    # have died before reporting).
    started_at: float = 0.0


@dataclass
class GroupJob:
    id: str
    job_ids: List[str] = field(default_factory=list)

    def state(self, jobs: Dict[str, Job]) -> JobState:
        states = [jobs[j].state for j in self.job_ids if j in jobs]
        if any(s is JobState.FAILURE for s in states):
            return JobState.FAILURE
        if all(s is JobState.SUCCESS for s in states) and states:
            return JobState.SUCCESS
        if any(s is JobState.STARTED for s in states):
            return JobState.STARTED
        return JobState.PENDING


class JobQueue:
    """The broker: named queues + job/group registry.

    ``max_backlog`` bounds each named queue: once full, the OLDEST
    queued job is evicted (FAILURE "evicted") — a queue whose consumer
    never attaches must not grow without bound."""

    def __init__(
        self, max_backlog: int = 10_000, *,
        backend: "Optional[StateBackend]" = None,
    ) -> None:
        self._mu = threading.Lock()
        self._queues: Dict[str, "queue.Queue[Job]"] = {}
        self.jobs: Dict[str, Job] = {}
        self.groups: Dict[str, GroupJob] = {}
        self.max_backlog = max_backlog
        # Durable broker (VERDICT r4 #5): with a manager state backend
        # attached, jobs/groups write through and a restarted manager
        # re-enqueues its backlog — a preheat in flight when the manager
        # dies completes after restart instead of vanishing (reference:
        # machinery's Redis-backed queues).  Persistence covers the WIRE
        # worker paths (enqueue/poll/set_result/prune); in-process
        # Workers mutate Job objects directly and are used with
        # ephemeral queues (scheduler-side), not the manager broker.
        self._table = backend.table("jobs") if backend is not None else None
        self._gtable = (
            backend.table("job_groups") if backend is not None else None
        )
        if self._table is not None:
            self._reload()

    def _persist_job(self, j: Job) -> None:
        if self._table is None:
            return
        doc = {
            "id": j.id, "type": j.type, "queue": j.queue, "args": j.args,
            "group_id": j.group_id, "state": j.state.value,
            "result": j.result, "error": j.error,
            "created_at": j.created_at, "expires_at": j.expires_at,
            "started_at": j.started_at,
        }
        try:
            self._table.put(j.id, doc)
        except (TypeError, ValueError):
            # Non-JSON result: persist state/error with result=None
            # rather than dropping the write — leaving the durable row
            # STARTED would GUARANTEE redelivery (and re-execution) of a
            # completed job after a manager restart, not just make it
            # possible on a crash (at-least-once means crash-only
            # redelivery, not redelivery by construction).
            import logging

            logging.getLogger(__name__).warning(
                "job %s: result not JSON-serializable; persisted with "
                "result=None", j.id,
            )
            doc["result"] = None
            try:
                self._table.put(j.id, doc)
            except (TypeError, ValueError):
                pass  # args themselves unserializable — keep last state

    def _persist_group(self, g: GroupJob) -> None:
        if self._gtable is not None:
            self._gtable.put(g.id, {"id": g.id, "job_ids": list(g.job_ids)})

    def _reload(self) -> None:
        """Restart recovery: reload every row; PENDING jobs re-enqueue in
        creation order; STARTED jobs keep their started_at and re-deliver
        through the stale-visibility requeue (at-least-once, same as a
        worker that died mid-job)."""
        for d in self._table.load_all().values():
            j = Job(
                id=d["id"], type=d["type"], queue=d["queue"],
                args=d.get("args") or {}, group_id=d.get("group_id"),
                state=JobState(d["state"]), result=d.get("result"),
                error=d.get("error", ""), created_at=d["created_at"],
                expires_at=d.get("expires_at", 0.0),
                started_at=d.get("started_at", 0.0),
            )
            self.jobs[j.id] = j
        for d in self._gtable.load_all().values():
            # A crash can strand half the pair: drop ids whose job row
            # never committed (group row won the race pre-fix era), and
            # re-adopt jobs whose row carries a group_id the group row
            # missed (job commits first, DF014 write order).
            ids = [i for i in d["job_ids"] if i in self.jobs]
            self.groups[d["id"]] = GroupJob(d["id"], ids)
        for j in sorted(self.jobs.values(), key=lambda x: x.created_at):
            if j.group_id is not None:
                # Boot is single-threaded, but the repaired group row
                # writes through the same locked path as live traffic.
                with self._mu:
                    group = self.groups.setdefault(
                        j.group_id, GroupJob(j.group_id)
                    )
                    if j.id not in group.job_ids:
                        group.job_ids.append(j.id)
                        self._persist_group(group)
            if j.state is JobState.PENDING:
                self._q(j.queue).put(j)

    def _q(self, name: str) -> "queue.Queue[Job]":
        with self._mu:
            if name not in self._queues:
                self._queues[name] = queue.Queue()
            return self._queues[name]

    def enqueue(
        self,
        type: str,
        args: Dict[str, Any],
        *,
        queue_name: str = GLOBAL_QUEUE,
        group_id: Optional[str] = None,
        expires_at: float = 0.0,
    ) -> Job:
        job = Job(
            id=uuid.uuid4().hex, type=type, queue=queue_name, args=args,
            group_id=group_id, expires_at=expires_at,
        )
        with self._mu:
            self.jobs[job.id] = job
            # Persist under _mu, BEFORE the queue put: a worker can poll
            # the job the instant it lands, and an unlocked write here
            # could commit a torn STARTED/started_at=0 row that the
            # stale-requeue can never redeliver after a crash.  The job
            # row also commits BEFORE the group row that references its
            # id (DF014 write order): a crash between the two leaves a
            # complete job row the group reconciler re-adopts on reload,
            # never a group pointing at a job that doesn't exist.
            self._persist_job(job)
            if group_id is not None:
                group = self.groups.setdefault(group_id, GroupJob(group_id))
                group.job_ids.append(job.id)
                self._persist_group(group)
        q = self._q(queue_name)
        while q.qsize() >= self.max_backlog:
            try:
                evicted = q.get_nowait()
            except queue.Empty:
                break
            with self._mu:
                if evicted.state is JobState.PENDING:
                    evicted.state = JobState.FAILURE
                    evicted.error = "evicted: queue backlog full"
                    self._persist_job(evicted)
        q.put(job)
        return job

    def create_group_job(
        self, type: str, per_queue_args: Dict[str, Dict[str, Any]]
    ) -> GroupJob:
        """Fan one logical job out to many queues (machinery group jobs)."""
        gid = uuid.uuid4().hex
        with self._mu:
            self.groups[gid] = GroupJob(gid)
        for queue_name, args in per_queue_args.items():
            self.enqueue(type, args, queue_name=queue_name, group_id=gid)
        return self.groups[gid]

    def group_state(self, group_id: str) -> JobState:
        with self._mu:
            group = self.groups.get(group_id)
            if group is None:
                raise KeyError(group_id)
            return group.state(self.jobs)

    def get(self, queue_name: str, timeout: Optional[float] = None) -> Optional[Job]:
        try:
            return self._q(queue_name).get(timeout=timeout)
        except queue.Empty:
            return None

    def poll(
        self,
        queue_name: str,
        timeout: Optional[float] = None,
        *,
        requeue_started_after_s: float = 120.0,
    ) -> Optional[Job]:
        """Wire-safe pop for remote workers: skips jobs no longer PENDING
        (pruned/evicted), fails expired ones instead of delivering them
        (the in-process Worker's expires_at contract), marks the returned
        job STARTED, and first REQUEUES jobs a dead worker popped but
        never reported (at-least-once)."""
        self._requeue_stale_started(queue_name, requeue_started_after_s)
        deadline = None if timeout is None else time.time() + timeout
        while True:
            remaining = None if deadline is None else max(deadline - time.time(), 0)
            job = self.get(queue_name, timeout=remaining)
            if job is None:
                return None
            now = time.time()
            with self._mu:
                if job.state is not JobState.PENDING:
                    continue  # pruned/evicted while queued
                if job.expires_at and now > job.expires_at:
                    job.state = JobState.FAILURE
                    job.error = "expired before execution"
                    self._persist_job(job)
                    continue
                job.state = JobState.STARTED
                job.started_at = now
                self._persist_job(job)
            return job

    def _requeue_stale_started(self, queue_name: str, max_age_s: float) -> None:
        if max_age_s <= 0:
            return
        cutoff = time.time() - max_age_s
        stale = []
        with self._mu:
            for j in self.jobs.values():
                if (
                    j.queue == queue_name
                    and j.state is JobState.STARTED
                    and 0 < j.started_at < cutoff
                ):
                    j.state = JobState.PENDING
                    j.started_at = 0.0
                    self._persist_job(j)
                    stale.append(j)
        for j in stale:
            self._q(queue_name).put(j)

    def set_result(
        self, job_id: str, state: JobState, result: Any = None, error: str = ""
    ) -> None:
        """Record a job outcome by id — the wire workers' completion path
        (in-process Workers mutate the shared Job object directly)."""
        with self._mu:
            job = self.jobs.get(job_id)
            if job is None:
                raise KeyError(job_id)
            job.state = state
            job.result = result
            job.error = error
            self._persist_job(job)

    def group_snapshot(self, group_id: str) -> Dict[str, Any]:
        """Group state + per-job states (the jobs API's GET view)."""
        with self._mu:
            group = self.groups.get(group_id)
            if group is None:
                raise KeyError(group_id)
            return {
                "group_id": group_id,
                "state": group.state(self.jobs).value,
                "jobs": [
                    {
                        "id": j.id,
                        "queue": j.queue,
                        "type": j.type,
                        "state": j.state.value,
                        "error": j.error,
                        "result": j.result,
                    }
                    for j in (self.jobs.get(jid) for jid in group.job_ids)
                    if j is not None
                ],
            }

    def list_groups(self, limit: int = 50) -> list:
        """Most-recent group snapshots (the console's jobs view)."""
        with self._mu:
            ids = list(self.groups.keys())[-limit:]
        out = []
        for gid in reversed(ids):
            try:
                out.append(self.group_snapshot(gid))
            except KeyError:
                continue  # pruned between listing and snapshot
        return out

    def prune(self, max_age_s: float) -> int:
        """Drop terminal job records (and emptied groups) older than
        ``max_age_s`` — interval producers (sync_peers every minute for
        the manager's lifetime) must not grow the registry unboundedly.

        PENDING jobs whose ``expires_at`` passed flip FAILURE first: a
        queue whose consumer never attached must not exempt its jobs
        from pruning."""
        now = time.time()
        cutoff = now - max_age_s
        removed = 0
        with self._mu:
            for j in self.jobs.values():
                if (
                    j.state is JobState.PENDING
                    and j.expires_at
                    and now > j.expires_at
                ):
                    j.state = JobState.FAILURE
                    j.error = "expired before execution"
                    self._persist_job(j)
            for jid in [
                j.id for j in self.jobs.values()
                if j.state in (JobState.SUCCESS, JobState.FAILURE)
                and j.created_at < cutoff
            ]:
                job = self.jobs.pop(jid)
                removed += 1
                if self._table is not None:
                    self._table.delete(jid)
                if job.group_id and job.group_id in self.groups:
                    group = self.groups[job.group_id]
                    if jid in group.job_ids:
                        group.job_ids.remove(jid)
                    if not group.job_ids:
                        self.groups.pop(job.group_id, None)
                        if self._gtable is not None:
                            self._gtable.delete(job.group_id)
                    else:
                        self._persist_group(group)
        return removed


class Worker:
    """Consumes one queue; handlers registered per job type
    (scheduler/job/job.go:125 Serve with named consumers)."""

    def __init__(self, broker: JobQueue, queue_name: str) -> None:
        self.broker = broker
        self.queue_name = queue_name
        self._handlers: Dict[str, Callable[[Dict[str, Any]], Any]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, job_type: str, handler: Callable[[Dict[str, Any]], Any]) -> None:
        self._handlers[job_type] = handler

    def _run_job(self, job: Job) -> None:
        if job.expires_at and time.time() > job.expires_at:
            job.state = JobState.FAILURE
            job.error = "expired before execution"
            return
        handler = self._handlers.get(job.type)
        if handler is None:
            job.state = JobState.FAILURE
            job.error = f"no handler for {job.type}"
            return
        job.state = JobState.STARTED
        try:
            job.result = handler(job.args)
            job.state = JobState.SUCCESS
        except Exception as exc:  # noqa: BLE001 — job errors land on the job record
            job.state = JobState.FAILURE
            job.error = str(exc)

    def drain(self, timeout: float = 0.0) -> int:
        """Synchronously process everything queued (tests / embedded mode)."""
        n = 0
        while True:
            job = self.broker.get(self.queue_name, timeout=timeout)
            if job is None:
                return n
            self._run_job(job)
            n += 1

    def serve(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.is_set():
                job = self.broker.get(self.queue_name, timeout=0.2)
                if job is not None:
                    self._run_job(job)

        self._thread = threading.Thread(
            target=loop, name=f"worker-{self.queue_name}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
