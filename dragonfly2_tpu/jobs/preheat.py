"""Preheat: warm content into seed peers ahead of demand.

Reference flow (SURVEY §3.5): console → manager resolves image layers /
file URLs → machinery group job fanned to scheduler clusters
(manager/job/preheat.go:126-167) → each scheduler's job worker triggers a
seed-peer download (scheduler/job/job.go:203-283 → seed_peer.go
TriggerDownloadTask).

Here: ``preheat()`` creates the group job over the target schedulers'
queues; each scheduler's worker handler drives a seed daemon's conductor
to fetch the URL, so subsequent peers find a warm parent.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence

from ..utils.types import Priority
from .queue import GroupJob, JobQueue

PREHEAT = "preheat"

# Preheat is warm-ahead-of-demand BACKGROUND work (DESIGN.md §26): the
# fan-out runs at the lowest priority class, so the seeder queue orders
# it behind interactive pulls and overload admission sheds it FIRST.
PREHEAT_PRIORITY = Priority.LEVEL6


@dataclass
class PreheatJob:
    group: GroupJob
    urls: List[str]


def preheat(
    broker: JobQueue,
    urls: Sequence[str],
    scheduler_queues: Sequence[str],
    *,
    piece_size: int = 4 << 20,
) -> PreheatJob:
    """Fan a preheat of the URLs out to every target scheduler's queue."""
    from ..utils.tracing import default_tracer

    with default_tracer.span(
        "jobs/preheat", urls=len(urls), queues=len(scheduler_queues)
    ) as span:
        per_queue = {
            q: {
                "urls": list(urls),
                "piece_size": piece_size,
                "priority": int(PREHEAT_PRIORITY),
            }
            for q in scheduler_queues
        }
        group = broker.create_group_job(PREHEAT, per_queue)
        span.set(group_id=group.id)
        return PreheatJob(group=group, urls=list(urls))


def preheat_image(
    broker: JobQueue,
    manifest_url: str,
    scheduler_queues: Sequence[str],
    resolver,
    *,
    piece_size: int = 4 << 20,
) -> PreheatJob:
    """Resolve an image's layer blobs and fan them out (the console's
    type=image preheat: manager/job/preheat.go:90-167)."""
    from ..utils.tracing import default_tracer

    with default_tracer.span(
        "jobs/preheat", image=manifest_url, queues=len(scheduler_queues)
    ) as span:
        resolved = resolver.resolve_layers(manifest_url)
        per_queue = {
            q: {
                "urls": list(resolved.urls),
                "piece_size": piece_size,
                "headers": dict(resolved.headers),
                "priority": int(PREHEAT_PRIORITY),
            }
            for q in scheduler_queues
        }
        group = broker.create_group_job(PREHEAT, per_queue)
        span.set(group_id=group.id, urls=len(resolved.urls))
        return PreheatJob(group=group, urls=list(resolved.urls))


def make_preheat_handler(seed_daemon, *, content_length_for=None):
    """Handler for a scheduler's worker: seed daemon downloads each URL.

    ``content_length_for(url)`` supplies origin sizes (HEAD request in a
    wire deployment); defaults to one piece.
    """

    def handler(args: Dict) -> Dict:
        from ..utils.tracing import default_tracer

        # The worker-side half of the fan-out: one span per executed
        # preheat job, so the manager's jobs/preheat span and each
        # scheduler's execution land in the same flight-recorder story.
        with default_tracer.span(
            "jobs/preheat.execute", urls=len(args["urls"])
        ):
            return _execute(args)

    def _execute(args: Dict) -> Dict:
        from ..source.client import call_with_optional_headers

        headers = args.get("headers") or None
        results = {}
        for url in args["urls"]:
            if content_length_for is not None:
                cl = call_with_optional_headers(
                    content_length_for, url, headers=headers
                )
            else:
                cl = args["piece_size"]
            # The registry pull token rides to the origin fetcher —
            # private-registry blobs need it on every GET.  Preheat runs
            # at the background class: the job args carry LEVEL6 so the
            # seed's download (and its scheduler registration) yields to
            # interactive pulls end-to-end (DESIGN.md §26).
            r = seed_daemon.download(
                url, piece_size=args["piece_size"], content_length=cl,
                source_headers=headers,
                priority=Priority(int(args.get("priority", PREHEAT_PRIORITY))),
            )
            if not r.ok:
                raise RuntimeError(f"preheat of {url} failed")
            results[url] = r.pieces
        return results

    return handler
