"""Image-manifest resolution for preheat (manager/job/preheat.go:169-333).

Given a registry manifest URL (``https://<registry>/v2/<repo>/manifests/
<ref>``), resolve the layer blob URLs to preheat: basic-auth or
distribution token-flow auth, Accept headers for the docker/OCI manifest
media types, manifest LISTS filtered per platform with each matched
entry fetched by digest, layers collected across entries.
"""

from __future__ import annotations

import base64
import json
import re
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

MANIFEST_ACCEPT = ", ".join(
    [
        "application/vnd.docker.distribution.manifest.v2+json",
        "application/vnd.docker.distribution.manifest.list.v2+json",
        "application/vnd.oci.image.manifest.v1+json",
        "application/vnd.oci.image.index.v1+json",
    ]
)
LIST_TYPES = (
    "application/vnd.docker.distribution.manifest.list.v2+json",
    "application/vnd.oci.image.index.v1+json",
)


def parse_manifest_url(url: str) -> Tuple[str, str, str]:
    """…/v2/<repo>/manifests/<ref> → (registry_base, repo, ref)."""
    parsed = urllib.parse.urlsplit(url)
    m = re.match(r"^/v2/(.+)/manifests/([^/]+)$", parsed.path)
    if not m:
        raise ValueError(f"not a registry manifest URL: {url}")
    base = f"{parsed.scheme}://{parsed.netloc}"
    return base, m.group(1), m.group(2)


def _default_transport(req: urllib.request.Request, timeout: float):
    from ..utils import faultinject

    faultinject.fire("jobs.image.fetch")
    return urllib.request.urlopen(req, timeout=timeout)


@dataclass
class ResolvedLayers:
    urls: List[str]
    headers: Dict[str, str]  # auth header the downloaders must carry


class ImageResolver:
    def __init__(
        self,
        *,
        username: str = "",
        password: str = "",
        token: str = "",     # pre-issued Authorization value (Harbor V1 path)
        platform: str = "",  # "os/arch", "" = accept all entries
        timeout: float = 15.0,
        transport: Optional[Callable] = None,
    ) -> None:
        self.username = username
        self.password = password
        self.token = token
        self.platform = platform
        self.timeout = timeout
        self.transport = transport or _default_transport

    # -- auth (imageAuthClient: basic → WWW-Authenticate token flow) --------

    def _basic(self) -> str:
        raw = f"{self.username}:{self.password}".encode()
        return "Basic " + base64.b64encode(raw).decode()

    def _fetch_token(self, challenge: str, repo: str) -> str:
        """Parse `Bearer realm="…",service="…"` and fetch a pull token."""
        _, _, params = challenge.partition(" ")
        fields = dict(re.findall(r'(\w+)="([^"]*)"', params))
        realm = fields.get("realm", "")
        if not realm:
            raise PermissionError(f"unparseable auth challenge: {challenge}")
        qs = {"scope": fields.get("scope", f"repository:{repo}:pull")}
        if fields.get("service"):
            qs["service"] = fields["service"]
        req = urllib.request.Request(
            realm + "?" + urllib.parse.urlencode(qs),
            headers={"Authorization": self._basic()} if self.username else {},
        )
        with self.transport(req, self.timeout) as resp:
            data = json.loads(resp.read())
        token = data.get("token") or data.get("access_token") or ""
        if not token:
            raise PermissionError("token endpoint returned no token")
        return "Bearer " + token

    def _get(self, url: str, headers: Dict[str, str]):
        req = urllib.request.Request(url, headers=headers)
        return self.transport(req, self.timeout)

    def _authed_get(self, url: str, repo: str, headers: Dict[str, str]):
        """GET with the current auth, driving the 401 token flow once."""
        hdrs = dict(headers)
        if self.token:
            hdrs["Authorization"] = self.token
        elif self.username:
            hdrs["Authorization"] = self._basic()
        try:
            return self._get(url, hdrs), hdrs.get("Authorization", "")
        except urllib.error.HTTPError as exc:
            challenge = exc.headers.get("WWW-Authenticate", "")
            if exc.code != 401 or not challenge.startswith("Bearer"):
                raise
            auth = self._fetch_token(challenge, repo)
            hdrs["Authorization"] = auth
            return self._get(url, hdrs), auth

    # -- manifests (getManifests + parseLayers) -----------------------------

    def _platform_matches(self, entry: dict) -> bool:
        if not self.platform:
            return True
        p = entry.get("platform") or {}
        want_os, _, want_arch = self.platform.partition("/")
        return p.get("os") == want_os and (
            not want_arch or p.get("architecture") == want_arch
        )

    def resolve_layers(self, manifest_url: str) -> ResolvedLayers:
        base, repo, _ref = parse_manifest_url(manifest_url)
        resp, auth = self._authed_get(
            manifest_url, repo, {"Accept": MANIFEST_ACCEPT}
        )
        with resp:
            media_type = resp.headers.get("Content-Type", "").split(";")[0]
            manifest = json.loads(resp.read())

        manifests = []
        if media_type in LIST_TYPES or "manifests" in manifest:
            entries = [
                e for e in manifest.get("manifests", [])
                if self._platform_matches(e)
            ]
            if not entries:
                raise LookupError(
                    f"no matching manifest for platform {self.platform!r}"
                )
            headers = {"Accept": MANIFEST_ACCEPT}
            if auth:
                headers["Authorization"] = auth
            for e in entries:
                sub_url = f"{base}/v2/{repo}/manifests/{e['digest']}"
                with self._get(sub_url, headers) as sub:
                    manifests.append(json.loads(sub.read()))
        else:
            manifests.append(manifest)

        urls: List[str] = []
        for m in manifests:
            for layer in m.get("layers") or m.get("fsLayers") or []:
                digest = layer.get("digest") or layer.get("blobSum")
                if digest:
                    urls.append(f"{base}/v2/{repo}/blobs/{digest}")
        if not urls:
            raise LookupError(f"manifest has no layers: {manifest_url}")
        headers = {"Authorization": auth} if auth else {}
        return ResolvedLayers(urls=urls, headers=headers)
