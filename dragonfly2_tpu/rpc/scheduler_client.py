"""RemoteScheduler: the client side of the scheduler wire API.

Implements the SchedulerService surface the daemon's Conductor uses
(register_peer / report_* / sync_probes_*) by forwarding over HTTP and
maintaining **local mirrors** of Host/Task/Peer — real resource classes —
so the conductor's code path is identical in embedded and remote modes
(the reference daemon likewise keeps local peer state synchronized with
the scheduler's view through the gRPC stream).
"""

from __future__ import annotations

import base64
import json
import threading
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Set, Tuple

from ..scheduler.resource import Host, Peer, Task
from ..scheduler.scheduling import ScheduleResult, ScheduleResultKind
from ..scheduler.service import RegisterResult
from ..utils.types import SizeScope
from .retry import retry_call
from .scheduler_server import host_from_wire, host_to_wire
from .version import PROTOCOL_VERSION


class RPCError(RuntimeError):
    def __init__(self, message: str, *, code: int = 0):
        super().__init__(message)
        self.code = code


class RemoteScheduler:
    def __init__(
        self,
        base_url: str,
        *,
        timeout: float = 10.0,
        protocol_version: Optional[int] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # protocol_version=1 is the N-1 SHIM: requests carry no version
        # field (byte-identical to pre-handshake clients) and v2-only
        # features stay off — tests/test_compat.py downloads through it
        # against the current scheduler every CI run.
        self.protocol_version = (
            PROTOCOL_VERSION if protocol_version is None else protocol_version
        )
        # What the server negotiated at announce (known after the first
        # announce_host; assume own version until told otherwise).
        self.negotiated_version = self.protocol_version
        self.server_capabilities: tuple = ()
        # Last ring payload the server re-published on announce (§24).
        self.scheduler_ring: Optional[dict] = None
        # Last tenant_qos payload re-published on announce (§26) and the
        # tenant identity stamped on this client's announces/registers
        # (the daemon's declared/derived tenant).
        self.tenant_qos: Optional[dict] = None
        self.tenant = ""
        self._mu = threading.Lock()
        self._tasks: Dict[str, Task] = {}
        self._hosts: Dict[str, Host] = {}
        self._peers: Dict[str, Peer] = {}
        self._announced: Set[str] = set()
        # Remote transport has no probe store mirrored locally.
        self.networktopology = None

    # -- wire ---------------------------------------------------------------

    def _call(
        self, method: str, req: dict, *, deadline_s: Optional[float] = None
    ) -> dict:
        def once() -> dict:
            from ..utils import faultinject
            from ..utils.tracing import default_tracer

            # Chaos seam: drop/delay/typed-error per call site, fired
            # INSIDE the retried attempt so injected faults exercise the
            # same retry machinery real transport failures do.
            faultinject.fire(f"rpc.client.{method}")

            body = json.dumps(req).encode()
            # Trace propagation (otelgrpc client-interceptor analog): the
            # caller's active span rides the wire so the server links its
            # handler span into the SAME trace.
            headers = {"Content-Type": "application/json"}
            headers.update(default_tracer.inject())
            http_req = urllib.request.Request(
                f"{self.base_url}/rpc/{method}",
                data=body,
                headers=headers,
                method="POST",
            )
            try:
                with urllib.request.urlopen(http_req, timeout=self.timeout) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                payload = exc.read()
                code = 0
                parsed: dict = {}
                try:
                    parsed = json.loads(payload)
                    message = parsed.get("error", "")
                    code = int(parsed.get("code", 0))
                except json.JSONDecodeError:
                    message = payload[:200].decode(errors="replace")
                # Sharded-fleet steering answers (DESIGN.md §24) surface
                # as their typed exceptions so the ShardRouter can act on
                # them; neither is retryable against THIS endpoint.
                if exc.code == 421 and message == "wrong_shard":
                    from ..scheduler.sharding import WrongShardError

                    raise WrongShardError(
                        str(parsed.get("task_id", "")),
                        owner_id=str(parsed.get("owner_id", "")),
                        owner_url=str(parsed.get("owner_url", "")),
                        ring_version=int(parsed.get("ring_version", 0)),
                    ) from exc
                if exc.code == 503 and message == "shard_saturated":
                    from ..scheduler.sharding import ShardSaturatedError

                    raise ShardSaturatedError(
                        retry_after_s=float(parsed.get("retry_after_s", 1.0)),
                        reason=str(parsed.get("reason", "")),
                    ) from exc
                raise RPCError(
                    f"{method}: HTTP {exc.code}: {message}", code=code
                ) from exc

        return retry_call(
            once,
            retry_on=(ConnectionError, TimeoutError, OSError),
            deadline_s=deadline_s,
        )

    # -- mirrors ------------------------------------------------------------

    def _mirror_host(self, data: dict) -> Host:
        with self._mu:
            existing = self._hosts.get(data["id"])
            if existing is not None:
                # Refresh addresses: the server's parent entries carry the
                # host's CURRENT announce (a restarted daemon has a new
                # download_port) and resolve_host must follow it.
                existing.ip = data.get("ip", existing.ip)
                existing.port = data.get("port", existing.port)
                existing.download_port = data.get(
                    "download_port", existing.download_port
                )
                return existing
            host = host_from_wire(data)
            self._hosts[host.id] = host
            return host

    def _mirror_task(self, task_id: str, url: str) -> Task:
        with self._mu:
            task = self._tasks.get(task_id)
            if task is None:
                task = Task(task_id, url)
                self._tasks[task_id] = task
            return task

    def _mirror_parent(self, task: Task, data: dict) -> Peer:
        with self._mu:
            peer = self._peers.get(data["peer_id"])
        if peer is None:
            host = self._mirror_host(data["host"])
            peer = Peer(data["peer_id"], task, host)
            # Mirror state: remote parents are serveable by definition.
            peer.fsm.set_state("Running")
            with self._mu:
                self._peers[peer.id] = peer
        return peer

    # -- SchedulerService surface -------------------------------------------

    def announce_host(self, host: Host) -> None:
        req = {"host": host_to_wire(host)}
        if self.tenant:
            req["tenant"] = self.tenant
        if self.protocol_version >= 2:
            # The v1 shim sends NO version field — that absence is the
            # legacy dialect's signature (rpc/version.py).
            req["protocol_version"] = self.protocol_version
        resp = self._call("announce_host", req)
        proto = resp.get("protocol")
        if proto:
            # Downgrade to what the server negotiated; a v1 server
            # answers {} and we keep speaking the legacy dialect.
            self.negotiated_version = int(
                proto.get("negotiated", self.protocol_version)
            )
            self.server_capabilities = tuple(proto.get("capabilities", ()))
        elif self.protocol_version >= 2:
            # A pre-handshake server (rollback at the same URL): drop to
            # the legacy dialect AND forget the old server's advertised
            # capabilities — they described a different server.
            self.negotiated_version = 1
            self.server_capabilities = ()
        # Ring re-publication (DESIGN.md §24): the server's adopted
        # shard ring rides the announce answer; steering compositions
        # read it off the client after each announce fan-out.
        self.scheduler_ring = resp.get("scheduler_ring")
        # Tenant QoS re-publication (DESIGN.md §26): the daemon adopts
        # upload caps/weights off the same answer.
        qos = resp.get("tenant_qos")
        if isinstance(qos, dict) and qos:
            self.tenant_qos = qos
        with self._mu:
            self._hosts[host.id] = host
            self._announced.add(host.id)

    def register_peer(
        self,
        *,
        host: Host,
        url: str,
        peer_id: Optional[str] = None,
        task_id: Optional[str] = None,
        tag: str = "",
        application: str = "",
        priority=None,
        tenant: str = "",
        **_ignored,
    ) -> RegisterResult:
        with self._mu:
            announced = host.id in self._announced
        if not announced:
            # One announce per host per client; periodic re-announce is the
            # announcer's job, not every registration's.
            self.announce_host(host)
        # Client-generated peer id = idempotency key: a retried POST after a
        # timeout re-registers the SAME peer (the server's load_or_store
        # dedupes) instead of leaking an orphan.
        from ..utils import idgen

        peer_id = peer_id or idgen.peer_id(host.ip, host.hostname)
        req = {"host_id": host.id, "url": url, "peer_id": peer_id,
               "task_id": task_id, "tag": tag, "application": application,
               "tenant": tenant or self.tenant,
               "priority": int(priority) if priority is not None else 0}
        try:
            resp = self._call("register_peer", req)
        except RPCError as exc:
            from ..utils.dferrors import Code

            if exc.code != int(Code.NOT_FOUND):
                raise
            # Scheduler restarted (or GC'd the host) since our announce:
            # re-announce and retry once.
            self.announce_host(host)
            resp = self._call("register_peer", req)
        task = self._mirror_task(resp["task_id"], url)
        task.content_length = resp["content_length"]
        task.total_piece_count = resp["total_piece_count"]
        task.piece_size = resp.get("piece_size", 0)
        peer = Peer(resp["peer_id"], task, host)
        peer.fsm.set_state("ReceivedNormal")
        with self._mu:
            self._peers[peer.id] = peer

        schedule: Optional[ScheduleResult] = None
        if resp.get("need_back_to_source"):
            schedule = ScheduleResult(kind=ScheduleResultKind.NEED_BACK_TO_SOURCE)
        elif resp.get("failed"):
            schedule = ScheduleResult(kind=ScheduleResultKind.FAILED)
        elif resp.get("parents"):
            parents = [self._mirror_parent(task, p) for p in resp["parents"]]
            schedule = ScheduleResult(kind=ScheduleResultKind.PARENTS, parents=parents)
        else:
            schedule = ScheduleResult(kind=ScheduleResultKind.NEED_BACK_TO_SOURCE)
        direct = base64.b64decode(resp.get("direct_piece", "") or "")
        return RegisterResult(
            peer=peer,
            size_scope=SizeScope(resp["size_scope"]),
            schedule=schedule,
            direct_piece=direct,
        )

    def set_task_info(
        self, peer: Peer, content_length: int, total_piece_count: int, piece_size: int
    ) -> None:
        resp = self._call(
            "set_task_info",
            {
                "peer_id": peer.id,
                "content_length": content_length,
                "total_piece_count": total_piece_count,
                "piece_size": piece_size,
            },
        )
        task = peer.task
        task.content_length = resp["content_length"]
        task.total_piece_count = resp["total_piece_count"]
        task.piece_size = resp["piece_size"]

    def report_piece_finished(
        self, peer: Peer, number: int, *, parent_id: str = "", length: int = 0, cost_ns: int = 0
    ) -> None:
        peer.finish_piece(number, cost_ns, parent_id=parent_id, length=length)
        self._call(
            "report_piece_finished",
            {"peer_id": peer.id, "number": number, "parent_id": parent_id,
             "length": length, "cost_ns": cost_ns},
        )

    def report_pieces_finished(self, peer: Peer, pieces) -> None:
        """Batched piece results: ONE wire call for a linger window of
        finished pieces (the daemon's report batcher).  Mirror updates
        (Peer.finish_piece) run per entry exactly like the singles path."""
        items = []
        for p in pieces:
            number = int(p["number"])
            parent_id = p.get("parent_id", "")
            length = int(p.get("length", 0))
            cost_ns = int(p.get("cost_ns", 0))
            peer.finish_piece(number, cost_ns, parent_id=parent_id, length=length)
            items.append(
                {"number": number, "parent_id": parent_id,
                 "length": length, "cost_ns": cost_ns}
            )
        self._call(
            "report_pieces_finished", {"peer_id": peer.id, "pieces": items}
        )

    def report_piece_failed(self, peer: Peer, parent_id: str) -> ScheduleResult:
        peer.block_parents.add(parent_id)
        resp = self._call(
            "report_piece_failed", {"peer_id": peer.id, "parent_id": parent_id}
        )
        if resp.get("parents"):
            parents = [self._mirror_parent(peer.task, p) for p in resp["parents"]]
            return ScheduleResult(kind=ScheduleResultKind.PARENTS, parents=parents)
        if resp.get("need_back_to_source"):
            return ScheduleResult(kind=ScheduleResultKind.NEED_BACK_TO_SOURCE)
        return ScheduleResult(kind=ScheduleResultKind.FAILED)

    def report_peer_finished(self, peer: Peer) -> None:
        if peer.fsm.can("DownloadSucceeded"):
            peer.fsm.event("DownloadSucceeded")
        self._call("report_peer_finished", {"peer_id": peer.id})

    def report_peer_failed(self, peer: Peer) -> None:
        if peer.fsm.can("DownloadFailed"):
            peer.fsm.event("DownloadFailed")
        self._call("report_peer_failed", {"peer_id": peer.id})

    def set_task_direct_piece(self, peer: Peer, data: bytes) -> None:
        self._call(
            "set_task_direct_piece",
            {"peer_id": peer.id, "data_b64": base64.b64encode(data).decode()},
        )

    def mark_back_to_source(self, peer: Peer) -> None:
        if peer.fsm.can("DownloadBackToSource"):
            peer.fsm.event("DownloadBackToSource")
        peer.task.back_to_source_peers.add(peer.id)
        self._call("mark_back_to_source", {"peer_id": peer.id})

    def leave_peer(self, peer: Peer) -> None:
        if peer.fsm.can("Leave"):
            peer.fsm.event("Leave")
        self._call("leave_peer", {"peer_id": peer.id})

    def resolve_host(self, host_id: str) -> Tuple[str, int]:
        """host id → (ip, download_port) from the mirror table — the piece
        fetcher's address resolver."""
        with self._mu:
            host = self._hosts[host_id]
        return host.ip, host.download_port

    def sync_probes_start(self, host: Host) -> List[Host]:
        resp = self._call("sync_probes_start", {"host_id": host.id})
        return [self._mirror_host(t) for t in resp.get("targets", [])]

    def sync_probes_finished(self, host: Host, results: List[Tuple[str, int]]) -> None:
        self._call(
            "sync_probes_finished",
            {"host_id": host.id, "results": [[d, int(r)] for d, r in results]},
        )
