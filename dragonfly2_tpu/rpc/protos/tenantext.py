"""Runtime-assembled tenant-carrying scheduler messages (DESIGN.md §26).

The JSON dialect has carried a ``tenant`` field on announces and
registers since the QoS plane landed, but the checked-in
``dragonfly_pb2.py`` predates it — and the image carries no protoc to
regenerate.  ``dict_to_proto`` parses with ``ignore_unknown_fields``,
so on the gRPC wire the daemon's tenant stamp was silently DROPPED and
gRPC deployments degraded to the default tenant.

Like ``protos/batch.py``, this module assembles the extended messages
at import time in a sibling package (``dragonfly2tpu.tenantext``):

- ``RegisterPeerRequest``  — fields 1-7 identical to the base message,
  plus ``tenant = 8``;
- ``AnnounceHostRequest``  — ``host = 1`` / ``protocol_version = 2``
  identical, plus ``tenant = 3``;
- ``AnnouncePeerRequest``  — the bidi stream envelope, with the
  ``register`` arm retyped to the extended ``RegisterPeerRequest``
  (all other arms reference the base types).

Adding a field number is wire-compatible in both directions: an old
peer's bytes parse with ``tenant`` empty, and a new peer's bytes parse
on an old binary with the unknown field skipped (degrading, as
documented, to the default tenant).  If a future protoc regeneration
bakes ``tenant`` into ``dragonfly_pb2``, the base classes already
carry the field and this module hands them straight back.

Keep ``dragonfly.proto`` in sync — it documents these fields for the
day codegen returns.
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from . import dragonfly_pb2 as pb

_FILE = "dragonfly_tenant.proto"
_PKG = "dragonfly2tpu.tenantext"

_STR = descriptor_pb2.FieldDescriptorProto.TYPE_STRING
_I32 = descriptor_pb2.FieldDescriptorProto.TYPE_INT32
_I64 = descriptor_pb2.FieldDescriptorProto.TYPE_INT64
_MSG = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
_OPT = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL


def _add_field(msg, name, number, ftype, type_name=None, oneof_index=None):
    f = msg.field.add()
    f.name, f.number, f.type, f.label = name, number, ftype, _OPT
    if type_name is not None:
        f.type_name = type_name
    if oneof_index is not None:
        f.oneof_index = oneof_index
    return f


def _build():
    # A regenerated dragonfly_pb2 that already carries tenant wins.
    if "tenant" in pb.RegisterPeerRequest.DESCRIPTOR.fields_by_name:
        return (
            pb.AnnounceHostRequest,
            pb.RegisterPeerRequest,
            pb.AnnouncePeerRequest,
        )
    pool = descriptor_pool.Default()
    try:
        fd = pool.FindFileByName(_FILE)
    except KeyError:
        fdp = descriptor_pb2.FileDescriptorProto()
        fdp.name = _FILE
        fdp.package = _PKG
        fdp.syntax = "proto3"
        fdp.dependency.append("dragonfly.proto")

        reg = fdp.message_type.add()
        reg.name = "RegisterPeerRequest"
        _add_field(reg, "host_id", 1, _STR)
        _add_field(reg, "url", 2, _STR)
        _add_field(reg, "peer_id", 3, _STR)
        _add_field(reg, "task_id", 4, _STR)
        _add_field(reg, "tag", 5, _STR)
        _add_field(reg, "application", 6, _STR)
        _add_field(reg, "priority", 7, _I32)
        _add_field(reg, "tenant", 8, _STR)

        ann = fdp.message_type.add()
        ann.name = "AnnounceHostRequest"
        _add_field(ann, "host", 1, _MSG, ".dragonfly2tpu.WireHost")
        _add_field(ann, "protocol_version", 2, _I32)
        _add_field(ann, "tenant", 3, _STR)

        stream = fdp.message_type.add()
        stream.name = "AnnouncePeerRequest"
        stream.oneof_decl.add().name = "payload"
        _add_field(stream, "seq", 1, _I64)
        arms = (
            ("register", 2, f".{_PKG}.RegisterPeerRequest"),
            ("task_info", 3, ".dragonfly2tpu.SetTaskInfoRequest"),
            ("piece_finished", 4, ".dragonfly2tpu.ReportPieceFinishedRequest"),
            ("piece_failed", 5, ".dragonfly2tpu.ReportPieceFailedRequest"),
            ("peer_finished", 6, ".dragonfly2tpu.PeerRequest"),
            ("peer_failed", 7, ".dragonfly2tpu.PeerRequest"),
            ("back_to_source", 8, ".dragonfly2tpu.PeerRequest"),
            ("leave", 9, ".dragonfly2tpu.PeerRequest"),
            ("direct_piece", 10, ".dragonfly2tpu.DirectPieceRequest"),
            ("resume", 11, ".dragonfly2tpu.PeerRequest"),
        )
        for name, number, type_name in arms:
            _add_field(stream, name, number, _MSG, type_name, oneof_index=0)
        fd = pool.Add(fdp)

    def cls(name):
        desc = fd.message_types_by_name[name]
        try:
            return message_factory.GetMessageClass(desc)
        except AttributeError:  # protobuf < 4.21 spelling
            return message_factory.MessageFactory(pool).GetPrototype(desc)

    return (
        cls("AnnounceHostRequest"),
        cls("RegisterPeerRequest"),
        cls("AnnouncePeerRequest"),
    )


AnnounceHostRequest, RegisterPeerRequest, AnnouncePeerRequest = _build()
