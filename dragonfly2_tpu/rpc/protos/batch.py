"""Runtime-assembled proto messages (no protoc in the image).

``ReportPiecesFinishedRequest`` — the batched piece-report request — is
declared in ``dragonfly.proto`` for schema documentation, but the image
carries no protoc to regenerate ``dragonfly_pb2.py``.  This module
assembles the identical ``FileDescriptorProto`` at import time and adds
it to the default descriptor pool, which is wire-compatible with codegen
output (the generated module does exactly this with a serialized blob).
If a future regeneration bakes the message into ``dragonfly_pb2``, that
definition wins and this one is skipped.
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from . import dragonfly_pb2  # registers dragonfly.proto in the pool


def _build():
    # A regenerated dragonfly_pb2 that already carries the message wins —
    # adding a second definition to the pool would collide.
    existing = getattr(dragonfly_pb2, "ReportPiecesFinishedRequest", None)
    if existing is not None:
        return existing
    pool = descriptor_pool.Default()
    try:
        fd = pool.FindFileByName("dragonfly_batch.proto")
    except KeyError:
        fdp = descriptor_pb2.FileDescriptorProto()
        fdp.name = "dragonfly_batch.proto"
        fdp.package = "dragonfly2tpu"
        fdp.syntax = "proto3"
        fdp.dependency.append("dragonfly.proto")
        msg = fdp.message_type.add()
        msg.name = "ReportPiecesFinishedRequest"
        f1 = msg.field.add()
        f1.name, f1.number = "peer_id", 1
        f1.type = descriptor_pb2.FieldDescriptorProto.TYPE_STRING
        f1.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
        f2 = msg.field.add()
        f2.name, f2.number = "pieces", 2
        f2.type = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
        f2.type_name = ".dragonfly2tpu.ReportPieceFinishedRequest"
        f2.label = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
        fd = pool.Add(fdp)
    desc = fd.message_types_by_name["ReportPiecesFinishedRequest"]
    try:
        return message_factory.GetMessageClass(desc)
    except AttributeError:  # protobuf < 4.21 spelling
        return message_factory.MessageFactory(pool).GetPrototype(desc)


ReportPiecesFinishedRequest = _build()
