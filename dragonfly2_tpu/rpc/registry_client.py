"""RemoteRegistry: the manager model registry over its REST surface.

Reference counterparts: the trainer's managerclient.CreateModel
(pkg/rpc/manager/client/client_v1.go:101-122) and the scheduler's
model-version pull through dynconfig.  Implements the registry surface
that TrainerService (create_model) and ModelSubscriber
(active_model / load_artifact) consume, so both run unchanged against a
manager in another process.
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from ..manager.registry import Model, ModelState
from .retry import retry_call


def _model_from_json(data: dict) -> Model:
    return Model(
        id=data["id"],
        name=data["name"],
        type=data["type"],
        version=data["version"],
        scheduler_id=data["scheduler_id"],
        state=ModelState(data["state"]),
        evaluation=data.get("evaluation") or {},
        artifact_digest=data.get("artifact_digest", ""),  # pre-digest managers
    )


class RemoteRegistry:
    """``base_url`` may be one URL, a comma-separated replica list, or a
    shared ``ManagerEndpoints`` — model polls and artifact fetches fail
    over to the surviving manager replica mid-flight (the HA story's
    zero-degraded-mode contract: a subscriber poll only pins when ALL
    replicas are down)."""

    def __init__(
        self, base_url, *, timeout: float = 30.0, token: Optional[str] = None
    ):
        from .resolver import ManagerEndpoints

        self.endpoints = ManagerEndpoints.of(base_url, client="registry")
        self.timeout = timeout
        # Bearer token for managers running RBAC (security/tokens.py); the
        # trainer's create_model needs PEER, activation needs OPERATOR.
        self.token = token

    @property
    def base_url(self) -> str:
        return self.endpoints.current()

    def _headers(self) -> dict:
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    @staticmethod
    def _translate(exc: urllib.error.HTTPError):
        """HTTP status → the LOCAL registry's exception types, so callers
        written against ModelRegistry behave identically remotely."""
        try:
            message = json.loads(exc.read()).get("error", "")
        except (json.JSONDecodeError, ValueError):
            message = str(exc)
        if exc.code == 404:
            return KeyError(message or "not found")
        if exc.code == 400:
            return ValueError(message or "bad request")
        return RuntimeError(f"manager: HTTP {exc.code}: {message}")

    def _get(self, path: str, *, deadline_s: Optional[float] = None) -> Optional[dict]:
        def one_endpoint(base: str):
            from ..utils import faultinject

            faultinject.fire("rpc.registry.get")
            try:
                with urllib.request.urlopen(
                    base + path, timeout=self.timeout
                ) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                if exc.code == 404:
                    return None
                if exc.code == 503:
                    raise  # standby replica: endpoints.call fails over
                raise self._translate(exc) from exc

        def once():
            return self.endpoints.call(one_endpoint)

        # HTTPError is handled inside once(); connect-refused arrives as
        # URLError (an OSError, NOT ConnectionError) — include OSError so
        # transient manager restarts actually retry (scheduler_client's
        # pattern).  The endpoint sweep runs INSIDE each retry attempt:
        # backoff only engages once every replica has failed.
        return retry_call(
            once,
            retry_on=(ConnectionError, TimeoutError, OSError),
            deadline_s=deadline_s,
        )

    def _post(
        self, path: str, payload: dict, *, deadline_s: Optional[float] = None
    ) -> dict:
        def one_endpoint(base: str):
            from ..utils import faultinject

            faultinject.fire("rpc.registry.post")
            req = urllib.request.Request(
                base + path,
                data=json.dumps(payload).encode(),
                headers=self._headers(),
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                if exc.code == 503:
                    raise  # standby replica: endpoints.call fails over
                raise self._translate(exc) from exc

        def once():
            return self.endpoints.call(one_endpoint)

        return retry_call(
            once,
            retry_on=(ConnectionError, TimeoutError, OSError),
            deadline_s=deadline_s,
        )

    # -- the surfaces TrainerService / ModelSubscriber use -------------------

    def create_model(
        self,
        *,
        name: str,
        type: str,
        scheduler_id: str,
        artifact: bytes,
        evaluation: Optional[Dict[str, float]] = None,
        **_ignored,
    ) -> Model:
        data = self._post(
            "/api/v1/models",
            {
                "name": name,
                "type": type,
                "scheduler_id": scheduler_id,
                "artifact_b64": base64.b64encode(artifact).decode(),
                "evaluation": evaluation or {},
            },
        )
        return _model_from_json(data)

    def active_model(self, scheduler_id: str, name: str) -> Optional[Model]:
        data = self._get(
            "/api/v1/models:active?"
            + urllib.parse.urlencode({"scheduler_id": scheduler_id, "name": name})
        )
        return None if data is None else _model_from_json(data)

    def candidate_model(self, scheduler_id: str, name: str) -> Optional[Model]:
        data = self._get(
            "/api/v1/models:candidate?"
            + urllib.parse.urlencode({"scheduler_id": scheduler_id, "name": name})
        )
        return None if data is None else _model_from_json(data["model"])

    def load_artifact(self, model: Model) -> bytes:
        data = self._get(
            "/api/v1/models:artifact?" + urllib.parse.urlencode({"id": model.id})
        )
        if data is None:
            raise KeyError(f"artifact for {model.id} not found")
        blob = base64.b64decode(data["artifact_b64"])
        if model.artifact_digest:
            # Same end-to-end verification as the local registry — the
            # wire and the manager's blob store are both inside the
            # tamper/corruption perimeter this digest closes.
            import hashlib

            from ..manager.registry import ArtifactDigestError

            got = hashlib.sha256(blob).hexdigest()
            if got != model.artifact_digest:
                raise ArtifactDigestError(
                    f"{model.id}: artifact sha256 {got[:12]}… != recorded "
                    f"{model.artifact_digest[:12]}…"
                )
        return blob

    def list(
        self,
        *,
        scheduler_id: Optional[str] = None,
        name: Optional[str] = None,
        **_ignored,
    ) -> List[Model]:
        params = {}
        if scheduler_id:
            params["scheduler_id"] = scheduler_id
        if name:
            params["name"] = name
        data = self._get("/api/v1/models?" + urllib.parse.urlencode(params))
        return [_model_from_json(d) for d in (data or [])]

    def activate(self, model_id: str) -> Model:
        return _model_from_json(
            self._post(f"/api/v1/models/{model_id}:activate", {})
        )

    def deactivate(self, model_id: str) -> Model:
        return _model_from_json(
            self._post(f"/api/v1/models/{model_id}:deactivate", {})
        )

    def get(self, model_id: str) -> Optional[Model]:
        data = self._get(
            "/api/v1/models:get?" + urllib.parse.urlencode({"id": model_id})
        )
        return None if data is None else _model_from_json(data)
