"""Scheduler HTTP/JSON server: the wire binding of SchedulerService.

Reference counterpart: scheduler/rpcserver + pkg/rpc/scheduler/server —
a gRPC surface over the service layer.  Here the same service methods are
exposed as POST /rpc/<method> with JSON bodies (stdlib ThreadingHTTPServer;
a gRPC binding can sit on the identical adapter).  The server owns the
authoritative Host/Task/Peer state; clients hold ids.

Wire methods:
  announce_host      {host: {...stats}}                 → {}
  register_peer      {host_id, url, peer_id?, task_id?, tag?, application?}
                                                        → registration view
  set_task_info      {peer_id, content_length, total_piece_count, piece_size}
  report_piece_finished / report_piece_failed / report_peer_finished /
  report_peer_failed / leave_peer                        (by peer_id)
  sync_probes_start  {host_id}                          → {targets: [...]}
  sync_probes_finished {host_id, results: [[dest, rtt]]}
"""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler
from typing import Tuple

from ._server import ThreadedHTTPService
from .version import (
    BASE_CAPABILITIES,
    UnsupportedProtocolError,
    negotiate,
    protocol_info,
)

from ..scheduler.resource import Host, Peer
from ..scheduler.scheduling import ScheduleResultKind
from ..scheduler.service import SchedulerService
from ..scheduler.sharding import ShardSaturatedError, WrongShardError
from ..utils.dferrors import Code
from ..utils.types import HostType


def host_from_wire(data: dict) -> Host:
    h = Host(
        id=data["id"],
        hostname=data.get("hostname", ""),
        ip=data.get("ip", ""),
        port=data.get("port", 0),
        download_port=data.get("download_port", 0),
        type=HostType(data.get("type", 0)),
        concurrent_upload_limit=data.get("concurrent_upload_limit", 50),
    )
    net = data.get("network", {})
    h.stats.network.idc = net.get("idc", "")
    h.stats.network.location = net.get("location", "")
    h.stats.cpu.percent = data.get("cpu_percent", 0.0)
    h.stats.memory.used_percent = data.get("mem_used_percent", 0.0)
    return h


def schedule_to_wire(res) -> dict:
    """ScheduleResult → the wire dict both transports use for schedule
    responses (request-paired and server-pushed alike)."""
    out = {"need_back_to_source": False, "parents": []}
    if res.kind is ScheduleResultKind.PARENTS:
        out["parents"] = [
            {"peer_id": p.id, "host": host_to_wire(p.host)} for p in res.parents
        ]
    elif res.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE:
        out["need_back_to_source"] = True
    return out


def host_to_wire(h: Host) -> dict:
    return {
        "id": h.id,
        "hostname": h.hostname,
        "ip": h.ip,
        "port": h.port,
        "download_port": h.download_port,
        "type": int(h.type),
        "concurrent_upload_limit": h.concurrent_upload_limit,
        "network": {"idc": h.stats.network.idc, "location": h.stats.network.location},
    }


class SchedulerRPCAdapter:
    """Maps wire dicts ↔ the in-memory service (transport-independent)."""

    def __init__(self, service: SchedulerService) -> None:
        self.service = service
        # What THIS transport can do; the gRPC binding appends
        # "push-reschedule" (its bidi stream) — the HTTP wire must not
        # advertise pushes it cannot deliver.
        self.capabilities = tuple(BASE_CAPABILITIES)
        self._mu = threading.Lock()
        # Weak values: when the resource layer's GC reaps a peer, the wire
        # mapping evaporates with it instead of leaking one entry per
        # download for the scheduler's lifetime.
        import weakref

        self._peers: "weakref.WeakValueDictionary[str, Peer]" = (
            weakref.WeakValueDictionary()
        )

    def _peer(self, peer_id: str) -> Peer:
        with self._mu:
            peer = self._peers.get(peer_id)
        if peer is None:
            raise KeyError(f"unknown peer {peer_id}")
        return peer

    def _track(self, peer: Peer) -> None:
        with self._mu:
            self._peers[peer.id] = peer

    # -- methods -------------------------------------------------------------

    def announce_host(self, req: dict) -> dict:
        # Versioned handshake (rpc/version.py): a field-less request is
        # the v1 legacy dialect; too-old dialects get the typed refusal.
        # proto3 renders an unset int32 as 0 — both absence and 0 mean
        # the legacy v1 dialect.
        negotiated = negotiate(int(req.get("protocol_version") or 1))
        host = host_from_wire(req["host"])
        host.protocol_version = negotiated
        # The service owns the announce decode (stats refresh + columnar
        # write-on-arrival, DESIGN.md §18) — the adapter only negotiates.
        stored = self.service.announce_host(
            host, tenant=str(req.get("tenant", "") or "")
        )
        stored.protocol_version = negotiated
        out = {"protocol": protocol_info(negotiated, self.capabilities)}
        # Ring re-publication (DESIGN.md §24): the announce answer
        # carries the shard ring this scheduler adopted from dynconfig,
        # so every announcing peer converges on the SAME versioned
        # ownership map without its own manager dependency.
        guard = self.service.shard_guard
        if guard is not None:
            ring = guard.ring()
            if ring is not None and len(ring):
                out["scheduler_ring"] = ring.to_payload()
        # Tenant QoS re-publication (DESIGN.md §26, same discipline):
        # daemons adopt upload caps + weights off the announce answer.
        policy = self.service.qos_policy
        if policy is not None:
            out["tenant_qos"] = policy.to_payload()
        return out

    def register_peer(self, req: dict) -> dict:
        host = self.service.resource.host_manager.load(req["host_id"])
        if host is None:
            raise KeyError(f"unknown host {req['host_id']} (announce first)")
        from ..utils.types import Priority

        result = self.service.register_peer(
            host=host,
            url=req["url"],
            peer_id=req.get("peer_id"),
            task_id=req.get("task_id"),
            tag=req.get("tag", ""),
            application=req.get("application", ""),
            tenant=str(req.get("tenant", "") or ""),
            # Clamp: wire clients may send out-of-range levels; an invalid
            # priority must not fail the registration.
            priority=Priority(max(0, min(6, int(req.get("priority", 0) or 0)))),
        )
        peer = result.peer
        self._track(peer)
        task = peer.task
        out = {
            "peer_id": peer.id,
            "task_id": task.id,
            "size_scope": int(result.size_scope),
            "direct_piece": base64.b64encode(result.direct_piece).decode()
            if result.direct_piece
            else "",
            "content_length": task.content_length,
            "total_piece_count": task.total_piece_count,
            "piece_size": task.piece_size,
            "need_back_to_source": False,
            "parents": [],
        }
        if result.schedule is not None:
            if result.schedule.kind is ScheduleResultKind.PARENTS:
                out["parents"] = [
                    {"peer_id": p.id, "host": host_to_wire(p.host)}
                    for p in result.schedule.parents
                ]
            elif result.schedule.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE:
                out["need_back_to_source"] = True
            else:
                out["failed"] = True
        return out

    def set_task_info(self, req: dict) -> dict:
        peer = self._peer(req["peer_id"])
        self.service.set_task_info(
            peer,
            int(req["content_length"]),
            int(req["total_piece_count"]),
            int(req.get("piece_size", 4 << 20)),
        )
        task = peer.task
        return {
            "content_length": task.content_length,
            "total_piece_count": task.total_piece_count,
            "piece_size": task.piece_size,
        }

    def report_piece_finished(self, req: dict) -> dict:
        self.service.report_piece_finished(
            self._peer(req["peer_id"]),
            int(req["number"]),
            parent_id=req.get("parent_id", ""),
            length=int(req.get("length", 0)),
            cost_ns=int(req.get("cost_ns", 0)),
        )
        return {}

    def report_pieces_finished(self, req: dict) -> dict:
        self.service.report_pieces_finished(
            self._peer(req["peer_id"]),
            [
                {
                    "number": int(p["number"]),
                    "parent_id": p.get("parent_id", ""),
                    "length": int(p.get("length", 0)),
                    "cost_ns": int(p.get("cost_ns", 0)),
                }
                for p in req.get("pieces", [])
            ],
        )
        return {}

    def report_piece_failed(self, req: dict) -> dict:
        res = self.service.report_piece_failed(
            self._peer(req["peer_id"]), req.get("parent_id", "")
        )
        return schedule_to_wire(res)

    def report_peer_finished(self, req: dict) -> dict:
        self.service.report_peer_finished(self._peer(req["peer_id"]))
        return {}

    def report_peer_failed(self, req: dict) -> dict:
        self.service.report_peer_failed(self._peer(req["peer_id"]))
        return {}

    def set_task_direct_piece(self, req: dict) -> dict:
        self.service.set_task_direct_piece(
            self._peer(req["peer_id"]), base64.b64decode(req["data_b64"])
        )
        return {}

    def mark_back_to_source(self, req: dict) -> dict:
        self.service.mark_back_to_source(self._peer(req["peer_id"]))
        return {}

    def leave_peer(self, req: dict) -> dict:
        self.service.leave_peer(self._peer(req["peer_id"]))
        return {}

    def sync_probes_start(self, req: dict) -> dict:
        host = self.service.resource.host_manager.load(req["host_id"])
        if host is None:
            return {"targets": []}
        targets = self.service.sync_probes_start(host)
        return {"targets": [host_to_wire(t) for t in targets]}

    def sync_probes_finished(self, req: dict) -> dict:
        host = self.service.resource.host_manager.load(req["host_id"])
        if host is not None:
            self.service.sync_probes_finished(
                host, [(d, int(r)) for d, r in req.get("results", [])]
            )
        return {}

    def topology_rtt(self, req: dict) -> dict:
        """Observability read: THIS replica's folded probe-graph RTT for
        one edge (the nt-evaluator's ranking input) — how a deployed
        multi-replica e2e proves a probe pushed to replica A reached
        replica B's evaluator via the manager's shared-topology sync
        (the reference inspects this state in redis)."""
        nt = getattr(self.service, "networktopology", None)
        if nt is None:
            return {"rtt_ns": None}
        return {"rtt_ns": nt.average_rtt(req["src"], req["dst"])}

    METHODS = frozenset(
        {
            "announce_host",
            "register_peer",
            "set_task_info",
            "report_piece_finished",
            "report_pieces_finished",
            "report_piece_failed",
            "report_peer_finished",
            "report_peer_failed",
            "set_task_direct_piece",
            "mark_back_to_source",
            "leave_peer",
            "sync_probes_start",
            "sync_probes_finished",
            "topology_rtt",
        }
    )

    def dispatch(self, method: str, req: dict) -> dict:
        if method not in self.METHODS:
            raise KeyError(f"unknown method {method}")
        return getattr(self, method)(req)


class SchedulerHTTPServer:
    """POST /rpc/<method> with JSON bodies over ThreadingHTTPServer."""

    def __init__(
        self,
        service: SchedulerService,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        rate_limit=None,
    ):
        self.adapter = SchedulerRPCAdapter(service)
        adapter = self.adapter

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_POST(self):
                if rate_limit is not None and not rate_limit.take():
                    # interceptor.go rate limiter → 429 on the JSON wire.
                    from .metrics import RATE_LIMITED_TOTAL

                    RATE_LIMITED_TOTAL.inc(transport="http")
                    body = json.dumps(
                        {"error": "rate limit exceeded",
                         "code": int(Code.RESOURCE_EXHAUSTED)}
                    ).encode()
                    self.send_response(429)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if not self.path.startswith("/rpc/"):
                    self.send_error(404)
                    return
                method = self.path[len("/rpc/") :]
                length = int(self.headers.get("Content-Length", 0))
                try:
                    from ..utils.tracing import (
                        TRACEPARENT_HEADER,
                        default_tracer,
                    )

                    req = json.loads(self.rfile.read(length) or b"{}")
                    # Handler span linked to the caller's trace (otelgrpc
                    # server-interceptor analog): the §3.1 call stack is
                    # followable across processes by trace id.
                    with default_tracer.remote_span(
                        f"rpc/{method}",
                        self.headers.get(TRACEPARENT_HEADER),
                        transport="http",
                    ):
                        resp = adapter.dispatch(method, req)
                    body = json.dumps(resp).encode()
                    self.send_response(200)
                except KeyError as exc:
                    # Typed code rides the payload so clients branch on it,
                    # never on the human-readable message text.
                    body = json.dumps(
                        {"error": str(exc), "code": int(Code.NOT_FOUND)}
                    ).encode()
                    self.send_response(404)
                except UnsupportedProtocolError as exc:
                    body = json.dumps(
                        {"error": str(exc), "code": int(exc.code)}
                    ).encode()
                    self.send_response(400)
                except WrongShardError as exc:
                    # REDIRECT-style steering answer (DESIGN.md §24): 421
                    # Misdirected Request with the owning shard's address
                    # — the router re-announces there, it never retries
                    # here.
                    body = json.dumps(
                        {
                            "error": "wrong_shard",
                            "code": int(Code.FAILED_PRECONDITION),
                            "task_id": exc.task_id,
                            "owner_id": exc.owner_id,
                            "owner_url": exc.owner_url,
                            "ring_version": exc.ring_version,
                        }
                    ).encode()
                    self.send_response(421)
                except ShardSaturatedError as exc:
                    # Load shed: 503 + Retry-After (the §20 standby
                    # discipline) so a backlogged fleet backs off instead
                    # of dogpiling a melting shard.
                    body = json.dumps(
                        {
                            "error": "shard_saturated",
                            "code": int(Code.RESOURCE_EXHAUSTED),
                            "retry_after_s": exc.retry_after_s,
                            "reason": exc.reason,
                        }
                    ).encode()
                    self.send_response(503)
                    self.send_header(
                        "Retry-After", f"{exc.retry_after_s:.3f}"
                    )
                except Exception as exc:  # noqa: BLE001 — wire boundary
                    body = json.dumps(
                        {"error": str(exc), "code": int(Code.UNKNOWN)}
                    ).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._svc = ThreadedHTTPService(Handler, host, port, "scheduler-http")
        self.address: Tuple[str, int] = self._svc.address

    @property
    def url(self) -> str:
        return self._svc.url

    def serve(self) -> None:
        self._svc.serve()

    def stop(self) -> None:
        self._svc.stop()
