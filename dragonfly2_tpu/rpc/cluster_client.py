"""RemoteClusterClient: scheduler → manager registration + keepalive, REST.

Reference: the scheduler registers itself with the manager and ticks a
keepalive stream (scheduler/announcer/announcer.go:84-127,
manager_server_v2.go:749 KeepAlive).  This is the cross-process wire for
that loop: without registration the manager's sync_peers fan-out
(jobs/sync_peers.py enqueues to ``scheduler:{sched.id}`` for *registered*
schedulers only) can never reach the instance's job queue.

Duck-type: implements the ``cluster_manager`` seam the Announcer already
drives in-process (``register_scheduler(SchedulerInstance)`` +
``keepalive(id)``, scheduler/announcer.py) so there is ONE liveness loop
implementation — the Announcer's when a trainer link is configured, this
client's own ``serve()`` otherwise.  ``keepalive`` self-heals: a manager
that answers ``known=False`` (restart lost its in-memory cluster table)
gets an immediate re-registration, whichever loop is ticking.
"""

from __future__ import annotations

import logging
import random
import threading
import urllib.error
from typing import Optional

from ..jobs.remote import RemoteJobClient
from .retry import DecorrelatedJitterBackoff

logger = logging.getLogger(__name__)


class RemoteClusterClient:
    def __init__(
        self,
        manager_url,
        *,
        token: Optional[str] = None,
        timeout: float = 10.0,
        keepalive_interval_s: float = 20.0,  # < manager TTL (60 s)
        backoff_rng: Optional[random.Random] = None,
    ) -> None:
        # One shared bearer-authed JSON wrapper with the job wire —
        # manager_url may be a replica list / shared ManagerEndpoints
        # (rpc/resolver), so keepalives fail over with everything else.
        self._http = RemoteJobClient(manager_url, token=token, timeout=timeout)
        self.keepalive_interval_s = keepalive_interval_s
        # Failed keepalives back off with capped decorrelated jitter: a
        # manager bounce must not get the whole fleet's keepalives back
        # in one synchronized wave (thundering herd).  The RNG is
        # injectable for reproducible schedules in tests.
        self._backoff = DecorrelatedJitterBackoff(
            base=min(2.0, keepalive_interval_s),
            cap=max(keepalive_interval_s * 3.0, 2.0),
            rng=backoff_rng,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registration: Optional[dict] = None

    def _post(self, path: str, body: dict) -> dict:
        return self._http.call("POST", path, body)

    def register_scheduler(self, inst=None, **kw) -> bool:
        """Accepts a ``SchedulerInstance`` (the ClusterManager duck-type
        the Announcer calls) or the same fields as kwargs.  True on
        success.  Auth failures log at WARNING — a misconfigured token
        otherwise leaves fan-out jobs PENDING with no visible cause."""
        if inst is not None:
            kw = {
                "id": inst.id, "cluster_id": inst.cluster_id,
                "hostname": inst.hostname, "ip": inst.ip, "port": inst.port,
            }
        kw.setdefault("cluster_id", "default")
        self._registration = kw
        return self._try_register()

    def _try_register(self) -> bool:
        if self._registration is None:
            return False
        try:
            self._post("/api/v1/schedulers", self._registration)
            return True
        except urllib.error.HTTPError as exc:
            if exc.code in (401, 403):
                logger.warning(
                    "scheduler registration unauthorized (HTTP %d): check "
                    "manager_token role — sync_peers/preheat jobs will not "
                    "reach this scheduler until registration succeeds",
                    exc.code,
                )
            else:
                logger.warning("scheduler registration failed: %s", exc)
            return False
        except (urllib.error.URLError, OSError) as exc:
            logger.warning("manager unreachable for registration: %s", exc)
            return False

    def keepalive(self, instance_id: str) -> bool:
        """One liveness tick; self-heals an unknown instance (manager
        restart) by re-registering.  False only when the manager stays
        unreachable/unaware after the heal attempt."""
        try:
            reply = self._post(
                f"/api/v1/schedulers/{instance_id}:keepalive", {}
            )
            if bool(reply.get("known")):
                return True
        except urllib.error.HTTPError as exc:
            if exc.code in (401, 403):
                logger.warning(
                    "scheduler keepalive unauthorized (HTTP %d): check "
                    "manager_token role", exc.code,
                )
            return False
        except (urllib.error.URLError, OSError):
            return False
        # Heal only OUR instance — an unknown foreign id is just unknown.
        reg = self._registration
        if reg is not None and reg.get("id") == instance_id:
            return self._try_register()
        return False

    def serve(self) -> None:
        """Standalone keepalive loop — for compositions with no Announcer
        (the Announcer runs the identical tick itself when present).
        Failed ticks wait a decorrelated-jitter backoff instead of the
        fixed interval; a success resets to the normal cadence."""
        if self._thread is not None:
            return

        def loop() -> None:
            wait = self.keepalive_interval_s
            while not self._stop.wait(wait):
                reg = self._registration
                if reg is None:
                    wait = self.keepalive_interval_s
                elif self.keepalive(reg["id"]):
                    self._backoff.reset()
                    wait = self.keepalive_interval_s
                else:
                    wait = self._backoff.next()

        self._thread = threading.Thread(
            target=loop, name="cluster-keepalive", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
