"""Trainer wire transport: the scheduler→trainer dataset stream.

Reference: pkg/rpc/trainer/client (client_v1.go:82-97 ``Train`` client
stream) + trainer/rpcserver — the announcer ships both record files in
128 MiB chunks over one stream (announcer.go:144-237).

HTTP binding onto TrainerService:
  POST /train/open    {ip, hostname, scheduler_id}            → {session}
  POST /train/shard?session=&kind=&name=&seq=   raw body = columnar bytes
  POST /train/close   {session}                               → {run}
  GET  /train/run?key=                                        → run status

``RemoteTrainerSession`` mirrors TrainSession's surface so the announcer
works unchanged against local or remote trainers; shards stream in
128 MiB chunks (appended server-side in sequence order).
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional, Tuple

from ..trainer.service import TrainerService, TrainSession
from ._server import ThreadedHTTPService
from .retry import retry_call

UPLOAD_CHUNK_BYTES = 128 << 20  # announcer.go:39-41


class TrainerHTTPServer:
    def __init__(self, service: TrainerService, host: str = "127.0.0.1", port: int = 0):
        if service.data_dir is None:
            raise ValueError("remote ingest requires TrainerService(data_dir=...)")
        self.service = service
        self._mu = threading.Lock()
        self._sessions: Dict[str, TrainSession] = {}
        self._closed: Dict[str, str] = {}  # session id -> run key
        self._counter = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                parsed = urllib.parse.urlsplit(self.path)
                q = dict(urllib.parse.parse_qsl(parsed.query))
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    if parsed.path == "/train/open":
                        req = json.loads(body or b"{}")
                        session = outer.service.open_train_stream(
                            ip=req.get("ip", ""),
                            hostname=req.get("hostname", ""),
                            scheduler_id=req.get("scheduler_id", ""),
                        )
                        with outer._mu:
                            outer._counter += 1
                            sid = f"sess-{outer._counter}"
                            outer._sessions[sid] = session
                        self._json(200, {"session": sid})
                    elif parsed.path == "/train/shard":
                        with outer._mu:
                            session = outer._sessions.get(q.get("session", ""))
                        if session is None:
                            self._json(404, {"error": "unknown session"})
                            return
                        outer.service.receive_shard_bytes(
                            session,
                            q.get("kind", "download"),
                            q.get("name", "shard"),
                            body,
                            seq=int(q.get("seq", 0)),
                        )
                        self._json(200, {})
                    elif parsed.path == "/train/close":
                        req = json.loads(body or b"{}")
                        sid = req.get("session", "")
                        with outer._mu:
                            # Idempotent: a client retrying a close whose
                            # response was lost (training can outlive the
                            # client timeout) gets the SAME run key back.
                            done_key = outer._closed.get(sid)
                            session = outer._sessions.get(sid)
                        if done_key is not None:
                            self._json(200, {"run": done_key})
                            return
                        if session is None:
                            self._json(404, {"error": "unknown session"})
                            return
                        key = session.close_and_train(
                            synchronous=bool(req.get("synchronous", True))
                        )
                        with outer._mu:
                            outer._closed[sid] = key
                            outer._sessions.pop(sid, None)
                        self._json(200, {"run": key})
                    else:
                        self._json(404, {"error": "not found"})
                except Exception as exc:  # noqa: BLE001 — wire boundary
                    self._json(500, {"error": str(exc)})

            def do_GET(self):
                parsed = urllib.parse.urlsplit(self.path)
                q = dict(urllib.parse.parse_qsl(parsed.query))
                if parsed.path == "/train/run":
                    run = outer.service.runs.get(q.get("key", ""))
                    if run is None:
                        self._json(404, {"error": "unknown run"})
                        return
                    self._json(
                        200,
                        {
                            "key": run.key,
                            "done": run.done.is_set(),
                            "error": run.error,
                            "download_rows": run.download_rows,
                            "topology_rows": run.topology_rows,
                            "models": run.models,
                            "metrics": {
                                k: m.to_dict() for k, m in run.metrics.items()
                            },
                        },
                    )
                else:
                    self._json(404, {"error": "not found"})

        self._svc = ThreadedHTTPService(Handler, host, port, "trainer-http")
        self.address: Tuple[str, int] = self._svc.address

    @property
    def url(self) -> str:
        return self._svc.url

    def serve(self) -> None:
        self._svc.serve()

    def stop(self) -> None:
        self._svc.stop()


class RemoteTrainerSession:
    """TrainSession mirror over HTTP (the announcer's remote mode)."""

    def __init__(self, client: "RemoteTrainer", session_id: str):
        self._client = client
        self._session_id = session_id

    def _send_file(self, kind: str, path: str) -> None:
        name = os.path.basename(path)
        with open(path, "rb") as f:
            seq = 0
            while True:
                chunk = f.read(UPLOAD_CHUNK_BYTES)
                if not chunk and seq > 0:
                    break
                self._client._post_raw(
                    f"/train/shard?session={self._session_id}&kind={kind}"
                    f"&name={urllib.parse.quote(name)}&seq={seq}",
                    chunk,
                )
                seq += 1
                if len(chunk) < UPLOAD_CHUNK_BYTES:
                    break

    def send_download_shard(self, path: str) -> None:
        self._send_file("download", path)

    def send_network_topology_shard(self, path: str) -> None:
        self._send_file("networktopology", path)

    def close_and_train(self, *, synchronous: bool = True) -> str:
        resp = self._client._post_json(
            "/train/close", {"session": self._session_id, "synchronous": synchronous}
        )
        return resp["run"]


class RemoteTrainer:
    """Client mirroring TrainerService's announcer-facing surface."""

    def __init__(self, base_url: str, *, timeout: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.runs: "_RemoteRuns" = _RemoteRuns(self)

    def _post_raw(
        self, path: str, data: bytes, *, deadline_s: Optional[float] = None
    ) -> dict:
        def once() -> dict:
            from ..utils import faultinject

            faultinject.fire("trainer.rpc.post")
            req = urllib.request.Request(
                self.base_url + path, data=data, method="POST"
            )
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())

        return retry_call(
            once, retry_on=(ConnectionError, TimeoutError), deadline_s=deadline_s
        )

    def _post_json(self, path: str, payload: dict) -> dict:
        return self._post_raw(path, json.dumps(payload).encode())

    def _get(self, path: str) -> dict:
        from ..utils import faultinject

        faultinject.fire("trainer.rpc.get")
        with urllib.request.urlopen(self.base_url + path, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def open_train_stream(
        self, *, ip: str, hostname: str, scheduler_id: str
    ) -> RemoteTrainerSession:
        resp = self._post_json(
            "/train/open",
            {"ip": ip, "hostname": hostname, "scheduler_id": scheduler_id},
        )
        return RemoteTrainerSession(self, resp["session"])


class _RemoteRuns:
    """Dict-ish view of remote runs (announcer reads trainer.runs[key])."""

    def __init__(self, client: RemoteTrainer):
        self._client = client

    def __getitem__(self, key: str):
        data = self._client._get(f"/train/run?key={urllib.parse.quote(key)}")
        from ..trainer.train import EvalMetrics

        class _DoneView:
            def __init__(self, flag: bool):
                self._flag = flag

            def is_set(self) -> bool:
                return self._flag

        class RunView:
            pass

        run = RunView()
        run.key = data["key"]
        run.error = data["error"]
        run.download_rows = data["download_rows"]
        run.topology_rows = data["topology_rows"]
        run.models = data["models"]
        # Same surface as the local TrainRun: metrics values are
        # EvalMetrics and done answers is_set().
        run.metrics = {k: EvalMetrics(**v) for k, v in data["metrics"].items()}
        run.done = _DoneView(bool(data["done"]))
        return run
