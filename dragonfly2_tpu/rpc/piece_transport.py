"""HTTP piece data plane (reference: client/daemon/upload's HTTP piece
server + the daemon's piece-download HTTP client).

Server: GET /pieces/<task_id>/<number> → piece bytes (whole-piece), plus
GET /tasks/<task_id> with a Range header → assembled byte range
(upload_manager.go range semantics).  503 when the upload concurrency cap
is hit, 404 for missing pieces — the conductor treats both as piece
failures and reschedules.  Speaks HTTP/1.1 with keep-alive, and streams
piece/range bodies kernel→socket via ``os.sendfile`` from the storage
engine's data file when the deployment allows it (plain TCP, plain-file
engine; TLS and torn-body chaos scenarios ride the buffered path —
byte-identical by test, DESIGN.md §22).

Piece-metadata SUBSCRIPTION (peertask_piecetask_synchronizer.go):
GET /tasks/<task_id>/pieces?have=N&wait_ms=M long-polls — the response
is deferred until the parent holds MORE than N pieces (a mid-download
parent commits new data) or M milliseconds pass, so children learn a
downloading parent's new pieces as they land instead of one-shot
snapshots.

Client: HTTPPieceFetcher resolves a parent host id to its announced
(ip, download_port) — carried in the scheduler's parent responses — and
GETs pieces over a per-parent KEEP-ALIVE connection pool
(``PieceConnectionPool``) with retry/backoff: one dial amortizes over a
whole task instead of a fresh TCP (+TLS) handshake per 4 MiB piece.
Bodies land in a reusable per-thread buffer via ``readinto`` (no
per-chunk allocate-and-join).  The pool invalidates on breaker-open and
on parent re-resolve (a restarted parent announces a new port).
"""

from __future__ import annotations

import http.client
import logging
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Callable, Dict, List, Optional, Tuple

from ..daemon.upload import UploadBusy, UploadManager
from ..records import abi_contracts as _abi
from ..utils.metrics import default_registry as _mreg
from ._server import ThreadedHTTPService
from .retry import retry_call

logger = logging.getLogger(__name__)

# Long-poll wait clamp for /tasks/<id>/pieces — shared with the native
# in-engine server (native.cpp kLongPollMaxMs) via the ABI registry so
# both planes defer at most the same bound (DF020).
LONG_POLL_MAX_MS = _abi.constant("kLongPollMaxMs")

# Fleet telemetry sketch (DESIGN.md §23): the transport-level fetch wall
# (dial + request + body, retries included) — the layer below the
# conductor's hedge-plan samples, so a slow wire is distinguishable from
# a slow schedule in the fleet view.
PIECE_TRANSPORT_SECONDS = _mreg.sketch(
    "rpc_piece_fetch_seconds",
    "HTTPPieceFetcher.fetch wall latency (retries included)",
)


class PieceHTTPServer:
    def __init__(
        self,
        upload: UploadManager,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        ssl_context=None,
        use_sendfile: bool = True,
    ):
        self.upload = upload
        upload_ref = upload
        # sendfile writes the raw fd — with TLS the bytes must pass the
        # SSL layer, so TLS deployments keep the buffered path.
        sendfile_ok = (
            use_sendfile and ssl_context is None and hasattr(os, "sendfile")
        )
        self.sendfile_enabled = sendfile_ok
        stats_mu = threading.Lock()
        stats = {"connections": 0, "sendfile_serves": 0}
        self._stats_mu = stats_mu
        self._stats = stats

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive: the fetcher's connection pool reuses one TCP
            # connection across a task's pieces; HTTP/1.0 would close per
            # request and re-pay the handshake every 4 MiB.
            protocol_version = "HTTP/1.1"

            def setup(self):
                super().setup()
                with stats_mu:
                    stats["connections"] += 1

            def log_message(self, *args):
                pass

            def _send(self, code: int, body: bytes, ctype: str = "application/octet-stream"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_span(self, code: int, span: Tuple[str, int, int]) -> None:
                """Zero-copy body: headers through the normal writer, then
                the span kernel→socket via os.sendfile.  Headers are out
                by the time the stream starts — a mid-stream failure tears
                the connection (client length-checks catch it), exactly
                like a dying parent."""
                path, offset, length = span
                with open(path, "rb") as src:
                    self.send_response(code)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(length))
                    self.end_headers()
                    self.wfile.flush()
                    # socket.sendfile drives os.sendfile with proper
                    # handling of the handler socket's timeout mode (a
                    # raw os.sendfile on a timeout-mode fd EAGAINs once
                    # the send buffer fills).
                    sent = self.connection.sendfile(src, offset, length)
                    if sent != length:
                        raise BrokenPipeError(
                            f"sendfile sent {sent} of {length} bytes"
                        )
                with stats_mu:
                    stats["sendfile_serves"] += 1

            def do_GET(self):
                import time as _time
                import urllib.parse as _parse

                split = _parse.urlsplit(self.path)
                parts = split.path.strip("/").split("/")
                streaming = False
                # Requester-pays accounting (§26/§28): the fetching peer
                # stamps its tenant on the wire; the upload gate charges
                # THAT tenant's byte bucket, not the task owner's.
                req_tenant = self.headers.get("X-Dragonfly-Tenant") or None
                try:
                    if len(parts) == 3 and parts[0] == "pieces":
                        from ..utils import faultinject

                        task_id, number = parts[1], int(parts[2])
                        if sendfile_ok:
                            span = upload_ref.piece_sendfile_span(task_id, number)
                            if span is not None:
                                upload_ref.begin_upload(task_id, req_tenant)
                                ok = False
                                try:
                                    streaming = True
                                    self._send_span(200, span)
                                    ok = True
                                finally:
                                    upload_ref.end_upload(
                                        ok, span[2] if ok else 0, task_id,
                                        req_tenant,
                                    )
                                return
                        data = upload_ref.serve_piece(task_id, number,
                                                      req_tenant)
                        # Torn-body seam: a truncate fault serves a SHORT
                        # 200 — the client's length check must catch it.
                        data = faultinject.fire("piece.server.body", data)
                        self._send(200, data)
                        return
                    if len(parts) == 3 and parts[0] == "tasks" and parts[2] == "pieces":
                        # Piece-metadata sync (reference: SyncPieceTasks —
                        # peers learn which pieces a parent holds before
                        # fetching).  Body: the piece bitmap, one byte per
                        # piece.  With ?have=N&wait_ms=M this LONG-POLLS:
                        # the reply defers until the parent holds more
                        # than N pieces (synchronizer subscription).
                        task_id = parts[1]
                        q = dict(_parse.parse_qsl(split.query))
                        try:
                            have = int(q.get("have", -1))
                            wait_ms = min(
                                int(q.get("wait_ms", 0)), LONG_POLL_MAX_MS
                            )
                        except ValueError:
                            self.send_error(400)
                            return
                        deadline = _time.monotonic() + wait_ms / 1000.0
                        while True:
                            n_pieces = upload_ref.storage.n_pieces(task_id)
                            if (
                                n_pieces > 0
                                and upload_ref.storage.held_pieces(task_id) > have
                            ):
                                break
                            if _time.monotonic() >= deadline:
                                break
                            _time.sleep(0.02)
                        if n_pieces <= 0:
                            self.send_error(404)
                            return
                        bm = upload_ref.storage.piece_bitmap(task_id, n_pieces)
                        self._send(200, bytes(bm))
                        return
                    if len(parts) == 2 and parts[0] == "tasks":
                        from ..utils.httprange import (
                            RangeNotSatisfiable,
                            parse_range,
                        )

                        task_id = parts[1]
                        total = upload_ref.storage.engine.content_length(task_id)
                        # Shared RFC-7233 parser (utils/httprange) keeps
                        # this endpoint byte-identical with the proxy and
                        # the gateway; a task endpoint without a servable
                        # range has nothing to answer → 416 (its read IS
                        # the range read).
                        try:
                            span_rng = parse_range(
                                self.headers.get("Range", ""), total
                            )
                        except RangeNotSatisfiable:
                            span_rng = None
                        if span_rng is None:
                            self.send_error(416)
                            return
                        start, end = span_rng
                        if sendfile_ok:
                            span = upload_ref.range_sendfile_span(
                                task_id, start, end - start + 1
                            )
                            if span is not None:
                                upload_ref.begin_upload(task_id, req_tenant)
                                ok = False
                                try:
                                    streaming = True
                                    self._send_span(206, span)
                                    ok = True
                                finally:
                                    upload_ref.end_upload(
                                        ok, span[2] if ok else 0, task_id,
                                        req_tenant,
                                    )
                                return
                        piece_size = upload_ref.storage.engine.piece_size(task_id)
                        data = upload_ref.serve_range(
                            task_id, start, end - start + 1, piece_size,
                            req_tenant,
                        )
                        self._send(206, data)
                        return
                    self.send_error(404)
                except UploadBusy:
                    self.send_error(503)
                except KeyError:
                    self.send_error(404)
                except Exception:  # noqa: BLE001 — wire boundary
                    if streaming:
                        # Headers (and possibly a partial body) are out:
                        # the only honest signal left is a torn stream.
                        self.close_connection = True
                        return
                    self.send_error(500)

        self._svc = ThreadedHTTPService(Handler, host, port, "piece-http", ssl_context)
        self.address: Tuple[str, int] = self._svc.address

    @property
    def port(self) -> int:
        return self._svc.port

    @property
    def connections_accepted(self) -> int:
        """TCP connections this server has accepted — the pool-reuse
        tests' server-side evidence (pieces served ≫ connections)."""
        with self._stats_mu:
            return self._stats["connections"]

    @property
    def sendfile_serves(self) -> int:
        with self._stats_mu:
            return self._stats["sendfile_serves"]

    def serve(self) -> None:
        self._svc.serve()

    def stop(self) -> None:
        self._svc.stop()


class NativePieceServer:
    """PieceHTTPServer-compatible facade over the C++ in-engine server
    (native.cpp ps_serve): same wire contract, but piece/range bodies go
    kernel→socket via sendfile with no Python on the data path — the
    upload_manager.go-grade hot path (BENCHMARKS.md piece-plane table).

    Binds AND serves from __init__ (the engine has no separate bind
    phase); ``serve()`` is a compatibility no-op.
    """

    def __init__(
        self,
        upload: UploadManager,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        concurrent_limit: int = 64,
    ):
        import socket as _socket

        from ..native import NativePieceStore

        engine = upload.storage.engine
        if not isinstance(engine, NativePieceStore):
            raise TypeError(
                "NativePieceServer needs a native-engine DaemonStorage "
                "(prefer_native=True and a built libdragonfly_native.so)"
            )
        self.upload = upload
        self._engine = engine
        # The engine binds via inet_pton (IPv4 literal only); resolve
        # hostnames here so configs that worked with the Python server
        # (server.host: "localhost") keep working.
        bind_ip = _socket.gethostbyname(host)
        bound = engine.serve(bind_ip, port, concurrent_limit=concurrent_limit)
        self.address: Tuple[str, int] = (bind_ip, bound)

    @property
    def port(self) -> int:
        return self.address[1]

    @property
    def upload_count(self) -> int:
        """Pieces served (UploadManager.upload_count parity — the C++
        server accounts in-engine, ps_serve_stats2)."""
        return self._engine.serve_stats()[0]

    @property
    def bytes_served(self) -> int:
        return self._engine.serve_stats()[1]

    @property
    def batched_pieces(self) -> int:
        """Pieces served through a coalesced writev burst (§28 batched
        submission) — the bench's both-ends-amortized evidence."""
        return self._engine.serve_stats_full()["batched"]

    def serve(self) -> None:  # already serving — interface parity
        pass

    def stop(self) -> None:
        self._engine.serve_stop()


def make_piece_server(
    upload: UploadManager,
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    ssl_context=None,
    prefer_native: bool = True,
):
    """Best available piece server: the C++ in-engine one when the store
    runs the native engine (and no TLS is required — the native server
    speaks plain HTTP; mTLS deployments keep the Python server), else the
    Python ThreadingHTTPServer.  The upload manager's configured
    concurrency cap carries into the native server's 503 limit."""
    from ..native import NativePieceStore

    if (
        prefer_native
        and ssl_context is None
        and isinstance(getattr(upload.storage, "engine", None), NativePieceStore)
    ):
        try:
            return NativePieceServer(
                upload, host, port,
                concurrent_limit=getattr(upload, "concurrent_limit", 64),
            )
        except Exception as exc:  # noqa: BLE001 — unresolvable host / engine error
            import logging

            # Python server below handles what the engine cannot.
            logging.getLogger(__name__).warning(
                "native piece server unavailable, falling back: %s", exc
            )
    return PieceHTTPServer(upload, host, port, ssl_context=ssl_context)


class PieceConnectionPool:
    """Per-parent keep-alive HTTP connections to piece servers.

    Invalidation rules (DESIGN.md §22):

    - a connection that errored mid-roundtrip is DISCARDED, never pooled
      (the retry re-dials);
    - a parent whose resolved ``(ip, port)`` changed (restart → new
      announce) drops every pooled connection to the old address;
    - ``invalidate(parent)`` drains the parent outright — the fetcher
      calls it when that parent's circuit breaker lands OPEN, so a dead
      parent's sockets don't linger for the breaker's reset window.

    The pool lock guards only the idle lists; dialing and every byte of
    I/O happen OUTSIDE it (DF008).
    """

    def __init__(
        self,
        *,
        timeout: float = 30.0,
        ssl_context=None,
        max_idle_per_parent: int = 4,
    ) -> None:
        self.timeout = timeout
        self.ssl_context = ssl_context
        self.max_idle_per_parent = max_idle_per_parent
        self._mu = threading.Lock()
        self._idle: Dict[str, List[http.client.HTTPConnection]] = {}
        self._addr: Dict[str, Tuple[str, int]] = {}
        self.dials = 0
        self.reuses = 0

    def _dial(self, ip: str, port: int) -> http.client.HTTPConnection:
        from ..utils import faultinject

        faultinject.fire("piece.pool.connect")
        if self.ssl_context is not None:
            conn: http.client.HTTPConnection = http.client.HTTPSConnection(
                ip, port, timeout=self.timeout, context=self.ssl_context
            )
        else:
            conn = http.client.HTTPConnection(ip, port, timeout=self.timeout)
        conn.connect()
        with self._mu:
            self.dials += 1
        return conn

    def acquire(
        self, parent_id: str, ip: str, port: int
    ) -> http.client.HTTPConnection:
        """An idle connection to the parent's CURRENT address, else a
        fresh dial.  A changed address invalidates the stale pool first."""
        stale: List[http.client.HTTPConnection] = []
        conn = None
        with self._mu:
            if self._addr.get(parent_id) != (ip, port):
                stale = self._idle.pop(parent_id, [])
                self._addr[parent_id] = (ip, port)
            else:
                idle = self._idle.get(parent_id)
                if idle:
                    conn = idle.pop()
                    self.reuses += 1
        for s in stale:
            s.close()
        if conn is not None:
            return conn
        return self._dial(ip, port)

    def release(
        self, parent_id: str, conn: http.client.HTTPConnection, *, reusable: bool
    ) -> None:
        if reusable:
            with self._mu:
                # Address changed while this roundtrip was in flight →
                # the connection points at the OLD parent incarnation.
                addr_current = self._addr.get(parent_id) == (
                    conn.host, conn.port
                )
                idle = self._idle.setdefault(parent_id, [])
                if addr_current and len(idle) < self.max_idle_per_parent:
                    idle.append(conn)
                    return
        conn.close()

    def invalidate(self, parent_id: str) -> None:
        with self._mu:
            drained = self._idle.pop(parent_id, [])
        for conn in drained:
            conn.close()

    def idle_count(self, parent_id: str) -> int:
        with self._mu:
            return len(self._idle.get(parent_id, []))

    def close(self) -> None:
        with self._mu:
            drained = [c for conns in self._idle.values() for c in conns]
            self._idle.clear()
        for conn in drained:
            conn.close()


class _PieceUnavailable(Exception):
    """Permanent-for-this-parent HTTP status (404/410/...): fail without
    retry so the conductor reschedules immediately."""


class HTTPPieceFetcher:
    """Conductor's PieceFetcher over HTTP.

    ``resolve(host_id) → (ip, port)``: in the wire flow the scheduler's
    parent entries carry the announced address (scheduler_client mirrors
    them into Host objects); an explicit table also works for tests.

    ``pooled=True`` (default) rides the keep-alive connection pool;
    ``pooled=False`` keeps the pre-pool one-urlopen-per-piece path — the
    benchmark's reference arm and an operational escape hatch.
    """

    def __init__(
        self,
        resolve: Callable[[str], Tuple[str, int]],
        *,
        timeout: float = 30.0,
        metadata_timeout: float = 2.0,
        ssl_context=None,
        breaker_threshold: int = 6,
        breaker_reset_s: float = 2.0,
        pooled: bool = True,
        tenant: str = "",
    ):
        self._resolve = resolve
        self.timeout = timeout
        # Requester-pays QoS (§26/§28): this daemon's tenant rides every
        # piece GET as X-Dragonfly-Tenant so the serving peer charges the
        # REQUESTER's upload bucket, not the task owner's.
        self.tenant = tenant or ""
        # Per-parent circuit breakers: a dead parent's piece port fails
        # fast after `breaker_threshold` consecutive connect failures
        # instead of burning a connect timeout per piece — the conductor
        # sees the fast ConnectionError and reschedules immediately.
        # breaker_threshold=0 disables.
        from .retry import CircuitBreaker

        self._breaker_mu = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._breaker_threshold = breaker_threshold
        self._breaker_reset_s = breaker_reset_s
        self._breaker_cls = CircuitBreaker
        # Bitmap queries are a pre-fetch optimization — a blackholed parent
        # must not stall the download for the full piece timeout.
        self.metadata_timeout = metadata_timeout
        # mTLS: present this daemon's CA-issued identity to parents running
        # TLS piece servers (security.tls.client_context).
        self.ssl_context = ssl_context
        self._scheme = "https" if ssl_context is not None else "http"
        self.pooled = pooled
        self.pool = PieceConnectionPool(
            timeout=timeout, ssl_context=ssl_context
        )
        # Reusable per-thread body buffer: responses land via readinto
        # instead of a fresh allocate-and-join per piece.
        self._tls_buf = threading.local()

    def _breaker(self, parent_host_id: str):
        if not self._breaker_threshold:
            return None
        with self._breaker_mu:
            b = self._breakers.get(parent_host_id)
            if b is None:
                b = self._breaker_cls(
                    failure_threshold=self._breaker_threshold,
                    reset_timeout_s=self._breaker_reset_s,
                )
                self._breakers[parent_host_id] = b
            return b

    # -- body read into the reusable buffer ----------------------------------

    def _read_body(self, resp: http.client.HTTPResponse) -> bytes:
        length = resp.length
        if length is None:
            return resp.read()
        buf = getattr(self._tls_buf, "buf", None)
        if buf is None or len(buf) < length:
            buf = bytearray(max(length, 1 << 16))
            self._tls_buf.buf = buf
        view = memoryview(buf)
        got = 0
        while got < length:
            n = resp.readinto(view[got:length])
            if not n:
                break
            got += n
        if got < length:
            raise ConnectionError(
                f"short body: {got} of {length} bytes"
            )
        return bytes(view[:length])

    # -- piece fetch ---------------------------------------------------------

    # dflint: hotpath
    def fetch(
        self,
        parent_host_id: str,
        task_id: str,
        number: int,
        *,
        deadline_s: Optional[float] = None,
    ) -> bytes:
        ip, port = self._resolve(parent_host_id)
        path = f"/pieces/{task_id}/{number}"
        once = (
            self._make_pooled_once(parent_host_id, ip, port, path)
            if self.pooled
            else self._make_urlopen_once(ip, port, path)
        )
        breaker = self._breaker(parent_host_id)
        t0 = time.monotonic()
        try:
            body = retry_call(
                once, attempts=2, retry_on=(ConnectionError, TimeoutError),
                breaker=breaker,
                deadline_s=deadline_s,
            )
            PIECE_TRANSPORT_SECONDS.observe(time.monotonic() - t0)
            return body
        except Exception:
            # Breaker landed OPEN (this failure tripped it, or it was
            # already open): drain the parent's pooled sockets — they
            # point at a dependency now considered down.
            if breaker is not None and breaker.state == "open":
                self.pool.invalidate(parent_host_id)
            raise

    def _make_pooled_once(
        self, parent_host_id: str, ip: str, port: int, path: str
    ):
        def once() -> bytes:
            from ..utils import faultinject

            faultinject.fire("piece.fetch")
            conn = self.pool.acquire(parent_host_id, ip, port)
            reusable = False
            try:
                try:
                    conn.request("GET", path, headers=(
                        {"X-Dragonfly-Tenant": self.tenant}
                        if self.tenant else {}
                    ))
                    resp = conn.getresponse()
                    body = self._read_body(resp)
                except (http.client.HTTPException, OSError) as exc:
                    if isinstance(exc, (ConnectionError, TimeoutError)):
                        # Includes RemoteDisconnected: a server-closed
                        # keep-alive socket — the retry re-dials.
                        raise
                    raise ConnectionError(f"piece GET {path}: {exc}") from exc
                reusable = not resp.will_close
                if resp.status == 503:
                    raise ConnectionError("parent busy")  # retried
                if resp.status != 200:
                    # 404 etc.: permanent for this parent — fail at once
                    # so the conductor reschedules.
                    raise _PieceUnavailable(
                        f"HTTP {resp.status} from {ip}:{port}{path}"
                    )
                return faultinject.fire("piece.fetch.body", body)
            finally:
                self.pool.release(parent_host_id, conn, reusable=reusable)

        return once

    def _make_urlopen_once(self, ip: str, port: int, path: str):
        url = f"{self._scheme}://{ip}:{port}{path}"

        def once() -> bytes:
            from ..utils import faultinject

            faultinject.fire("piece.fetch")
            req = urllib.request.Request(url, headers=(
                {"X-Dragonfly-Tenant": self.tenant} if self.tenant else {}
            ))
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout, context=self.ssl_context
                ) as resp:
                    return faultinject.fire("piece.fetch.body", resp.read())
            except urllib.error.HTTPError as exc:
                if exc.code == 503:
                    raise ConnectionError("parent busy") from exc  # retried
                # 404 etc.: permanent for this parent — fail immediately so
                # the conductor reschedules (HTTPError is an OSError
                # subclass, which retry_call's default would retry).
                raise _PieceUnavailable(f"HTTP {exc.code} from {url}") from exc

        return once

    def close(self) -> None:
        self.pool.close()

    def native_endpoint(self, parent_host_id: str):
        """(ip, port) the in-engine fetch loop (native.pf_*) can dial for
        this parent, or None when the transport cannot be represented
        natively — TLS deployments (the engine speaks plain HTTP) and
        unresolvable parents stay on the Python path (§28 fallback
        matrix)."""
        if self.ssl_context is not None:
            return None
        try:
            return self._resolve(parent_host_id)
        except KeyError:
            return None

    def piece_bitmap(self, parent_host_id: str, task_id: str):
        """Which pieces the parent holds (None when unknown/unreachable)."""
        return self._bitmap_get(parent_host_id, f"/tasks/{task_id}/pieces",
                                self.metadata_timeout)

    def wait_piece_bitmap(
        self, parent_host_id: str, task_id: str, have: int, wait_s: float
    ):
        """Long-poll subscription: returns once the parent holds more than
        ``have`` pieces or the window closes (synchronizer semantics)."""
        wait_ms = max(int(wait_s * 1000), 0)
        return self._bitmap_get(
            parent_host_id,
            f"/tasks/{task_id}/pieces?have={have}&wait_ms={wait_ms}",
            wait_s + self.metadata_timeout,
        )

    def _bitmap_get(self, parent_host_id: str, path: str, timeout: float):
        from ..utils import faultinject

        try:
            ip, port = self._resolve(parent_host_id)
        except KeyError:
            return None
        url = f"{self._scheme}://{ip}:{port}{path}"
        try:
            faultinject.fire("piece.bitmap")
            with urllib.request.urlopen(
                url, timeout=timeout, context=self.ssl_context
            ) as resp:
                # Truncate seam: a torn bitmap body must be survivable
                # (the conductor treats a short bitmap as fewer pieces).
                return faultinject.fire("piece.bitmap.body", resp.read())
        except (urllib.error.URLError, OSError):
            return None


def resolver_from_hosts(hosts: Dict[str, "object"]) -> Callable[[str], Tuple[str, int]]:
    """Resolve from a host-id → Host mapping (the client's mirror table)."""

    def resolve(host_id: str) -> Tuple[str, int]:
        host = hosts[host_id]
        return host.ip, host.download_port

    return resolve
