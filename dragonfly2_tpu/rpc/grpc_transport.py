"""gRPC bindings of the wire adapters (binary protobuf transport).

Reference: pkg/rpc — every service speaks gRPC (scheduler server at
pkg/rpc/scheduler/server/server.go:64-95, trainer Train client stream at
pkg/rpc/trainer/client/client_v1.go:82-97).  The TPU build's adapters
(SchedulerRPCAdapter, TrainerService) are transport-independent, so this
module binds the SAME adapters the HTTP/JSON servers use onto grpc:

- messages: protos/dragonfly.proto, protoc-generated (no grpc codegen
  plugin in the image → method handlers and stubs are registered through
  grpc's generic-handler API, which is wire-identical);
- proto ↔ adapter-dict conversion via protobuf json_format with
  preserving_proto_field_name (the JSON mapping of the proto IS the
  HTTP wire schema), plus an int64 fix-up (proto3 JSON renders int64 as
  strings);
- GRPCRemoteScheduler reuses RemoteScheduler wholesale — only ``_call``
  swaps transports, so retry/mirroring/error semantics stay identical;
- Trainer.Train is a real client-streaming RPC: first chunk keys the
  session, data chunks append shards, stream end kicks training.
"""

from __future__ import annotations

import json
import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Tuple

import grpc
from google.protobuf.json_format import MessageToDict, ParseDict

from .protos import dragonfly_pb2 as pb
from .protos import tenantext as pbx
from .protos.batch import ReportPiecesFinishedRequest
from .scheduler_client import RemoteScheduler, RPCError

SCHEDULER_SERVICE = "dragonfly2tpu.Scheduler"
TRAINER_SERVICE = "dragonfly2tpu.Trainer"

# gRPC status → wire-stable dfcode (utils/dferrors.Code), so client-side
# recovery branches (e.g. register_peer's NOT_FOUND re-announce) behave
# identically on both transports.
def _grpc_to_dfcode():
    from ..utils.dferrors import Code

    return {
        grpc.StatusCode.NOT_FOUND: int(Code.NOT_FOUND),
        grpc.StatusCode.INVALID_ARGUMENT: int(Code.INVALID_ARGUMENT),
        grpc.StatusCode.UNAVAILABLE: int(Code.UNAVAILABLE),
        grpc.StatusCode.RESOURCE_EXHAUSTED: int(Code.RESOURCE_EXHAUSTED),
        grpc.StatusCode.FAILED_PRECONDITION: int(Code.FAILED_PRECONDITION),
    }


_GRPC_TO_DFCODE = _grpc_to_dfcode()


# -- sharded-fleet steering parity (DESIGN.md §24/§25) -----------------------
#
# The HTTP wire answers steering as 421/503 bodies; the gRPC wire maps
# the SAME typed errors onto status codes + TRAILING METADATA so a
# daemon behind either transport raises the identical exception and the
# ShardRouter follows both without knowing which wire it rides:
#
#   WrongShardError     → FAILED_PRECONDITION, df-steering=wrong_shard,
#                         df-owner-id / df-owner-url / df-ring-version
#   ShardSaturatedError → RESOURCE_EXHAUSTED, df-steering=shard_saturated,
#                         retry-after (seconds) / df-reason
#
# (RESOURCE_EXHAUSTED is shared with the rate limiter; the df-steering
# key is what disambiguates — absence keeps the plain RPCError path.)

def _steering_trailers(exc) -> tuple:
    from ..scheduler.sharding import ShardSaturatedError, WrongShardError

    if isinstance(exc, WrongShardError):
        return (
            ("df-steering", "wrong_shard"),
            ("df-task-id", exc.task_id),
            ("df-owner-id", exc.owner_id),
            ("df-owner-url", exc.owner_url),
            ("df-ring-version", str(exc.ring_version)),
        )
    assert isinstance(exc, ShardSaturatedError)
    return (
        ("df-steering", "shard_saturated"),
        ("retry-after", f"{exc.retry_after_s:.3f}"),
        ("df-reason", exc.reason),
    )


def _steering_error_from_metadata(metadata):
    """Trailing metadata → the typed steering exception, or None."""
    md = {k: v for k, v in (metadata or ())}
    kind = md.get("df-steering")
    if kind == "wrong_shard":
        from ..scheduler.sharding import WrongShardError

        try:
            version = int(md.get("df-ring-version", 0) or 0)
        except ValueError:
            version = 0
        return WrongShardError(
            md.get("df-task-id", ""),
            owner_id=md.get("df-owner-id", ""),
            owner_url=md.get("df-owner-url", ""),
            ring_version=version,
        )
    if kind == "shard_saturated":
        from ..scheduler.sharding import ShardSaturatedError

        try:
            retry_after = float(md.get("retry-after", 1.0) or 1.0)
        except ValueError:
            retry_after = 1.0
        return ShardSaturatedError(
            retry_after_s=retry_after, reason=md.get("df-reason", "")
        )
    return None


def _steering_error_to_stream(exc) -> str:
    """Bidi-stream encoding: the response's ``error`` field carries the
    steering payload as ``<kind>:<json>`` (streams have no per-message
    trailers to ride)."""
    from ..scheduler.sharding import WrongShardError

    if isinstance(exc, WrongShardError):
        return "wrong_shard:" + json.dumps({
            "task_id": exc.task_id,
            "owner_id": exc.owner_id,
            "owner_url": exc.owner_url,
            "ring_version": exc.ring_version,
        })
    return "shard_saturated:" + json.dumps({
        "retry_after_s": exc.retry_after_s,
        "reason": exc.reason,
    })


def _steering_error_from_stream(error: str):
    """Stream ``error`` field → the typed steering exception, or None."""
    for kind in ("wrong_shard", "shard_saturated"):
        prefix = kind + ":"
        if not error.startswith(prefix):
            continue
        try:
            payload = json.loads(error[len(prefix):])
        except (ValueError, TypeError):
            return None
        if kind == "wrong_shard":
            from ..scheduler.sharding import WrongShardError

            return WrongShardError(
                str(payload.get("task_id", "")),
                owner_id=str(payload.get("owner_id", "")),
                owner_url=str(payload.get("owner_url", "")),
                ring_version=int(payload.get("ring_version", 0) or 0),
            )
        from ..scheduler.sharding import ShardSaturatedError

        return ShardSaturatedError(
            retry_after_s=float(payload.get("retry_after_s", 1.0) or 1.0),
            reason=str(payload.get("reason", "")),
        )
    return None


def _iter_until_closed(request_iterator):
    """Drain a server-side request stream, treating client cancel/close
    (grpc.RpcError mid-iteration) as normal end-of-stream."""
    while True:
        try:
            yield next(request_iterator)
        except StopIteration:
            return
        except grpc.RpcError:
            return

# method → (request message, response message); mirrors
# SchedulerRPCAdapter.METHODS exactly.
SCHEDULER_METHODS = {
    # announce_host/register_peer ride the tenant-extended messages
    # (protos/tenantext.py): same field numbers plus the §26 tenant
    # stamp the JSON dialect already carries.
    "announce_host": (pbx.AnnounceHostRequest, pb.AnnounceHostResponse),
    "register_peer": (pbx.RegisterPeerRequest, pb.RegisterPeerResponse),
    "set_task_info": (pb.SetTaskInfoRequest, pb.TaskInfoResponse),
    "report_piece_finished": (pb.ReportPieceFinishedRequest, pb.Empty),
    "report_pieces_finished": (ReportPiecesFinishedRequest, pb.Empty),
    "report_piece_failed": (pb.ReportPieceFailedRequest, pb.ScheduleResponse),
    "report_peer_finished": (pb.PeerRequest, pb.Empty),
    "report_peer_failed": (pb.PeerRequest, pb.Empty),
    "set_task_direct_piece": (pb.DirectPieceRequest, pb.Empty),
    "mark_back_to_source": (pb.PeerRequest, pb.Empty),
    "leave_peer": (pb.PeerRequest, pb.Empty),
    "sync_probes_start": (pb.HostRequest, pb.SyncProbesStartResponse),
    "sync_probes_finished": (pb.SyncProbesFinishedRequest, pb.Empty),
}

# proto3's JSON mapping renders int64 as decimal strings; the adapters
# expect Python ints for these keys (at any nesting level).
_INT64_KEYS = frozenset(
    {"content_length", "length", "cost_ns", "rtt_ns", "seq",
     "download_rows", "topology_rows"}
)


def _fix_int64(obj):
    if isinstance(obj, dict):
        return {
            k: int(v) if k in _INT64_KEYS and isinstance(v, str) else _fix_int64(v)
            for k, v in obj.items()
        }
    if isinstance(obj, list):
        return [_fix_int64(v) for v in obj]
    return obj


def proto_to_dict(msg) -> dict:
    # Defaults emitted: the adapters and RemoteScheduler index required
    # keys (resp["content_length"]) — the dict must match the HTTP wire
    # exactly, not protobuf's sparse JSON.
    return _fix_int64(
        MessageToDict(
            msg,
            preserving_proto_field_name=True,
            always_print_fields_with_no_presence=True,
        )
    )


def dict_to_proto(data: dict, msg_cls):
    return ParseDict(data, msg_cls(), ignore_unknown_fields=True)


def dict_to_proto_into(data: dict, msg) -> None:
    """Parse into an existing submessage (selects its oneof arm even when
    every field is default — SetInParent marks presence)."""
    msg.SetInParent()
    ParseDict(data, msg, ignore_unknown_fields=True)


def _to_wire_probe_results(req: dict) -> dict:
    """sync_probes_finished carries (dest, rtt) pairs in the dict schema;
    the proto uses ProbeResult messages."""
    out = dict(req)
    out["results"] = [
        {"dest": d, "rtt_ns": int(r)} for d, r in req.get("results", [])
    ]
    return out


def _from_wire_probe_results(req: dict) -> dict:
    out = dict(req)
    out["results"] = [
        (r.get("dest", ""), int(r.get("rtt_ns", 0)))
        for r in req.get("results", [])
    ]
    return out


class SchedulerGRPCServer:
    """Binds a SchedulerRPCAdapter onto a grpc server.

    Besides the unary methods, serves the bidi ``announce_peer`` stream
    (service_v2.go:89-207 AnnouncePeer analog): a PeerStreamHub is
    attached to the service so scheduling decisions made outside a peer's
    own request cycle (bad parents, parent death, stalls) are PUSHED to
    connected peers as seq=0 responses.
    """

    def __init__(
        self,
        service,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_workers: int = 16,
        server_credentials: Optional[grpc.ServerCredentials] = None,
        rate_limit=None,
    ) -> None:
        from ..scheduler.push import PeerStreamHub
        from .scheduler_server import SchedulerRPCAdapter

        self.adapter = SchedulerRPCAdapter(service)
        # This binding HAS the bidi push stream; advertise it.
        self.adapter.capabilities = self.adapter.capabilities + (
            "push-reschedule",
        )
        # Share the service's hub if the composition root made one;
        # otherwise create it (tests construct the server directly).
        if getattr(service, "hub", None) is None:
            service.hub = PeerStreamHub()
        self.hub = service.hub
        interceptors = ()
        if rate_limit is not None:
            from .ratelimit import RateLimitInterceptor

            interceptors = (RateLimitInterceptor(rate_limit),)
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=max_workers),
            interceptors=interceptors,
        )

        handlers = {}
        for method, (req_cls, resp_cls) in SCHEDULER_METHODS.items():
            handlers[method] = grpc.unary_unary_rpc_method_handler(
                self._behavior(method, resp_cls),
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
        handlers["announce_peer"] = grpc.stream_stream_rpc_method_handler(
            self._announce_peer,
            request_deserializer=pbx.AnnouncePeerRequest.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SCHEDULER_SERVICE, handlers),)
        )
        addr = f"{host}:{port}"
        if server_credentials is not None:
            bound = self._server.add_secure_port(addr, server_credentials)
        else:
            bound = self._server.add_insecure_port(addr)
        self.address: Tuple[str, int] = (host, bound)

    # oneof payload field → (adapter method, response oneof field)
    _STREAM_DISPATCH = {
        "register": ("register_peer", "registration"),
        "task_info": ("set_task_info", "task_info"),
        "piece_finished": ("report_piece_finished", "ack"),
        "piece_failed": ("report_piece_failed", "schedule"),
        "peer_finished": ("report_peer_finished", "ack"),
        "peer_failed": ("report_peer_failed", "ack"),
        "back_to_source": ("mark_back_to_source", "ack"),
        "leave": ("leave_peer", "ack"),
        "direct_piece": ("set_task_direct_piece", "ack"),
    }

    def _announce_peer(self, request_iterator, context):
        """One generator per connected daemon: requests demux to the same
        adapter the unary wire uses; a writer queue serializes the
        request-paired responses with hub pushes."""
        import queue
        import threading

        from ..scheduler.sharding import ShardSaturatedError, WrongShardError
        from ..utils.tracing import TRACEPARENT_HEADER, default_tracer
        from .metrics import GRPC_REQUESTS_TOTAL
        from .scheduler_server import schedule_to_wire

        # The stream's traceparent arrives ONCE in the invocation
        # metadata (one bidi stream per daemon); every dispatched message
        # opens its handler span against it so the stream wire has the
        # same flight-recorder coverage as the unary wire (DF016).
        stream_traceparent = None
        for key, value in context.invocation_metadata():
            if key == TRACEPARENT_HEADER:
                stream_traceparent = value

        out: "queue.Queue" = queue.Queue()
        registered: dict = {}  # peer_id → THIS stream's push callback

        def make_push(peer_id: str):
            def push(result) -> None:
                msg = pb.AnnouncePeerResponse(seq=0, peer_id=peer_id)
                dict_to_proto_into(schedule_to_wire(result), msg.schedule)
                out.put(msg)
            return push

        def reader() -> None:
            try:
                it = _iter_until_closed(request_iterator)
                for req in it:
                    kind = req.WhichOneof("payload")
                    resp = pb.AnnouncePeerResponse(seq=req.seq)
                    if kind == "resume":
                        # Reconnect: re-attach the push channel for a peer
                        # registered on a PREVIOUS stream (whose teardown
                        # unregistered it) — no adapter dispatch, so no
                        # duplicate peer records (ADVICE r2 finding).
                        # Validated against the adapter's live-peer table:
                        # a bogus/stale id must not leak a hub channel
                        # (cross-peer trust stays at the transport's mTLS
                        # layer, as for every other peer_id-carrying
                        # message on this stream).
                        pid = req.resume.peer_id
                        known = True
                        if pid:
                            try:
                                self.adapter._peer(pid)
                            except KeyError:
                                known = False
                        if pid and not known:
                            from ..utils.dferrors import Code

                            resp.error = f"resume: unknown peer {pid}"
                            resp.code = int(Code.NOT_FOUND)
                        elif pid:
                            registered[pid] = make_push(pid)
                            self.hub.register(pid, registered[pid])
                        out.put(resp)
                        continue
                    entry = self._STREAM_DISPATCH.get(kind)
                    if entry is None:
                        resp.error, resp.code = f"unknown payload {kind}", 0
                        out.put(resp)
                        continue
                    method, body_field = entry
                    try:
                        with default_tracer.remote_span(
                            f"rpc/{method}", stream_traceparent,
                            transport="grpc-stream",
                        ):
                            body = self.adapter.dispatch(
                                method, proto_to_dict(getattr(req, kind))
                            )
                        dict_to_proto_into(body, getattr(resp, body_field))
                        GRPC_REQUESTS_TOTAL.inc(
                            service="scheduler", method=f"stream/{method}",
                            code="OK",
                        )
                        if method == "register_peer":
                            pid = body["peer_id"]
                            registered[pid] = make_push(pid)
                            self.hub.register(pid, registered[pid])
                        elif method == "leave_peer":
                            pid = proto_to_dict(getattr(req, kind)).get(
                                "peer_id", ""
                            )
                            own = registered.pop(pid, None)
                            self.hub.unregister(pid, own)
                    except KeyError as exc:
                        from ..utils.dferrors import Code

                        resp.error, resp.code = str(exc), int(Code.NOT_FOUND)
                        GRPC_REQUESTS_TOTAL.inc(
                            service="scheduler", method=f"stream/{method}",
                            code="NOT_FOUND",
                        )
                    except (WrongShardError, ShardSaturatedError) as exc:
                        # Steering parity on the bidi wire: the typed
                        # payload rides the response error field (streams
                        # have no per-message trailers) and the client
                        # re-raises the SAME exception the HTTP wire
                        # would (§24/§25).
                        from ..utils.dferrors import Code

                        resp.error = _steering_error_to_stream(exc)
                        resp.code = int(
                            Code.FAILED_PRECONDITION
                            if isinstance(exc, WrongShardError)
                            else Code.RESOURCE_EXHAUSTED
                        )
                        GRPC_REQUESTS_TOTAL.inc(
                            service="scheduler", method=f"stream/{method}",
                            code=(
                                "FAILED_PRECONDITION"
                                if isinstance(exc, WrongShardError)
                                else "RESOURCE_EXHAUSTED"
                            ),
                        )
                    except Exception as exc:  # noqa: BLE001 — wire boundary
                        resp.error, resp.code = str(exc), 0
                        GRPC_REQUESTS_TOTAL.inc(
                            service="scheduler", method=f"stream/{method}",
                            code="UNKNOWN",
                        )
                    out.put(resp)
            finally:
                # The reader is the SOLE owner of `registered` (the
                # response generator must not clean up concurrently — a
                # client cancel would race its iteration against our
                # adds and leak hub registrations bound to a dead queue).
                # Ownership-aware: only evict channels still bound to THIS
                # stream — a reconnected stream's resume may already have
                # replaced them, and this (late) teardown must not undo it.
                for pid, own in registered.items():
                    self.hub.unregister(pid, own)
                out.put(None)

        t = threading.Thread(target=reader, name="announce-reader", daemon=True)
        t.start()
        while True:
            # Bounded get + loop (DF008 timeout sweep): the None sentinel
            # still terminates; the timeout only guarantees this thread
            # is visible in watchdog dumps instead of parked forever.
            try:
                item = out.get(timeout=30.0)
            except queue.Empty:
                continue
            if item is None:
                return
            yield item

    def _behavior(self, method: str, resp_cls):
        from .metrics import GRPC_REQUESTS_TOTAL

        def handle(request, context):
            # Exactly ONE count per call, whatever the outcome — error
            # spikes must be visible in rpc_grpc_requests_total.
            counted = [False]

            def count(code: str) -> None:
                if not counted[0]:
                    counted[0] = True
                    GRPC_REQUESTS_TOTAL.inc(
                        service="scheduler", method=method, code=code
                    )

            try:
                from ..utils.tracing import TRACEPARENT_HEADER, default_tracer

                traceparent = None
                for key, value in context.invocation_metadata():
                    if key == TRACEPARENT_HEADER:
                        traceparent = value
                req = proto_to_dict(request)
                if method == "sync_probes_finished":
                    req = _from_wire_probe_results(req)
                from ..scheduler.sharding import (
                    ShardSaturatedError,
                    WrongShardError,
                )

                try:
                    # otelgrpc server-interceptor analog: handler span
                    # linked into the caller's trace.
                    with default_tracer.remote_span(
                        f"rpc/{method}", traceparent, transport="grpc"
                    ):
                        out = self.adapter.dispatch(method, req)
                except KeyError as exc:
                    count("NOT_FOUND")
                    context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
                except WrongShardError as exc:
                    # Steering parity with the HTTP 421 answer (§24): a
                    # typed status + trailing metadata carrying the
                    # owner hint, so the client re-announces there.
                    count("FAILED_PRECONDITION")
                    context.set_trailing_metadata(_steering_trailers(exc))
                    context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION, "wrong_shard"
                    )
                except ShardSaturatedError as exc:
                    # Load shed parity with HTTP 503 + Retry-After.
                    count("RESOURCE_EXHAUSTED")
                    context.set_trailing_metadata(_steering_trailers(exc))
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED, "shard_saturated"
                    )
                except (ValueError, TypeError) as exc:
                    count("INVALID_ARGUMENT")
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
                resp = dict_to_proto(out, resp_cls)
            except Exception:
                count("UNKNOWN")  # no-op on the already-counted abort paths
                raise
            count("OK")
            return resp

        return handle

    @property
    def target(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def serve(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class GRPCRemoteScheduler(RemoteScheduler):
    """RemoteScheduler over gRPC: same mirrors/retries/errors, binary
    transport.  ``target`` is host:port."""

    def __init__(
        self,
        target: str,
        *,
        timeout: float = 10.0,
        channel_credentials: Optional[grpc.ChannelCredentials] = None,
        protocol_version: Optional[int] = None,
    ) -> None:
        # base_url is only used by HTTP _call, which we override.
        super().__init__(
            f"grpc://{target}", timeout=timeout,
            protocol_version=protocol_version,
        )
        if channel_credentials is not None:
            self._channel = grpc.secure_channel(target, channel_credentials)
        else:
            self._channel = grpc.insecure_channel(target)
        self._stubs = {}
        for method, (req_cls, resp_cls) in SCHEDULER_METHODS.items():
            self._stubs[method] = self._channel.unary_unary(
                f"/{SCHEDULER_SERVICE}/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )

    def _call(
        self, method: str, req: dict, *, deadline_s: Optional[float] = None
    ) -> dict:
        from .retry import retry_call

        req_cls, _ = SCHEDULER_METHODS[method]
        if method == "sync_probes_finished":
            req = _to_wire_probe_results(req)
        msg = dict_to_proto(req, req_cls)

        def once():
            from ..utils import faultinject
            from ..utils.tracing import default_tracer

            faultinject.fire(f"grpc.client.{method}")
            metadata = tuple(default_tracer.inject().items()) or None
            try:
                return self._stubs[method](
                    msg, timeout=self.timeout, metadata=metadata
                )
            except grpc.RpcError as exc:
                code = exc.code()
                # Steering answers surface as their typed exceptions on
                # BOTH transports (§24/§25): the ShardRouter acts on
                # them identically, never knowing which wire it rode.
                steering = _steering_error_from_metadata(
                    exc.trailing_metadata()
                    if hasattr(exc, "trailing_metadata") else None
                )
                if steering is not None:
                    raise steering from exc
                if code in (
                    grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                ):
                    # Transient: same retry class as the HTTP transport's
                    # ConnectionError/TimeoutError set.
                    raise ConnectionError(
                        f"{method}: gRPC {code.name}: {exc.details()}"
                    ) from exc
                raise RPCError(
                    f"{method}: gRPC {code.name}: {exc.details()}",
                    code=_GRPC_TO_DFCODE.get(code, 0),
                ) from exc

        resp = retry_call(
            once,
            retry_on=(ConnectionError, TimeoutError, OSError),
            deadline_s=deadline_s,
        )
        return proto_to_dict(resp)

    def close(self) -> None:
        self._channel.close()


class GRPCStreamingScheduler(GRPCRemoteScheduler):
    """RemoteScheduler whose per-peer methods ride ONE bidi
    ``announce_peer`` stream instead of per-call unary RPCs — the v2 wire:
    piece results flow up the stream, and the scheduler can PUSH parent
    lists down mid-download (seq=0 responses), consumed by the conductor
    via ``take_pushed_schedule``.

    announce_host / sync_probes stay unary (they are host-scoped, not
    download-scoped — the reference keeps them on separate RPCs too).
    On any stream failure the affected call falls back to the unary stub,
    so a mid-download scheduler restart degrades to round-1 behavior
    instead of failing the download.
    """

    # adapter method → request oneof field
    _STREAM_FIELDS = {
        "register_peer": ("register", pbx.RegisterPeerRequest),
        "set_task_info": ("task_info", pb.SetTaskInfoRequest),
        "report_piece_finished": ("piece_finished", pb.ReportPieceFinishedRequest),
        "report_piece_failed": ("piece_failed", pb.ReportPieceFailedRequest),
        "report_peer_finished": ("peer_finished", pb.PeerRequest),
        "report_peer_failed": ("peer_failed", pb.PeerRequest),
        "mark_back_to_source": ("back_to_source", pb.PeerRequest),
        "leave_peer": ("leave", pb.PeerRequest),
        "set_task_direct_piece": ("direct_piece", pb.DirectPieceRequest),
    }
    _RESPONSE_BODY = {
        "registration": pb.RegisterPeerResponse,
        "schedule": pb.ScheduleResponse,
        "task_info": pb.TaskInfoResponse,
    }

    def __init__(self, target: str, **kwargs) -> None:
        super().__init__(target, **kwargs)
        import queue
        import threading

        self._stream_mu = threading.Lock()
        self._sendq: Optional["queue.Queue"] = None
        self._waiters: dict = {}          # seq → (Event, [resp])
        self._pushed: dict = {}           # peer_id → latest pushed dict
        self._active_peers: set = set()   # downloads whose pushes we want
        self._seq = 0
        self._stream_stub = self._channel.stream_stream(
            f"/{SCHEDULER_SERVICE}/announce_peer",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.AnnouncePeerResponse.FromString,
        )

    # -- stream lifecycle ---------------------------------------------------

    def _ensure_stream(self):
        import queue
        import threading

        with self._stream_mu:
            if self._sendq is not None:
                return
            self._sendq = queue.Queue()
            sendq = self._sendq

            def request_iter():
                while True:
                    # Bounded get + loop (DF008 timeout sweep); the None
                    # sentinel still shuts the stream down.
                    try:
                        item = sendq.get(timeout=30.0)
                    except queue.Empty:
                        continue
                    if item is None:
                        return
                    yield item

            # Stream-scoped traceparent: the download span active at
            # stream open rides the invocation metadata once; the server
            # links every per-message handler span to it (the unary wire
            # injects per call — a stream only gets this one chance).
            from ..utils.tracing import default_tracer

            call = self._stream_stub(
                request_iter(),
                metadata=tuple(default_tracer.inject().items()) or None,
            )

            def read_loop():
                try:
                    for resp in call:
                        if resp.seq == 0:
                            body = resp.WhichOneof("body")
                            if body == "schedule" and resp.peer_id:
                                with self._stream_mu:
                                    # Bounded: a push racing a download's
                                    # completion must not accumulate
                                    # forever (terminal calls also clear
                                    # their entry).
                                    while len(self._pushed) >= 512:
                                        self._pushed.pop(
                                            next(iter(self._pushed))
                                        )
                                    self._pushed[resp.peer_id] = proto_to_dict(
                                        resp.schedule
                                    )
                            continue
                        with self._stream_mu:
                            waiter = self._waiters.pop(resp.seq, None)
                        if waiter is not None:
                            waiter[1].append(resp)
                            waiter[0].set()
                except Exception as exc:  # noqa: BLE001 — stream died
                    logging.getLogger(__name__).debug(
                        "announce stream read loop died: %s", exc
                    )
                finally:
                    # Wake every in-flight caller so they fall back to unary
                    # instead of blocking out the timeout.  Only clear the
                    # queue if it is still OURS — a reconnect may have
                    # already installed a fresh one.
                    with self._stream_mu:
                        dead = list(self._waiters.values())
                        self._waiters.clear()
                        if self._sendq is sendq:
                            self._sendq = None
                    for ev, _slot in dead:
                        ev.set()

            threading.Thread(
                target=read_loop, name="announce-read", daemon=True
            ).start()

            # Reconnect: the old stream's server-side teardown unregistered
            # every push channel — re-attach them for in-flight downloads
            # (resume carries no adapter dispatch, so no duplicate peers).
            # Fire-and-forget: the acks correlate to seqs nobody waits on.
            for pid in self._active_peers:
                self._seq += 1
                msg = pbx.AnnouncePeerRequest(seq=self._seq)
                msg.resume.peer_id = pid
                sendq.put(msg)

    def _stream_call(self, method: str, req: dict) -> dict:
        import threading

        field, req_cls = self._STREAM_FIELDS[method]
        self._ensure_stream()
        with self._stream_mu:
            self._seq += 1
            seq = self._seq
            ev: threading.Event = threading.Event()
            slot: list = []
            self._waiters[seq] = (ev, slot)
            sendq = self._sendq
        msg = pbx.AnnouncePeerRequest(seq=seq)
        dict_to_proto_into(req, getattr(msg, field))
        try:
            if sendq is None:
                raise ConnectionError("announce stream closed")
            sendq.put(msg)
            if not ev.wait(self.timeout) or not slot:
                raise ConnectionError(f"{method}: announce stream no response")
        finally:
            with self._stream_mu:
                self._waiters.pop(seq, None)
        # A finished download stops consuming pushes — drop any stale one.
        if method in ("report_peer_finished", "report_peer_failed", "leave_peer"):
            with self._stream_mu:
                self._pushed.pop(req.get("peer_id", ""), None)
        resp = slot[0]
        if resp.error:
            steering = _steering_error_from_stream(resp.error)
            if steering is not None:
                raise steering
            raise RPCError(f"{method}: {resp.error}", code=resp.code)
        body = resp.WhichOneof("body")
        return proto_to_dict(getattr(resp, body)) if body else {}

    def _call(self, method: str, req: dict) -> dict:
        if method not in self._STREAM_FIELDS:
            return super()._call(method, req)
        try:
            out = self._stream_call(method, req)
        except ConnectionError:
            # Stream broken (scheduler restart, network blip): unary
            # fallback keeps the download alive; next call retries the
            # stream via _ensure_stream.
            out = super()._call(method, req)
        # Track in-flight downloads so a reconnected stream can resume
        # their push registrations (covers unary-registered peers too —
        # their pushes come alive when a stream next establishes).
        if method == "register_peer" and out.get("peer_id"):
            with self._stream_mu:
                self._active_peers.add(out["peer_id"])
        elif method in (
            "report_peer_finished", "report_peer_failed", "leave_peer"
        ):
            with self._stream_mu:
                self._active_peers.discard(req.get("peer_id", ""))
        return out

    # -- pushed reschedules (conductor seam) --------------------------------

    def take_pushed_schedule(self, peer) -> Optional["object"]:
        """Latest server-pushed schedule for this peer, as a
        ScheduleResult with mirrored parents — or None."""
        from ..scheduler.scheduling import ScheduleResult, ScheduleResultKind

        with self._stream_mu:
            resp = self._pushed.pop(peer.id, None)
        if resp is None:
            return None
        if resp.get("parents"):
            parents = [
                self._mirror_parent(peer.task, p) for p in resp["parents"]
            ]
            return ScheduleResult(
                kind=ScheduleResultKind.PARENTS, parents=parents
            )
        if resp.get("need_back_to_source"):
            return ScheduleResult(kind=ScheduleResultKind.NEED_BACK_TO_SOURCE)
        return None

    def close(self) -> None:
        with self._stream_mu:
            sendq = self._sendq
            self._sendq = None
        if sendq is not None:
            sendq.put(None)
        super().close()


# 128 MiB protocol chunk + proto/field overhead headroom.
_TRAIN_MSG_CAP = (128 << 20) + (1 << 20)


class TrainerGRPCServer:
    """Trainer.Train client-streaming ingest + run-status lookups."""

    def __init__(
        self,
        service,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_workers: int = 8,
    ) -> None:
        if service.data_dir is None:
            raise ValueError("remote ingest requires TrainerService(data_dir=...)")
        self.service = service
        # The Train protocol frames datasets in 128 MiB chunks
        # (announcer.go:39-41); gRPC's default 4 MiB message cap would
        # reject the FIRST real chunk (caught by tools/bench_wire_ingest
        # — the tests' tiny shards never hit it).
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.max_receive_message_length", _TRAIN_MSG_CAP)],
        )
        handlers = {
            "Train": grpc.stream_unary_rpc_method_handler(
                self._train,
                request_deserializer=pb.TrainChunk.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
            "RunStatus": grpc.unary_unary_rpc_method_handler(
                self._run_status,
                request_deserializer=pb.RunStatusRequest.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(TRAINER_SERVICE, handlers),)
        )
        bound = self._server.add_insecure_port(f"{host}:{port}")
        self.address: Tuple[str, int] = (host, bound)

    def _train(self, request_iterator, context):
        session = None
        for chunk in request_iterator:
            if session is None:
                # First message keys the per-host dataset files
                # (service_v1.go:85-88 HostIDV2 keying).
                session = self.service.open_train_stream(
                    ip=chunk.ip, hostname=chunk.hostname,
                    scheduler_id=chunk.scheduler_id,
                )
                if not chunk.data:
                    continue
            self.service.receive_shard_bytes(
                session, chunk.kind or "download", chunk.name or "shard",
                bytes(chunk.data), seq=int(chunk.seq),
            )
        if session is None:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "empty Train stream")
        # EOF → train (service_v1.go:153-158; async like the goroutine).
        key = session.close_and_train(synchronous=False)
        return pb.TrainReply(run=key)

    def _run_status(self, request, context):
        run = self.service.runs.get(request.key)
        if run is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"unknown run {request.key}")
        return pb.RunStatusReply(
            key=run.key,
            done=run.done.is_set(),
            error=run.error or "",
            download_rows=run.download_rows,
            topology_rows=run.topology_rows,
            models=list(run.models),
            metrics_json=json.dumps(
                {k: m.to_dict() for k, m in run.metrics.items()}
            ),
        )

    @property
    def target(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def serve(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


MANAGER_SERVICE = "dragonfly2tpu.Manager"


def _model_to_proto(m) -> "pb.WireModel":
    return pb.WireModel(
        id=m.id, name=m.name, type=m.type, version=m.version,
        scheduler_id=m.scheduler_id, state=m.state.value,
        evaluation_json=json.dumps(m.evaluation),
    )


class ManagerGRPCServer:
    """Manager control plane over gRPC (manager/rpcserver v1/v2 analog):
    model registry RPCs incl. CreateModel, scheduler registration +
    keepalive, cluster search.

    With a ``token_verifier``, mutations require a bearer token in call
    metadata at the SAME role tiers as the REST surface (reads stay
    open, matching the reference's authenticated-writes posture) — the
    gRPC port must not be an RBAC bypass."""

    def __init__(
        self,
        registry,
        clusters,
        searcher=None,
        scheduler_clusters=None,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_workers: int = 16,
        token_verifier=None,
        users=None,
        rate_limit=None,
        server_credentials: Optional[grpc.ServerCredentials] = None,
        ca=None,
    ) -> None:
        from ..manager.searcher import Searcher
        from ..security.tokens import Role

        self.registry = registry
        self.clusters = clusters
        # Cluster CA for wire certificate issuance (certify analog) —
        # same instance as the REST surface's so both ports sign with
        # one trust root.  None → NOT_FOUND.
        self.ca = ca
        self.searcher = searcher or Searcher()
        self.scheduler_clusters = scheduler_clusters or []
        self.token_verifier = token_verifier
        # With a UserStore, personal access tokens authenticate here
        # exactly like on REST — both ports accept the same credentials.
        self.users = users
        # ONE bucket with the REST surface (cli/manager wires the same
        # instance): the configured qps bounds the SERVICE, not each
        # transport separately (scheduler CLI precedent).
        interceptors = ()
        if rate_limit is not None:
            from .ratelimit import RateLimitInterceptor

            interceptors = (RateLimitInterceptor(rate_limit),)
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=max_workers),
            interceptors=interceptors,
        )
        methods = {
            # name: (fn, req, resp, required role — None = open read)
            "create_model": (self._create_model, pb.CreateModelRequest, pb.WireModel, Role.PEER),
            "get_model": (self._get_model, pb.ModelIdRequest, pb.WireModel, None),
            "list_models": (self._list_models, pb.ListModelsRequest, pb.ListModelsReply, None),
            "active_model": (self._active_model, pb.ActiveModelRequest, pb.WireModel, None),
            "activate_model": (self._activate, pb.ModelIdRequest, pb.WireModel, Role.OPERATOR),
            "deactivate_model": (self._deactivate, pb.ModelIdRequest, pb.WireModel, Role.OPERATOR),
            "model_artifact": (self._artifact, pb.ModelIdRequest, pb.ArtifactReply, None),
            "register_scheduler": (self._register_scheduler, pb.RegisterSchedulerRequest, pb.Empty, Role.PEER),
            "keepalive": (self._keepalive, pb.KeepaliveRequest, pb.KeepaliveReply, Role.PEER),
            "list_schedulers": (self._list_schedulers, pb.Empty, pb.ListSchedulersReply, None),
            "search_clusters": (self._search, pb.ClusterSearchRequest, pb.ClusterSearchReply, None),
            "issue_certificate": (self._issue_certificate, pb.CertificateRequest, pb.CertificateReply, Role.PEER),
        }
        handlers = {}
        for name, (fn, req_cls, _resp_cls, role) in methods.items():
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                self._wrap(fn, role),
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(MANAGER_SERVICE, handlers),)
        )
        addr = f"{host}:{port}"
        if server_credentials is not None:
            bound = self._server.add_secure_port(addr, server_credentials)
        else:
            bound = self._server.add_insecure_port(addr)
        self.address: Tuple[str, int] = (host, bound)

    def _authorized(self, token, required_role) -> bool:
        from ..security.tokens import resolve_credential

        ident = resolve_credential(token, self.token_verifier, self.users)
        return ident is not None and ident[1] >= required_role

    def _wrap(self, fn, required_role):
        def handle(request, context):
            if required_role is not None and (
                self.token_verifier is not None or self.users is not None
            ):
                token = None
                for key, value in context.invocation_metadata():
                    if key == "authorization" and value.startswith("Bearer "):
                        token = value[len("Bearer "):]
                if not self._authorized(token, required_role):
                    context.abort(
                        grpc.StatusCode.PERMISSION_DENIED,
                        f"requires role >= {required_role.name}",
                    )
            try:
                return fn(request, context)
            except KeyError as exc:
                context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
            except (ValueError, TypeError) as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))

        return handle

    # -- model registry (manager_server_v1.go:802-901 + service/model.go) --

    def _create_model(self, req, context):
        m = self.registry.create_model(
            name=req.name, type=req.type, scheduler_id=req.scheduler_id,
            artifact=bytes(req.artifact),
            evaluation=json.loads(req.evaluation_json or "{}"),
        )
        return _model_to_proto(m)

    def _get_model(self, req, context):
        m = self.registry.get(req.id)
        if m is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"model {req.id}")
        return _model_to_proto(m)

    def _list_models(self, req, context):
        models = self.registry.list(
            scheduler_id=req.scheduler_id or None, name=req.name or None
        )
        return pb.ListModelsReply(models=[_model_to_proto(m) for m in models])

    def _active_model(self, req, context):
        m = self.registry.active_model(req.scheduler_id, req.name)
        if m is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "no active model")
        return _model_to_proto(m)

    def _activate(self, req, context):
        return _model_to_proto(self.registry.activate(req.id))

    def _deactivate(self, req, context):
        return _model_to_proto(self.registry.deactivate(req.id))

    def _artifact(self, req, context):
        m = self.registry.get(req.id)
        if m is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"model {req.id}")
        try:
            blob = self.registry.load_artifact(m)
        except (KeyError, OSError, ValueError) as exc:
            # Missing blob OR a failed digest check (ArtifactDigestError):
            # a clean NOT_FOUND — unverifiable bytes never leave the
            # registry on this wire either.
            context.abort(grpc.StatusCode.NOT_FOUND, f"artifact unavailable: {exc}")
        return pb.ArtifactReply(artifact=blob)

    # -- certificate issuance (pkg/issuer, security_server.go) --------------

    def _issue_certificate(self, req, context):
        if self.ca is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "no cluster CA configured")
        from ..security.ca import clamp_ttl

        ttl = clamp_ttl(req.ttl_hours)
        try:
            cert_pem = self.ca.sign_csr(bytes(req.csr_pem), ttl=ttl)
        except ValueError:
            raise
        except Exception as exc:  # noqa: BLE001 — x509 parse errors
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad csr: {exc}")
        return pb.CertificateReply(cert_pem=cert_pem, ca_pem=self.ca.cert_pem)

    # -- clusters (manager_server_v2.go keepalive, searcher) ----------------

    def _register_scheduler(self, req, context):
        from ..manager.cluster import SchedulerInstance

        self.clusters.register_scheduler(
            SchedulerInstance(
                id=req.id, cluster_id=req.cluster_id, ip=req.ip, port=req.port
            )
        )
        return pb.Empty()

    def _keepalive(self, req, context):
        return pb.KeepaliveReply(known=self.clusters.keepalive(req.instance_id))

    def _list_schedulers(self, req, context):
        return pb.ListSchedulersReply(
            schedulers=[
                pb.WireScheduler(
                    id=s.id, cluster_id=s.cluster_id, ip=s.ip, port=s.port,
                    state=s.state,
                )
                for s in self.clusters.active_schedulers()
            ]
        )

    def _search(self, req, context):
        try:
            ranked = self.searcher.find_scheduler_clusters(
                self.scheduler_clusters,
                ip=req.ip, hostname=req.hostname,
                conditions={"idc": req.idc, "location": req.location},
            )
        except LookupError as exc:
            context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
        return pb.ClusterSearchReply(cluster_ids=[c.id for c in ranked])

    @property
    def target(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def serve(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class GRPCRemoteRegistry:
    """Drop-in for rpc.registry_client.RemoteRegistry over gRPC — the
    trainer publishes models and the scheduler fetches scorers through
    the same surface."""

    def __init__(
        self,
        target: str,
        *,
        timeout: float = 60.0,
        token: str = "",
        channel_credentials: Optional[grpc.ChannelCredentials] = None,
    ) -> None:
        if channel_credentials is not None:
            self._channel = grpc.secure_channel(target, channel_credentials)
        else:
            self._channel = grpc.insecure_channel(target)
        self.timeout = timeout
        self.token = token
        self._stubs = {}
        for name, (req_cls, resp_cls) in {
            "create_model": (pb.CreateModelRequest, pb.WireModel),
            "get_model": (pb.ModelIdRequest, pb.WireModel),
            "list_models": (pb.ListModelsRequest, pb.ListModelsReply),
            "active_model": (pb.ActiveModelRequest, pb.WireModel),
            "activate_model": (pb.ModelIdRequest, pb.WireModel),
            "deactivate_model": (pb.ModelIdRequest, pb.WireModel),
            "model_artifact": (pb.ModelIdRequest, pb.ArtifactReply),
            "register_scheduler": (pb.RegisterSchedulerRequest, pb.Empty),
            "keepalive": (pb.KeepaliveRequest, pb.KeepaliveReply),
            "list_schedulers": (pb.Empty, pb.ListSchedulersReply),
            "search_clusters": (pb.ClusterSearchRequest, pb.ClusterSearchReply),
            "issue_certificate": (pb.CertificateRequest, pb.CertificateReply),
        }.items():
            self._stubs[name] = self._channel.unary_unary(
                f"/{MANAGER_SERVICE}/{name}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )

    def _call(
        self, name, msg, *, not_found_none: bool = False,
        deadline_s: Optional[float] = None,
    ):
        """Same exception contract as RemoteRegistry._translate — callers
        written against the local ModelRegistry behave identically:
        NOT_FOUND → KeyError (or None), INVALID_ARGUMENT → ValueError,
        transient UNAVAILABLE/DEADLINE retried."""
        from .retry import retry_call

        metadata = (
            [("authorization", f"Bearer {self.token}")] if self.token else None
        )

        def once():
            from ..utils import faultinject

            faultinject.fire(f"grpc.manager.{name}")
            try:
                return self._stubs[name](
                    msg, timeout=self.timeout, metadata=metadata
                )
            except grpc.RpcError as exc:
                code = exc.code()
                if code is grpc.StatusCode.NOT_FOUND:
                    if not_found_none:
                        return None
                    raise KeyError(exc.details()) from exc
                if code is grpc.StatusCode.INVALID_ARGUMENT:
                    raise ValueError(exc.details()) from exc
                if code in (
                    grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                ):
                    raise ConnectionError(
                        f"{name}: gRPC {code.name}: {exc.details()}"
                    ) from exc
                raise RPCError(
                    f"{name}: gRPC {code.name}: {exc.details()}",
                    code=_GRPC_TO_DFCODE.get(code, 0),
                ) from exc

        return retry_call(
            once,
            retry_on=(ConnectionError, TimeoutError, OSError),
            deadline_s=deadline_s,
        )

    @staticmethod
    def _model(m):
        from ..manager.registry import Model, ModelState

        return Model(
            id=m.id, name=m.name, type=m.type, version=m.version,
            scheduler_id=m.scheduler_id, state=ModelState(m.state),
            evaluation=json.loads(m.evaluation_json or "{}"),
        )

    def create_model(self, *, name, type, scheduler_id, artifact=b"",
                     evaluation=None):
        reply = self._call("create_model", pb.CreateModelRequest(
            name=name, type=type, scheduler_id=scheduler_id,
            artifact=artifact, evaluation_json=json.dumps(evaluation or {}),
        ))
        return self._model(reply)

    def get(self, model_id):
        reply = self._call(
            "get_model", pb.ModelIdRequest(id=model_id), not_found_none=True
        )
        return None if reply is None else self._model(reply)

    def list(self, scheduler_id=None, name=None):
        reply = self._call("list_models", pb.ListModelsRequest(
            scheduler_id=scheduler_id or "", name=name or ""
        ))
        return [self._model(m) for m in reply.models]

    def active_model(self, scheduler_id, name):
        reply = self._call("active_model", pb.ActiveModelRequest(
            scheduler_id=scheduler_id, name=name
        ), not_found_none=True)
        return None if reply is None else self._model(reply)

    def activate(self, model_id):
        return self._model(
            self._call("activate_model", pb.ModelIdRequest(id=model_id))
        )

    def deactivate(self, model_id):
        return self._model(
            self._call("deactivate_model", pb.ModelIdRequest(id=model_id))
        )

    def load_artifact(self, model):
        # WireModel carries no artifact_digest, so client-side digest
        # verification rides the REST registry path (registry_client.py);
        # the manager itself still verifies before serving either wire.
        reply = self._call("model_artifact", pb.ModelIdRequest(id=model.id))
        return bytes(reply.artifact)

    def register_scheduler(self, *, id, cluster_id, ip, port):
        self._call("register_scheduler", pb.RegisterSchedulerRequest(
            id=id, cluster_id=cluster_id, ip=ip, port=port
        ))

    def keepalive(self, instance_id):
        return self._call(
            "keepalive", pb.KeepaliveRequest(instance_id=instance_id)
        ).known

    def issue_certificate(self, csr_pem: bytes, *, ttl_hours: int = 0):
        """CSR → (cert_pem, ca_pem) signed by the manager's cluster CA."""
        reply = self._call("issue_certificate", pb.CertificateRequest(
            csr_pem=csr_pem, ttl_hours=ttl_hours
        ))
        return bytes(reply.cert_pem), bytes(reply.ca_pem)

    def list_schedulers(self):
        reply = self._call("list_schedulers", pb.Empty())
        return [
            {"id": s.id, "cluster_id": s.cluster_id, "ip": s.ip,
             "port": s.port, "state": s.state}
            for s in reply.schedulers
        ]

    def search_clusters(self, *, ip="", hostname="", idc="", location=""):
        reply = self._call("search_clusters", pb.ClusterSearchRequest(
            ip=ip, hostname=hostname, idc=idc, location=location
        ))
        return list(reply.cluster_ids)

    def close(self):
        self._channel.close()


class GRPCTrainerClient:
    """Scheduler-side Train stream (announcer.go's uploader over gRPC)."""

    # The HTTP transport keeps the announcer's 128 MiB framing
    # (announcer.go:39-41); grpc-python's per-message copy cost grows
    # with message size, so THIS client streams 4 MiB chunks — measured
    # 490 vs 131 MB/s against 128 MiB messages (tools/bench_wire_ingest
    # sweep, BENCHMARKS.md).  The server accepts either (seq-ordered
    # appends; receive cap still fits a 128 MiB-chunk sender).
    CHUNK_BYTES = 4 << 20

    def __init__(self, target: str, *, timeout: float = 600.0) -> None:
        self._channel = grpc.insecure_channel(
            target,
            options=[("grpc.max_send_message_length", _TRAIN_MSG_CAP)],
        )
        self.timeout = timeout
        self._train = self._channel.stream_unary(
            f"/{TRAINER_SERVICE}/Train",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.TrainReply.FromString,
        )
        self._status = self._channel.unary_unary(
            f"/{TRAINER_SERVICE}/RunStatus",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.RunStatusReply.FromString,
        )

    def train(
        self,
        *,
        ip: str,
        hostname: str,
        scheduler_id: str,
        download_shards=(),
        topology_shards=(),
    ) -> str:
        """Stream both dataset files in ``CHUNK_BYTES`` chunks over ONE
        stream (announcer.go:144-171 flow), returning the run key."""

        def chunks():
            yield pb.TrainChunk(ip=ip, hostname=hostname, scheduler_id=scheduler_id)
            for kind, paths in (
                ("download", download_shards),
                ("networktopology", topology_shards),
            ):
                for path in paths:
                    name = path.rsplit("/", 1)[-1]
                    seq = 0
                    with open(path, "rb") as f:
                        while True:
                            data = f.read(self.CHUNK_BYTES)
                            if not data:
                                break
                            yield pb.TrainChunk(
                                kind=kind, name=name, seq=seq, data=data
                            )
                            seq += 1

        try:
            reply = self._train(chunks(), timeout=self.timeout)
        except grpc.RpcError as exc:
            raise RPCError(
                f"Train: gRPC {exc.code().name}: {exc.details()}"
            ) from exc
        return reply.run

    def run_status(self, key: str) -> dict:
        try:
            r = self._status(pb.RunStatusRequest(key=key), timeout=30.0)
        except grpc.RpcError as exc:
            raise RPCError(
                f"RunStatus: gRPC {exc.code().name}: {exc.details()}"
            ) from exc
        return {
            "key": r.key,
            "done": r.done,
            "error": r.error,
            "download_rows": r.download_rows,
            "topology_rows": r.topology_rows,
            "models": list(r.models),
            "metrics": json.loads(r.metrics_json or "{}"),
        }

    def close(self) -> None:
        self._channel.close()
