"""gRPC bindings of the wire adapters (binary protobuf transport).

Reference: pkg/rpc — every service speaks gRPC (scheduler server at
pkg/rpc/scheduler/server/server.go:64-95, trainer Train client stream at
pkg/rpc/trainer/client/client_v1.go:82-97).  The TPU build's adapters
(SchedulerRPCAdapter, TrainerService) are transport-independent, so this
module binds the SAME adapters the HTTP/JSON servers use onto grpc:

- messages: protos/dragonfly.proto, protoc-generated (no grpc codegen
  plugin in the image → method handlers and stubs are registered through
  grpc's generic-handler API, which is wire-identical);
- proto ↔ adapter-dict conversion via protobuf json_format with
  preserving_proto_field_name (the JSON mapping of the proto IS the
  HTTP wire schema), plus an int64 fix-up (proto3 JSON renders int64 as
  strings);
- GRPCRemoteScheduler reuses RemoteScheduler wholesale — only ``_call``
  swaps transports, so retry/mirroring/error semantics stay identical;
- Trainer.Train is a real client-streaming RPC: first chunk keys the
  session, data chunks append shards, stream end kicks training.
"""

from __future__ import annotations

import json
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Tuple

import grpc
from google.protobuf.json_format import MessageToDict, ParseDict

from .protos import dragonfly_pb2 as pb
from .scheduler_client import RemoteScheduler, RPCError

SCHEDULER_SERVICE = "dragonfly2tpu.Scheduler"
TRAINER_SERVICE = "dragonfly2tpu.Trainer"

# gRPC status → wire-stable dfcode (utils/dferrors.Code), so client-side
# recovery branches (e.g. register_peer's NOT_FOUND re-announce) behave
# identically on both transports.
def _grpc_to_dfcode():
    from ..utils.dferrors import Code

    return {
        grpc.StatusCode.NOT_FOUND: int(Code.NOT_FOUND),
        grpc.StatusCode.INVALID_ARGUMENT: int(Code.INVALID_ARGUMENT),
        grpc.StatusCode.UNAVAILABLE: int(Code.UNAVAILABLE),
        grpc.StatusCode.RESOURCE_EXHAUSTED: int(Code.RESOURCE_EXHAUSTED),
        grpc.StatusCode.FAILED_PRECONDITION: int(Code.FAILED_PRECONDITION),
    }


_GRPC_TO_DFCODE = _grpc_to_dfcode()

# method → (request message, response message); mirrors
# SchedulerRPCAdapter.METHODS exactly.
SCHEDULER_METHODS = {
    "announce_host": (pb.AnnounceHostRequest, pb.Empty),
    "register_peer": (pb.RegisterPeerRequest, pb.RegisterPeerResponse),
    "set_task_info": (pb.SetTaskInfoRequest, pb.TaskInfoResponse),
    "report_piece_finished": (pb.ReportPieceFinishedRequest, pb.Empty),
    "report_piece_failed": (pb.ReportPieceFailedRequest, pb.ScheduleResponse),
    "report_peer_finished": (pb.PeerRequest, pb.Empty),
    "report_peer_failed": (pb.PeerRequest, pb.Empty),
    "set_task_direct_piece": (pb.DirectPieceRequest, pb.Empty),
    "mark_back_to_source": (pb.PeerRequest, pb.Empty),
    "leave_peer": (pb.PeerRequest, pb.Empty),
    "sync_probes_start": (pb.HostRequest, pb.SyncProbesStartResponse),
    "sync_probes_finished": (pb.SyncProbesFinishedRequest, pb.Empty),
}

# proto3's JSON mapping renders int64 as decimal strings; the adapters
# expect Python ints for these keys (at any nesting level).
_INT64_KEYS = frozenset(
    {"content_length", "length", "cost_ns", "rtt_ns", "seq",
     "download_rows", "topology_rows"}
)


def _fix_int64(obj):
    if isinstance(obj, dict):
        return {
            k: int(v) if k in _INT64_KEYS and isinstance(v, str) else _fix_int64(v)
            for k, v in obj.items()
        }
    if isinstance(obj, list):
        return [_fix_int64(v) for v in obj]
    return obj


def proto_to_dict(msg) -> dict:
    # Defaults emitted: the adapters and RemoteScheduler index required
    # keys (resp["content_length"]) — the dict must match the HTTP wire
    # exactly, not protobuf's sparse JSON.
    return _fix_int64(
        MessageToDict(
            msg,
            preserving_proto_field_name=True,
            always_print_fields_with_no_presence=True,
        )
    )


def dict_to_proto(data: dict, msg_cls):
    return ParseDict(data, msg_cls(), ignore_unknown_fields=True)


def _to_wire_probe_results(req: dict) -> dict:
    """sync_probes_finished carries (dest, rtt) pairs in the dict schema;
    the proto uses ProbeResult messages."""
    out = dict(req)
    out["results"] = [
        {"dest": d, "rtt_ns": int(r)} for d, r in req.get("results", [])
    ]
    return out


def _from_wire_probe_results(req: dict) -> dict:
    out = dict(req)
    out["results"] = [
        (r.get("dest", ""), int(r.get("rtt_ns", 0)))
        for r in req.get("results", [])
    ]
    return out


class SchedulerGRPCServer:
    """Binds a SchedulerRPCAdapter onto a grpc server."""

    def __init__(
        self,
        service,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_workers: int = 16,
        server_credentials: Optional[grpc.ServerCredentials] = None,
    ) -> None:
        from .scheduler_server import SchedulerRPCAdapter

        self.adapter = SchedulerRPCAdapter(service)
        self._server = grpc.server(ThreadPoolExecutor(max_workers=max_workers))

        handlers = {}
        for method, (req_cls, resp_cls) in SCHEDULER_METHODS.items():
            handlers[method] = grpc.unary_unary_rpc_method_handler(
                self._behavior(method, resp_cls),
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SCHEDULER_SERVICE, handlers),)
        )
        addr = f"{host}:{port}"
        if server_credentials is not None:
            bound = self._server.add_secure_port(addr, server_credentials)
        else:
            bound = self._server.add_insecure_port(addr)
        self.address: Tuple[str, int] = (host, bound)

    def _behavior(self, method: str, resp_cls):
        def handle(request, context):
            req = proto_to_dict(request)
            if method == "sync_probes_finished":
                req = _from_wire_probe_results(req)
            try:
                out = self.adapter.dispatch(method, req)
            except KeyError as exc:
                context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
            except (ValueError, TypeError) as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
            return dict_to_proto(out, resp_cls)

        return handle

    @property
    def target(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def serve(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class GRPCRemoteScheduler(RemoteScheduler):
    """RemoteScheduler over gRPC: same mirrors/retries/errors, binary
    transport.  ``target`` is host:port."""

    def __init__(
        self,
        target: str,
        *,
        timeout: float = 10.0,
        channel_credentials: Optional[grpc.ChannelCredentials] = None,
    ) -> None:
        # base_url is only used by HTTP _call, which we override.
        super().__init__(f"grpc://{target}", timeout=timeout)
        if channel_credentials is not None:
            self._channel = grpc.secure_channel(target, channel_credentials)
        else:
            self._channel = grpc.insecure_channel(target)
        self._stubs = {}
        for method, (req_cls, resp_cls) in SCHEDULER_METHODS.items():
            self._stubs[method] = self._channel.unary_unary(
                f"/{SCHEDULER_SERVICE}/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )

    def _call(self, method: str, req: dict) -> dict:
        from .retry import retry_call

        req_cls, _ = SCHEDULER_METHODS[method]
        if method == "sync_probes_finished":
            req = _to_wire_probe_results(req)
        msg = dict_to_proto(req, req_cls)

        def once():
            try:
                return self._stubs[method](msg, timeout=self.timeout)
            except grpc.RpcError as exc:
                code = exc.code()
                if code in (
                    grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                ):
                    # Transient: same retry class as the HTTP transport's
                    # ConnectionError/TimeoutError set.
                    raise ConnectionError(
                        f"{method}: gRPC {code.name}: {exc.details()}"
                    ) from exc
                raise RPCError(
                    f"{method}: gRPC {code.name}: {exc.details()}",
                    code=_GRPC_TO_DFCODE.get(code, 0),
                ) from exc

        resp = retry_call(once, retry_on=(ConnectionError, TimeoutError, OSError))
        return proto_to_dict(resp)

    def close(self) -> None:
        self._channel.close()


class TrainerGRPCServer:
    """Trainer.Train client-streaming ingest + run-status lookups."""

    def __init__(
        self,
        service,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_workers: int = 8,
    ) -> None:
        if service.data_dir is None:
            raise ValueError("remote ingest requires TrainerService(data_dir=...)")
        self.service = service
        self._server = grpc.server(ThreadPoolExecutor(max_workers=max_workers))
        handlers = {
            "Train": grpc.stream_unary_rpc_method_handler(
                self._train,
                request_deserializer=pb.TrainChunk.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
            "RunStatus": grpc.unary_unary_rpc_method_handler(
                self._run_status,
                request_deserializer=pb.RunStatusRequest.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(TRAINER_SERVICE, handlers),)
        )
        bound = self._server.add_insecure_port(f"{host}:{port}")
        self.address: Tuple[str, int] = (host, bound)

    def _train(self, request_iterator, context):
        session = None
        for chunk in request_iterator:
            if session is None:
                # First message keys the per-host dataset files
                # (service_v1.go:85-88 HostIDV2 keying).
                session = self.service.open_train_stream(
                    ip=chunk.ip, hostname=chunk.hostname,
                    scheduler_id=chunk.scheduler_id,
                )
                if not chunk.data:
                    continue
            self.service.receive_shard_bytes(
                session, chunk.kind or "download", chunk.name or "shard",
                bytes(chunk.data), seq=int(chunk.seq),
            )
        if session is None:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "empty Train stream")
        # EOF → train (service_v1.go:153-158; async like the goroutine).
        key = session.close_and_train(synchronous=False)
        return pb.TrainReply(run=key)

    def _run_status(self, request, context):
        run = self.service.runs.get(request.key)
        if run is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"unknown run {request.key}")
        return pb.RunStatusReply(
            key=run.key,
            done=run.done.is_set(),
            error=run.error or "",
            download_rows=run.download_rows,
            topology_rows=run.topology_rows,
            models=list(run.models),
            metrics_json=json.dumps(
                {k: m.to_dict() for k, m in run.metrics.items()}
            ),
        )

    @property
    def target(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def serve(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class GRPCTrainerClient:
    """Scheduler-side Train stream (announcer.go's uploader over gRPC)."""

    CHUNK_BYTES = 128 << 20  # announcer.go:39-41

    def __init__(self, target: str, *, timeout: float = 600.0) -> None:
        self._channel = grpc.insecure_channel(target)
        self.timeout = timeout
        self._train = self._channel.stream_unary(
            f"/{TRAINER_SERVICE}/Train",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.TrainReply.FromString,
        )
        self._status = self._channel.unary_unary(
            f"/{TRAINER_SERVICE}/RunStatus",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.RunStatusReply.FromString,
        )

    def train(
        self,
        *,
        ip: str,
        hostname: str,
        scheduler_id: str,
        download_shards=(),
        topology_shards=(),
    ) -> str:
        """Stream both dataset files in 128 MiB chunks over ONE stream
        (announcer.go:144-171), returning the run key."""

        def chunks():
            yield pb.TrainChunk(ip=ip, hostname=hostname, scheduler_id=scheduler_id)
            for kind, paths in (
                ("download", download_shards),
                ("networktopology", topology_shards),
            ):
                for path in paths:
                    name = path.rsplit("/", 1)[-1]
                    seq = 0
                    with open(path, "rb") as f:
                        while True:
                            data = f.read(self.CHUNK_BYTES)
                            if not data:
                                break
                            yield pb.TrainChunk(
                                kind=kind, name=name, seq=seq, data=data
                            )
                            seq += 1

        try:
            reply = self._train(chunks(), timeout=self.timeout)
        except grpc.RpcError as exc:
            raise RPCError(
                f"Train: gRPC {exc.code().name}: {exc.details()}"
            ) from exc
        return reply.run

    def run_status(self, key: str) -> dict:
        try:
            r = self._status(pb.RunStatusRequest(key=key), timeout=30.0)
        except grpc.RpcError as exc:
            raise RPCError(
                f"RunStatus: gRPC {exc.code().name}: {exc.details()}"
            ) from exc
        return {
            "key": r.key,
            "done": r.done,
            "error": r.error,
            "download_rows": r.download_rows,
            "topology_rows": r.topology_rows,
            "models": list(r.models),
            "metrics": json.loads(r.metrics_json or "{}"),
        }

    def close(self) -> None:
        self._channel.close()
