"""Wire-protocol versioning + negotiation (VERDICT r4 #6).

Reference: the scheduler serves gRPC v1 AND v2 concurrently and ships a
compatibility e2e mode that runs old client images against new servers
(DRAGONFLY_COMPATIBILITY_E2E_TEST_MODE, SURVEY §4).  The analog here:

- **v1** is the legacy, UNVERSIONED dialect — every request shape this
  wire spoke before the handshake existed.  A v1 client sends no
  ``protocol_version`` field anywhere and uses request-paired calls
  only.  Absence of the field IS the v1 signature, so every client
  built before this module is, by construction, a v1 client.
- **v2** adds the explicit handshake: ``announce_host`` carries
  ``protocol_version``; the server answers with its own version window
  and the NEGOTIATED version (min of both), and advertises capability
  strings (the server-push reschedule stream, steering).  All v2
  changes are additive on the wire, so a v2 server serves v1 clients
  with byte-compatible responses — the compat e2e in
  tests/test_compat.py downloads through a frozen v1 shim against the
  current scheduler every CI run.

Skew policy (DESIGN.md §10d): a server supports [PROTOCOL_VERSION - 1,
PROTOCOL_VERSION] — one release of client skew, the reference's
v1+v2-concurrently posture.  Clients NEWER than the server downgrade
themselves to the server's negotiated answer; clients OLDER than
MIN_SUPPORTED get a typed INVALID_ARGUMENT telling them exactly what to
upgrade.
"""

from __future__ import annotations

from ..utils.dferrors import Code

PROTOCOL_VERSION = 2
MIN_SUPPORTED = 1

# Capability strings a v2 server advertises in the announce response —
# feature discovery is by capability, not by sniffing version numbers
# (a v2.1 server can add one without a version bump).  BASE_CAPABILITIES
# hold on every transport; the gRPC binding adds "push-reschedule" (the
# server-push stream only exists on its bidi announce_peer wire).
BASE_CAPABILITIES = ("steering", "probe-sync")


class UnsupportedProtocolError(ValueError):
    """Client dialect older than the server's support window.
    (A ValueError: the gRPC transport maps those to INVALID_ARGUMENT.)"""

    code = Code.INVALID_ARGUMENT

    def __init__(self, client_version: int):
        super().__init__(
            f"protocol version {client_version} is no longer supported "
            f"(server speaks {MIN_SUPPORTED}..{PROTOCOL_VERSION}); "
            f"upgrade the client"
        )
        self.client_version = client_version


def negotiate(client_version: int) -> int:
    """Server side: the version this connection speaks — min(client,
    ours).  A FUTURE client downgrades to us (it understands our
    dialect by its own skew policy); a too-old client gets the typed
    refusal."""
    if client_version < MIN_SUPPORTED:
        raise UnsupportedProtocolError(client_version)
    return min(int(client_version), PROTOCOL_VERSION)


def protocol_info(negotiated: int, capabilities=BASE_CAPABILITIES) -> dict:
    """The handshake block a server attaches to its announce response."""
    return {
        "version": PROTOCOL_VERSION,
        "min_supported": MIN_SUPPORTED,
        "negotiated": negotiated,
        "capabilities": list(capabilities),
    }
