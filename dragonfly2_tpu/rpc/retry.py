"""Retry with exponential backoff (reference: pkg/retry + the rpc clients'
retry interceptors, pkg/rpc/interceptor.go)."""

from __future__ import annotations

import random
import time
from typing import Callable, Tuple, Type, TypeVar

T = TypeVar("T")


def retry_call(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (ConnectionError, TimeoutError, OSError),
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    last: BaseException | None = None
    for i in range(attempts):
        try:
            return fn()
        except retry_on as exc:  # noqa: PERF203
            last = exc
            if i == attempts - 1:
                break
            delay = min(base_delay * (2**i), max_delay)
            sleep(delay * (0.5 + random.random() / 2))  # jitter
    assert last is not None
    raise last
