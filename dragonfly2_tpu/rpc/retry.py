"""Retry with bounded exponential backoff, full jitter, per-attempt
deadlines, and a circuit breaker (reference: pkg/retry + the rpc
clients' retry interceptors, pkg/rpc/interceptor.go).

Backoff is AWS-style FULL jitter: attempt i sleeps uniform(0,
min(base·2^i, max_delay)).  ``deadline_s`` bounds the WHOLE call
(attempts + sleeps); a callable that accepts a ``deadline_s`` kwarg
receives the remaining budget each attempt so the transport can clamp
its own timeout to what's left (deadline propagation) instead of
overshooting the caller's budget on the last attempt.

``CircuitBreaker`` guards a repeatedly-failing dependency (a dead
parent's piece port, an unreachable manager backend): after
``failure_threshold`` consecutive failures the circuit OPENS and calls
fail fast with ``CircuitOpenError`` (no connect timeout burned per
call) until ``reset_timeout_s`` passes, when ONE half-open probe is let
through — success closes the circuit, failure re-opens it.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

T = TypeVar("T")


class RetryBudgetExceeded(TimeoutError):
    """The overall ``deadline_s`` expired before an attempt succeeded."""


class CircuitOpenError(ConnectionError):
    """Fast-fail: the breaker is OPEN for this dependency."""


class DecorrelatedJitterBackoff:
    """AWS-style decorrelated jitter: each delay is
    ``uniform(base, min(cap, prev * 3))`` — successive failures spread a
    fleet out instead of re-synchronizing it (the thundering-herd
    failure mode of fixed-interval retry loops after a manager bounce).

    ``rng`` is injectable, so a seeded ``random.Random`` makes the whole
    schedule reproducible per instance while staying decorrelated across
    a fleet seeded differently (the ModelSubscriber jitter discipline).
    ``reset()`` after a success returns the next failure to ``base``.
    """

    def __init__(
        self,
        *,
        base: float = 1.0,
        cap: float = 60.0,
        rng: Optional[random.Random] = None,
    ) -> None:
        if base <= 0 or cap < base:
            raise ValueError(f"need 0 < base <= cap, got {base}/{cap}")
        self.base = base
        self.cap = cap
        self._rand = rng.uniform if rng is not None else random.uniform
        self._prev = base

    def next(self) -> float:
        delay = self._rand(self.base, min(self.cap, self._prev * 3.0))
        self._prev = delay
        return delay

    def reset(self) -> None:
        self._prev = self.base


# Gauge codes for rpc_circuit_breaker_state{target}.
_BREAKER_STATE_CODES = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class CircuitBreaker:
    """Consecutive-failure breaker with half-open recovery.

    States: ``closed`` (calls flow; failures count), ``open`` (calls
    fail fast until ``reset_timeout_s`` since the trip), ``half_open``
    (one probe in flight; its outcome decides).  Thread-safe; the clock
    is injectable so tests drive recovery without sleeping.

    With a ``name``, every state TRANSITION (never per-call) is exported
    on the ``rpc_circuit_breaker_state{target=...}`` gauge and logged
    once — a failover storm's open breakers are diagnosable from
    metrics/logs instead of invisible fast-fails.
    """

    def __init__(
        self,
        *,
        failure_threshold: int = 5,
        reset_timeout_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        name: str = "",
    ) -> None:
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self.name = name
        self._clock = clock
        self._mu = threading.Lock()
        self._failures = 0
        self._state = "closed"
        self._opened_at = 0.0
        if name:
            self._export("closed")

    def _export(self, state: str) -> None:
        from .metrics import CIRCUIT_BREAKER_STATE

        CIRCUIT_BREAKER_STATE.set(
            _BREAKER_STATE_CODES[state], target=self.name
        )

    def _note_transition(self, old: str, new: str) -> None:
        """OUTSIDE the lock: one gauge write + one log line per
        transition, not per call."""
        if old == new or not self.name:
            return
        import logging

        self._export(new)
        log = logging.getLogger(__name__)
        if new == "open":
            log.warning(
                "circuit breaker %s: %s -> open (failing fast for %.1fs)",
                self.name, old, self.reset_timeout_s,
            )
        else:
            log.info("circuit breaker %s: %s -> %s", self.name, old, new)

    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    def allow(self) -> bool:
        """May a call proceed right now?  An allowed call while OPEN
        transitions to HALF_OPEN (that call is the recovery probe)."""
        with self._mu:
            old = self._state
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at >= self.reset_timeout_s:
                    self._state = "half_open"
                    out = True
                else:
                    out = False
            else:
                # half_open: one probe at a time — concurrent callers
                # wait out the probe as if still open.
                out = False
            new = self._state
        self._note_transition(old, new)
        return out

    def record_success(self) -> None:
        with self._mu:
            old = self._state
            self._failures = 0
            self._state = "closed"
        self._note_transition(old, "closed")

    def record_failure(self) -> None:
        with self._mu:
            old = self._state
            self._failures += 1
            if self._state == "half_open" or (
                self._failures >= self.failure_threshold
            ):
                self._state = "open"
                self._opened_at = self._clock()
            new = self._state
        self._note_transition(old, new)


def _accepts_deadline(fn) -> bool:
    """True when ``fn`` takes a ``deadline_s`` kwarg — inspected once and
    cached on the callable (source/client._accepts_headers pattern)."""
    try:
        cached = fn.__dict__.get("_df_accepts_deadline")
    except AttributeError:
        cached = None
    if cached is not None:
        return cached
    import inspect

    try:
        sig = inspect.signature(fn)
        ok = "deadline_s" in sig.parameters or any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in sig.parameters.values()
        )
    except (ValueError, TypeError):
        ok = False
    try:
        fn.__dict__["_df_accepts_deadline"] = ok
    except AttributeError:
        pass
    return ok


def retry_call(
    fn: Callable[..., T],
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (ConnectionError, TimeoutError, OSError),
    sleep: Callable[[float], None] = time.sleep,
    deadline_s: Optional[float] = None,
    breaker: Optional[CircuitBreaker] = None,
    rng: Optional[random.Random] = None,
    clock: Callable[[], float] = time.monotonic,
) -> T:
    """Call ``fn`` with bounded, fully-jittered exponential backoff.

    - ``deadline_s``: overall budget.  Attempts stop (RetryBudgetExceeded,
      chained to the last failure) once it's spent, and a deadline-aware
      ``fn`` receives the remaining budget via ``deadline_s=``.
    - ``breaker``: consulted before every attempt (CircuitOpenError when
      open) and told each outcome.
    - ``rng``: injectable jitter source — pass a seeded ``random.Random``
      for deterministic schedules (chaos drills replay exact timings).
    """
    rand = rng.uniform if rng is not None else random.uniform
    pass_deadline = deadline_s is not None and _accepts_deadline(fn)
    start = clock()
    last: BaseException | None = None
    for i in range(attempts):
        if deadline_s is not None:
            remaining = deadline_s - (clock() - start)
            if remaining <= 0:
                exc = RetryBudgetExceeded(
                    f"retry budget {deadline_s}s spent after {i} attempts"
                )
                if last is not None:
                    raise exc from last
                raise exc
        if breaker is not None and not breaker.allow():
            exc = CircuitOpenError("circuit open; failing fast")
            if last is not None:
                raise exc from last
            raise exc
        try:
            if pass_deadline:
                out = fn(deadline_s=max(deadline_s - (clock() - start), 0.0))
            else:
                out = fn()
        except retry_on as exc:  # noqa: PERF203
            if breaker is not None:
                breaker.record_failure()
            last = exc
            if i == attempts - 1:
                break
            delay = rand(0.0, min(base_delay * (2**i), max_delay))
            if deadline_s is not None:
                # Never sleep past the budget — the NEXT attempt should
                # get a chance (or the budget check should fire), not a
                # sleep that silently overshoots the caller's deadline.
                delay = min(delay, max(deadline_s - (clock() - start), 0.0))
            sleep(delay)
        else:
            if breaker is not None:
                breaker.record_success()
            return out
    assert last is not None
    raise last
