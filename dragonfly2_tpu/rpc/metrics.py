"""Wire-layer metrics (reference: grpc_prometheus interceptors on every
gRPC server + the rate-limit interceptor, pkg/rpc/interceptor.go).

Counters shared by the gRPC servers and the rate limiter; the per-service
metric sets (scheduler/trainer) stay in their own modules.
"""

from __future__ import annotations

from ..utils.metrics import default_registry as _reg

GRPC_REQUESTS_TOTAL = _reg.counter(
    "rpc_grpc_requests_total", "gRPC requests handled",
    ["service", "method", "code"],
)
RATE_LIMITED_TOTAL = _reg.counter(
    "rpc_rate_limited_total", "Requests rejected by the rate limiter",
    ["transport"],
)
SYNC_PEERS_ROUNDS_TOTAL = _reg.counter(
    "manager_sync_peers_rounds_total", "sync_peers rounds completed"
)
SYNC_PEERS_ACTIVE = _reg.gauge(
    "manager_sync_peers_active_peers", "Active peers in the last merge"
)
DAEMON_CONTROL_DOWNLOADS = _reg.counter(
    "daemon_control_downloads_total", "Downloads via the control API",
    ["result"],
)
