"""Wire-layer metrics (reference: grpc_prometheus interceptors on every
gRPC server + the rate-limit interceptor, pkg/rpc/interceptor.go).

Counters shared by the gRPC servers and the rate limiter; the per-service
metric sets (scheduler/trainer) stay in their own modules.
"""

from __future__ import annotations

from ..utils.metrics import default_registry as _reg

GRPC_REQUESTS_TOTAL = _reg.counter(
    "rpc_grpc_requests_total", "gRPC requests handled",
    ["service", "method", "code"],
)
RATE_LIMITED_TOTAL = _reg.counter(
    "rpc_rate_limited_total", "Requests rejected by the rate limiter",
    ["transport"],
)
SYNC_PEERS_ROUNDS_TOTAL = _reg.counter(
    "manager_sync_peers_rounds_total", "sync_peers rounds completed"
)
SYNC_PEERS_ACTIVE = _reg.gauge(
    "manager_sync_peers_active_peers", "Active peers in the last merge"
)
DAEMON_CONTROL_DOWNLOADS = _reg.counter(
    "daemon_control_downloads_total", "Downloads via the control API",
    ["result"],
)
# -- manager HA plane (manager/replication.py, DESIGN.md §20) ---------------
MANAGER_ROLE = _reg.gauge(
    "manager_role",
    "1 for this process's current replication role, 0 otherwise",
    ["role"],
)
REPLICATION_LAG = _reg.gauge(
    "manager_replication_lag_seconds",
    "Seconds since this follower last matched the leader's log frontier",
)
MANAGER_FAILOVERS_TOTAL = _reg.counter(
    "manager_failovers_total",
    "Standby-to-leader promotions performed by this process",
    ["node"],
)
MANAGER_ENDPOINT_FAILOVERS_TOTAL = _reg.counter(
    "manager_endpoint_failovers_total",
    "Client-side manager endpoint rotations after a failed call",
    ["client"],
)
CIRCUIT_BREAKER_STATE = _reg.gauge(
    "rpc_circuit_breaker_state",
    "Per-target breaker state: 0 closed, 1 half_open, 2 open",
    ["target"],
)
# Fleet telemetry sketch (DESIGN.md §23): write-ahead append + data
# commit wall per replicated op — the control plane's commit-lag tail,
# journaled crash-safe next to the data-plane sketches.
REPLICATION_COMMIT_SECONDS = _reg.sketch(
    "manager_replication_commit_seconds",
    "Replicated commit wall (WAL append + data commit, per op)",
)
