"""Daemon local control API + the dfget→daemon contract.

Reference: client/daemon/rpcserver serves a Download RPC on a local unix
socket and dfget spawns the daemon when absent
(cmd/dfget/cmd/root.go:234-260 checkAndSpawnDaemon).  TPU-build shape:
a loopback HTTP control endpoint —

  GET  /healthy                    liveness {ok, pid}
  POST /download  {url, output?, piece_size?} → download result

— plus a state file (daemon.json under the daemon's storage dir, or
$DF_DAEMON_STATE) advertising the control URL so dfget can find a
running daemon or know to spawn one.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Optional, Tuple

from ._server import ThreadedHTTPService

STATE_ENV = "DF_DAEMON_STATE"


def state_path() -> str:
    """ONE discovery path shared by writer (dfdaemon) and readers (dfget,
    ensure_daemon): $DF_DAEMON_STATE, else a user-scoped default.  Both
    sides MUST use this function — a storage-dir-relative location would
    desynchronize discovery for custom configs."""
    return os.environ.get(
        STATE_ENV, os.path.expanduser("~/.dragonfly2-tpu/daemon.json")
    )


def write_state(url: str, path: Optional[str] = None) -> str:
    path = path or state_path()
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    # Atomic publish: a concurrent reader must never see a half-written
    # file (JSONDecodeError → spurious re-spawn).
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump({"url": url, "pid": os.getpid()}, f)
    os.replace(tmp, path)
    return path


def read_state(path: Optional[str] = None) -> Optional[dict]:
    try:
        with open(path or state_path()) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class DaemonControlServer:
    """Control surface over the daemon composition: loopback by default
    (/download writes local files); configs may bind a trusted pod/compose
    network instead (DaemonConfig.control_host) — the caller owns that
    trust boundary."""

    def __init__(
        self,
        conductor,
        *,
        piece_size: int = 4 << 20,
        host: str = "127.0.0.1",
        port: int = 0,
        seeder=None,
        public: bool = False,
    ) -> None:
        """``seeder`` (daemon/seeder.Seeder) enables POST /obtain_seeds —
        the scheduler-triggered prioritized seed download with a chunked
        JSON-line event stream (seeder.go:41-151 ObtainSeeds analog).

        ``public=True`` exposes ONLY /healthy and /obtain_seeds: the full
        control surface (/download writes arbitrary local files) is a
        same-machine contract unless the deployment's own trust boundary
        (pod/compose network, DaemonConfig.control_host) widens it —
        seed daemons run one loopback control server AND one public
        seed-endpoint server.
        """
        outer_piece_size = piece_size

        class Handler(BaseHTTPRequestHandler):
            # Chunked transfer (the /obtain_seeds event stream) requires 1.1;
            # plain responses still carry explicit Content-Length.
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _json(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthy":
                    self._json(200, {"ok": True, "pid": os.getpid()})
                else:
                    self._json(404, {"error": "not found"})

            def _obtain_seeds(self):
                """Chunked JSON-line event stream (ObtainSeeds analog)."""
                if seeder is None:
                    self._json(404, {"error": "not a seed peer"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                from ..utils.types import Priority

                try:
                    req = json.loads(self.rfile.read(length) or b"{}")
                    url = req["url"]
                    priority = Priority(int(req.get("priority", 0)))
                except (KeyError, ValueError, TypeError) as exc:
                    # Network-reachable input: malformed bodies (arrays,
                    # priority outside 0..6) must get a clean 400, not a
                    # dropped connection.
                    self._json(400, {"error": str(exc)})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                closed = [False]

                def emit(event: dict) -> None:
                    if closed[0]:
                        return
                    line = (json.dumps(event) + "\n").encode()
                    try:
                        self.wfile.write(f"{len(line):x}\r\n".encode())
                        self.wfile.write(line + b"\r\n")
                        self.wfile.flush()
                    except OSError:
                        # Scheduler hung up — the seed download continues
                        # (children still benefit), only the stream stops.
                        closed[0] = True

                try:
                    seeder.obtain(
                        url,
                        piece_size=int(req.get("piece_size") or outer_piece_size),
                        priority=priority,
                        content_length=req.get("content_length"),
                        task_id=req.get("task_id") or None,
                        emit=emit,
                    )
                finally:
                    if not closed[0]:
                        try:
                            self.wfile.write(b"0\r\n\r\n")
                            self.wfile.flush()
                        except OSError:
                            pass

            def do_POST(self):
                if self.path == "/obtain_seeds":
                    self._obtain_seeds()
                    return
                if public or self.path != "/download":
                    self._json(404, {"error": "not found"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    import time as _time

                    req = json.loads(self.rfile.read(length) or b"{}")
                    url = req["url"]
                    piece_size = int(req.get("piece_size") or outer_piece_size)
                    content_length = conductor.probe_content_length(url)
                    output = req.get("output")
                    t0 = _time.monotonic()
                    if output:
                        # Same-machine contract (dfget and the daemon share
                        # the host, like the reference's unix socket).
                        # STREAM the output (StartFileTask semantics):
                        # bytes land in the file as pieces commit instead
                        # of buffering the whole task first — and a
                        # partial file never masquerades as complete
                        # (tmp + atomic rename on success).
                        handle = conductor.open_stream(
                            url, piece_size=piece_size,
                            content_length=content_length,
                        )
                        # Per-REQUEST tmp name: handler threads share a
                        # pid, and two concurrent downloads to one output
                        # path must not interleave into the same file.
                        tmp_out = (
                            f"{output}.{os.getpid()}."
                            f"{threading.get_ident()}.part"
                        )
                        nbytes = 0
                        try:
                            with open(tmp_out, "wb") as f:
                                for chunk in handle.chunks():
                                    f.write(chunk)
                                    nbytes += len(chunk)
                            os.replace(tmp_out, output)
                        except BaseException:
                            try:
                                os.remove(tmp_out)
                            except OSError:
                                pass
                            raise
                        # chunks() drains at the LAST piece commit; the
                        # run's result normally lands moments later.  The
                        # wait is SHORT: the file is already complete on
                        # disk, so a stalled finish phase (hung report
                        # RPC) must not hold the client's response — the
                        # telemetry fields just flag themselves pending.
                        final = handle.wait_result(timeout_s=2.0)
                        out = {
                            "ok": True,  # content served: file complete
                            "task_id": handle.task_id,
                            "pieces": handle.n_pieces,
                            "bytes": nbytes,
                            "back_to_source": bool(
                                final.back_to_source if final else False
                            ),
                            "result_pending": final is None,
                            "cost_s": _time.monotonic() - t0,
                            "output": output,
                        }
                        self._json(200, out)
                        # AFTER the response write: a client that hung up
                        # mid-stream raises out of _json and must count
                        # once (as failure), not as success+failure.
                        from .metrics import DAEMON_CONTROL_DOWNLOADS

                        DAEMON_CONTROL_DOWNLOADS.inc(result="success")
                        return
                    result = conductor.download(
                        url, piece_size=piece_size,
                        content_length=content_length,
                    )
                    out = {
                        "ok": result.ok,
                        "task_id": result.task_id,
                        "pieces": result.pieces,
                        "bytes": result.bytes,
                        "back_to_source": result.back_to_source,
                        "cost_s": result.cost_s,
                    }
                    from .metrics import DAEMON_CONTROL_DOWNLOADS

                    DAEMON_CONTROL_DOWNLOADS.inc(
                        result="success" if result.ok else "failure"
                    )
                    self._json(200 if result.ok else 502, out)
                except (KeyError, ValueError) as exc:
                    self._json(400, {"error": str(exc)})
                except Exception as exc:  # noqa: BLE001 — wire boundary:
                    # any failure (scheduler RpcError, storage, ...) must
                    # reach the client as JSON, not a closed socket.
                    from .metrics import DAEMON_CONTROL_DOWNLOADS

                    DAEMON_CONTROL_DOWNLOADS.inc(result="failure")
                    self._json(500, {"ok": False, "error": str(exc)})

        self._svc = ThreadedHTTPService(Handler, host, port, "daemon-control")
        self.address: Tuple[str, int] = self._svc.address
        # VM-guest surface (pkg/rpc/vsock.go): the SAME handler can also
        # bind an AF_VSOCK listener so guests without a network stack
        # drive the daemon over vsock://2:<port>.
        self._handler_cls = Handler
        self._vsock = None

    def serve_vsock(self, port: int, *, cid=None):
        """Bind the GUEST-SAFE surface on an AF_VSOCK listener; returns
        the bound port (vsock.go listener analog).

        /download is NOT exposed: it writes HOST-side files at caller-
        chosen paths (a same-machine contract), and any guest CID can
        dial the listener.  Guests get /healthy and /obtain_seeds — the
        piece/seed plane, which is what the reference serves them."""
        from .vsock import VMADDR_CID_ANY, VsockService

        base = self._handler_cls

        class VsockHandler(base):
            def do_POST(self):
                if self.path == "/download":
                    self._json(404, {"error": "not on the vsock surface"})
                    return
                base.do_POST(self)

        self._vsock = VsockService(
            VsockHandler, port,
            cid=VMADDR_CID_ANY if cid is None else cid,
        )
        self._vsock.serve()
        return self._vsock.port

    @property
    def url(self) -> str:
        return self._svc.url

    def serve(self) -> None:
        self._svc.serve()

    def stop(self) -> None:
        self._svc.stop()
        if self._vsock is not None:
            self._vsock.stop()


# -- dfget side (checkAndSpawnDaemon) ----------------------------------------


def daemon_healthy(url: str, timeout: float = 2.0) -> bool:
    from ..utils import faultinject

    try:
        faultinject.fire("daemon.control.healthy")
        with urllib.request.urlopen(url + "/healthy", timeout=timeout) as r:
            return bool(json.loads(r.read()).get("ok"))
    except Exception as exc:  # noqa: BLE001 — any failure means "not healthy"
        logging.getLogger(__name__).debug("health probe %s: %s", url, exc)
        return False


def download_via_daemon(
    url: str, daemon_url: str, *, output: Optional[str] = None,
    piece_size: Optional[int] = None, timeout: float = 600.0,
) -> dict:
    payload = {"url": url}
    if output:
        payload["output"] = os.path.abspath(output)
    if piece_size:
        payload["piece_size"] = piece_size
    req = urllib.request.Request(
        daemon_url + "/download", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    from ..utils import faultinject

    faultinject.fire("daemon.control.download")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as exc:
        # Error statuses (400/500/502) still carry the JSON result — the
        # caller's ok-check handles them, not a raw traceback.
        try:
            return json.loads(exc.read())
        except (ValueError, OSError):
            return {"ok": False, "error": f"HTTP {exc.code}"}


def find_healthy_daemon() -> Optional[str]:
    """→ control URL of a running healthy daemon, else None — the ONE
    discovery check (dfget and ensure_daemon share it)."""
    state = read_state()
    if state and daemon_healthy(state["url"]):
        return state["url"]
    return None


def ensure_daemon(
    scheduler_url: str,
    *,
    spawn_timeout: float = 20.0,
    extra_args: Optional[list] = None,
) -> str:
    """→ control URL of a healthy daemon, spawning one detached if
    needed (root.go:251 checkAndSpawnDaemon).

    Spawning is serialized through a lock file (the reference does the
    same): two concurrent dfgets must not each spawn a daemon, orphaning
    the one that loses the state-file race."""
    import fcntl
    import subprocess
    import sys
    import time

    url = find_healthy_daemon()
    if url:
        return url
    lock_path = state_path() + ".lock"
    os.makedirs(os.path.dirname(os.path.abspath(lock_path)) or ".", exist_ok=True)
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        # The winner of the lock may have spawned while we waited.
        url = find_healthy_daemon()
        if url:
            return url
        log_path = state_path() + ".spawn.log"
        with open(log_path, "ab") as log:
            subprocess.Popen(
                [sys.executable, "-m", "dragonfly2_tpu.cli.dfdaemon",
                 "--scheduler", scheduler_url, *(extra_args or [])],
                stdout=log, stderr=log,
                start_new_session=True,  # outlives dfget, like the reference
            )
        deadline = time.time() + spawn_timeout
        while time.time() < deadline:
            url = find_healthy_daemon()
            if url:
                return url
            time.sleep(0.2)
    raise TimeoutError(
        f"daemon did not become healthy within {spawn_timeout}s "
        f"(spawn log: {log_path})"
    )
