"""AF_VSOCK transport (reference: pkg/rpc/vsock.go — the dialer/listener
dfdaemon exposes to VM guests, ``vsock://<cid>:<port>`` addresses).

VM guests reach the host daemon without a network stack: the control
surface binds a vsock listener alongside its TCP one, and guest-side
clients dial ``vsock://2:port`` (CID 2 = the host).  Python's stdlib
http.server runs unchanged over the family — only the bind differs.
"""

from __future__ import annotations

import http.client
import socket
import threading
import urllib.parse
from http.server import ThreadingHTTPServer
from typing import Optional, Tuple

# Linux well-known CIDs (linux/vm_sockets.h).
VMADDR_CID_ANY = 0xFFFFFFFF
VMADDR_CID_LOCAL = 1   # loopback (vsock_loopback module)
VMADDR_CID_HOST = 2    # the hypervisor host, from a guest
# vsock's "ephemeral port" sentinel is -1U, NOT the TCP-style 0 (binding
# literal port 0 binds port 0).
VMADDR_PORT_ANY = 0xFFFFFFFF


def vsock_available() -> bool:
    if not hasattr(socket, "AF_VSOCK"):
        return False
    try:
        s = socket.socket(socket.AF_VSOCK, socket.SOCK_STREAM)
        s.close()
        return True
    except OSError:
        return False


def parse_vsock_addr(address: str) -> Tuple[int, int]:
    """``vsock://<cid>:<port>`` → (cid, port) (vsock.go VsockDialer's
    URL shape).  Parsed by hand: vsock ports are u32, and urlsplit's
    ``.port`` enforces the TCP 0-65535 range."""
    u = urllib.parse.urlsplit(address)
    cid_s, sep, port_s = u.netloc.partition(":")
    if u.scheme != "vsock" or not sep or not cid_s.isdigit() or not port_s.isdigit():
        raise ValueError(f"not a vsock address: {address!r}")
    cid, port = int(cid_s), int(port_s)
    if cid > 0xFFFFFFFF or port > 0xFFFFFFFF:
        raise ValueError(f"not a vsock address: {address!r}")
    return cid, port


def vsock_connect(cid: int, port: int, *, timeout: float = 10.0) -> socket.socket:
    s = socket.socket(socket.AF_VSOCK, socket.SOCK_STREAM)
    s.settimeout(timeout)
    s.connect((cid, port))
    return s


class VsockHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer over an AF_VSOCK listener: the SAME handler
    classes the TCP services use, bound on (cid, port)."""

    address_family = socket.AF_VSOCK
    daemon_threads = True
    allow_reuse_address = False  # SO_REUSEADDR is TCP-only

    def server_bind(self):  # no getfqdn over vsock addresses
        self.socket.bind(self.server_address)
        self.server_address = self.socket.getsockname()
        self.server_name = f"vsock:{self.server_address[0]}"
        self.server_port = self.server_address[1]


class VsockService:
    """Serve an existing BaseHTTPRequestHandler over vsock."""

    def __init__(self, handler_cls, port: int, *, cid: int = VMADDR_CID_ANY):
        # TCP idiom compatibility: port 0 = "pick one" → vsock's -1U.
        self._httpd = VsockHTTPServer(
            (cid, VMADDR_PORT_ANY if port == 0 else port), handler_cls
        )
        self.address: Tuple[int, int] = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.address[1]

    def serve(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="vsock-http", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class VsockHTTPConnection(http.client.HTTPConnection):
    """http.client.HTTPConnection whose transport is a vsock stream —
    the stdlib owns ALL request/response framing (chunked transfer
    included; the control handler's /obtain_seeds streams chunked);
    only the dial differs."""

    def __init__(self, cid: int, port: int, *, timeout: float = 10.0):
        super().__init__(f"vsock-{cid}", timeout=timeout)
        self.cid = cid
        self.vsock_port = port

    def connect(self) -> None:
        self.sock = vsock_connect(self.cid, self.vsock_port, timeout=self.timeout)

    def call(
        self, method: str, path: str, body: bytes = b"",
        headers: Optional[dict] = None,
    ) -> Tuple[int, bytes]:
        """One-shot convenience: → (status, decoded body bytes)."""
        self.request(method, path, body=body, headers=headers or {})
        resp = self.getresponse()
        try:
            return resp.status, resp.read()
        finally:
            self.close()
