"""Shared HTTP server scaffold for the rpc package's services."""

from __future__ import annotations

import logging
import ssl
import threading
from http.server import ThreadingHTTPServer
from typing import Optional, Tuple, Type


class ThreadedHTTPService:
    """Owns a ThreadingHTTPServer + its serve thread (one lifecycle impl
    for the scheduler RPC, piece, and REST servers).

    ``ssl_context`` wraps the listening socket — with a mutual-TLS context
    (security.tls.server_context) every connecting client must present a
    CA-issued certificate."""

    def __init__(
        self, handler_cls: Type, host: str, port: int, name: str, ssl_context=None
    ):
        # A per-SERVICE subclass (never mutate the caller's class — that
        # would leak a timeout into every other user of it): adds the
        # per-connection read timeout so a stalled client can't pin a
        # handler thread, and swallows TLS handshake failures quietly (the
        # deferred handshake surfaces SSLError on first read; an anonymous
        # client or port scanner is routine, not a traceback).
        class _Handler(handler_cls):  # type: ignore[misc,valid-type]
            timeout = 60

            def handle(self):
                from ..utils import faultinject

                try:
                    # Server-side chaos seam: a drop/dferror here kills
                    # the connection before any request is served — the
                    # client sees a reset, exactly like a dying server.
                    faultinject.fire(f"rpc.server.{name}")
                except Exception as exc:  # noqa: BLE001 — injected
                    logging.getLogger(__name__).debug(
                        "injected fault at rpc.server.%s: %s", name, exc
                    )
                    self.close_connection = True
                    return
                try:
                    super().handle()
                except (ssl.SSLError, ConnectionError, TimeoutError):
                    self.close_connection = True

        _Handler.__name__ = f"{handler_cls.__name__}@{name}"
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._tls = ssl_context is not None
        if ssl_context is not None:
            # Handshake deferred to first read, which happens in the
            # per-connection HANDLER thread — with the default
            # do_handshake_on_connect=True the handshake runs inside
            # accept() on the single serve thread, so one stalled client
            # would block every other connection.
            self._httpd.socket = ssl_context.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False,
            )
        self.address: Tuple[str, int] = self._httpd.server_address
        self._name = name
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.address[1]

    @property
    def url(self) -> str:
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{self.address[0]}:{self.address[1]}"

    def serve(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=self._name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
