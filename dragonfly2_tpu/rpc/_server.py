"""Shared HTTP server scaffold for the rpc package's services."""

from __future__ import annotations

import threading
from http.server import ThreadingHTTPServer
from typing import Optional, Tuple, Type


class ThreadedHTTPService:
    """Owns a ThreadingHTTPServer + its serve thread (one lifecycle impl
    for the scheduler RPC, piece, and REST servers)."""

    def __init__(self, handler_cls: Type, host: str, port: int, name: str):
        self._httpd = ThreadingHTTPServer((host, port), handler_cls)
        self.address: Tuple[str, int] = self._httpd.server_address
        self._name = name
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.address[1]

    @property
    def url(self) -> str:
        return f"http://{self.address[0]}:{self.address[1]}"

    def serve(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=self._name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
