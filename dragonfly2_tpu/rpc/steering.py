"""Multi-scheduler steering client (reference: pkg/balancer's
consistent-hash gRPC picker + resolver/scheduler_resolver.go).

The reference daemon holds a scheduler LIST and its balancer hashes each
task id onto the ring so one task's whole swarm state lives on one
scheduler replica.  ``SteeringSchedulerClient`` is that picker as a
drop-in for the Conductor's single-scheduler client surface:

- task-scoped calls (register/report/leave/...) route to the replica
  owning ``peer.task.id`` on the ring — stable for the task's lifetime;
- host-scoped announces fan out to every replica (each keeps its own
  host inventory);
- probe sync (``sync_probes_*``) pins each HOST to one replica by host
  id — the probe graph still reaches the other replicas through the
  manager's shared-topology sync (scheduler/topology_sync.py), which is
  exactly the cross-replica property the deployment e2e asserts;
- ``resolve_host`` asks the task-agnostic replicas in ring order until
  one knows the host (parents may have announced anywhere).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Sequence

from .balancer import HashRing

logger = logging.getLogger(__name__)


def default_scheduler_factory(url: str):
    """URL scheme → client: grpc://host:port or http(s)://..."""
    if url.startswith("grpc://"):
        from .grpc_transport import GRPCStreamingScheduler

        return GRPCStreamingScheduler(url[len("grpc://"):])
    from .scheduler_client import RemoteScheduler

    return RemoteScheduler(url)


class SteeringSchedulerClient:
    def __init__(
        self,
        urls: Sequence[str],
        *,
        factory: Optional[Callable] = None,
    ) -> None:
        if not urls:
            raise ValueError("SteeringSchedulerClient needs >= 1 scheduler url")
        factory = factory or default_scheduler_factory
        self._clients: Dict[str, object] = {u: factory(u) for u in urls}
        self._ring = HashRing(list(urls))

    # -- routing -------------------------------------------------------------

    def _owner(self, key: str):
        return self._clients[self._ring.pick(key)]

    def for_task(self, task_id: str):
        """The replica owning this task (exposed for tests/debugging)."""
        return self._owner(task_id)

    def backends(self) -> List[str]:
        return sorted(self._clients)

    # -- host-scoped ---------------------------------------------------------

    def announce_host(self, host) -> None:
        # Per-replica isolation: one down replica must not starve the
        # healthy ones of announces (their host-TTL GC would evict this
        # daemon).  Raise only when EVERY replica failed.
        last_exc: Optional[Exception] = None
        ok = 0
        for c in self._clients.values():
            try:
                c.announce_host(host)
                ok += 1
            except Exception as exc:  # noqa: BLE001 — replica outage
                last_exc = exc
        if ok == 0 and last_exc is not None:
            raise last_exc

    def leave_host(self, host) -> None:
        for c in self._clients.values():
            leave = getattr(c, "leave_host", None)
            if leave is None:
                continue
            try:
                leave(host)
            except Exception as exc:  # noqa: BLE001 — best-effort on shutdown
                logger.debug("leave_host on replica failed: %s", exc)

    def sync_probes_start(self, host):
        return self._owner(host.id).sync_probes_start(host)

    def sync_probes_finished(self, host, results) -> None:
        self._owner(host.id).sync_probes_finished(host, results)

    def resolve_host(self, host_id: str):
        last_exc: Optional[Exception] = None
        for c in self._clients.values():
            try:
                return c.resolve_host(host_id)
            except Exception as exc:  # noqa: BLE001 — try the next replica
                last_exc = exc
        raise last_exc if last_exc else KeyError(host_id)

    # -- task-scoped ---------------------------------------------------------

    def register_peer(self, *, host, url, task_id=None, **kw):
        if task_id is None:
            from ..utils import idgen

            task_id = idgen.task_id(url)
        return self._owner(task_id).register_peer(
            host=host, url=url, task_id=task_id, **kw
        )

    def _peer_owner(self, peer):
        return self._owner(peer.task.id)

    def set_task_info(self, peer, *a, **kw):
        return self._peer_owner(peer).set_task_info(peer, *a, **kw)

    def report_piece_finished(self, peer, *a, **kw):
        return self._peer_owner(peer).report_piece_finished(peer, *a, **kw)

    def report_pieces_finished(self, peer, *a, **kw):
        return self._peer_owner(peer).report_pieces_finished(peer, *a, **kw)

    def report_piece_failed(self, peer, *a, **kw):
        return self._peer_owner(peer).report_piece_failed(peer, *a, **kw)

    def report_peer_finished(self, peer):
        return self._peer_owner(peer).report_peer_finished(peer)

    def report_peer_failed(self, peer):
        return self._peer_owner(peer).report_peer_failed(peer)

    def set_task_direct_piece(self, peer, data):
        return self._peer_owner(peer).set_task_direct_piece(peer, data)

    def mark_back_to_source(self, peer):
        return self._peer_owner(peer).mark_back_to_source(peer)

    def leave_peer(self, peer):
        return self._peer_owner(peer).leave_peer(peer)

    def take_pushed_schedule(self, peer):
        """Server-push adoption: only streaming transports have it; a
        mixed ring degrades to None (no push) for the others."""
        take = getattr(self._peer_owner(peer), "take_pushed_schedule", None)
        return take(peer) if take is not None else None

    def close(self) -> None:
        for c in self._clients.values():
            close = getattr(c, "close", None)
            if close is not None:
                close()
