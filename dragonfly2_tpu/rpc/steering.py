"""Multi-scheduler steering client (reference: pkg/balancer's
consistent-hash gRPC picker + resolver/scheduler_resolver.go).

The reference daemon holds a scheduler LIST and its balancer hashes each
task id onto the ring so one task's whole swarm state lives on one
scheduler replica.  ``SteeringSchedulerClient`` is that picker as a
drop-in for the Conductor's single-scheduler client surface:

- task-scoped calls (register/report/leave/...) route to the replica
  owning ``peer.task.id`` on the ring — stable for the task's lifetime;
- host-scoped announces fan out to every replica (each keeps its own
  host inventory);
- probe sync (``sync_probes_*``) pins each HOST to one replica by host
  id — the probe graph still reaches the other replicas through the
  manager's shared-topology sync (scheduler/topology_sync.py), which is
  exactly the cross-replica property the deployment e2e asserts;
- ``resolve_host`` asks the task-agnostic replicas in ring order until
  one knows the host (parents may have announced anywhere).

Sharded-fleet awareness (DESIGN.md §24): schedulers re-publish the
manager's versioned shard ring on every announce answer.  The steering
client adopts the newest payload after each announce fan-out and, once
it has one, routes task-scoped calls by the PUBLISHED ring (scheduler
ids, sha placement — the same map the shards' guards enforce) instead
of the bootstrap url-hash ring; members it has no client for yet are
dialed through the factory on first use.  A ``WrongShardError``
steering answer (stale ring mid-membership-change) is followed to the
hinted owner once.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Sequence

from ..scheduler.sharding import ShardRing, WrongShardError
from .balancer import HashRing

logger = logging.getLogger(__name__)


def default_scheduler_factory(url: str):
    """URL scheme → client: grpc://host:port or http(s)://..."""
    if url.startswith("grpc://"):
        from .grpc_transport import GRPCStreamingScheduler

        return GRPCStreamingScheduler(url[len("grpc://"):])
    from .scheduler_client import RemoteScheduler

    return RemoteScheduler(url)


class SteeringSchedulerClient:
    def __init__(
        self,
        urls: Sequence[str],
        *,
        factory: Optional[Callable] = None,
    ) -> None:
        if not urls:
            raise ValueError("SteeringSchedulerClient needs >= 1 scheduler url")
        factory = factory or default_scheduler_factory
        self._factory = factory
        self._mu = threading.Lock()
        self._clients: Dict[str, object] = {u: factory(u) for u in urls}
        self._ring = HashRing(list(urls))
        # Published shard ring (ids → urls), adopted from announce
        # answers; None until a sharded scheduler answers one.
        self._shard_ring: Optional[ShardRing] = None
        # Tenant identity stamped on every backend client (§26), and the
        # newest tenant_qos payload re-published on announce answers —
        # the daemon CLI adopts it into upload caps/shaper weights.
        self._tenant = ""
        self.tenant_qos: Optional[dict] = None

    # -- tenant identity ------------------------------------------------------

    @property
    def tenant(self) -> str:
        return self._tenant

    @tenant.setter
    def tenant(self, value: str) -> None:
        with self._mu:
            self._tenant = value or ""
            clients = list(self._clients.values())
        for c in clients:
            if hasattr(c, "tenant"):
                c.tenant = value or ""

    # -- routing -------------------------------------------------------------

    def _client_for(self, url: str):
        with self._mu:
            client = self._clients.get(url)
            if client is None:
                client = self._clients[url] = self._factory(url)
                if self._tenant and hasattr(client, "tenant"):
                    client.tenant = self._tenant
            return client

    def _adopt_ring(self, payload) -> None:
        if not isinstance(payload, dict) or not payload.get("members"):
            return
        try:
            ring = ShardRing.from_payload(payload)
        except (KeyError, TypeError, ValueError):
            return
        with self._mu:
            current = self._shard_ring
            if len(ring) and (current is None or ring.version > current.version):
                self._shard_ring = ring

    def ring_version(self) -> int:
        with self._mu:
            return self._shard_ring.version if self._shard_ring else 0

    def _owner(self, key: str):
        with self._mu:
            ring = self._shard_ring
        if ring is not None:
            url = ring.url_of(ring.owner(key))
            if url:
                return self._client_for(url)
        return self._clients[self._ring.pick(key)]

    def _task_call(self, task_id: str, fn):
        """Task-scoped call with steering: a wrong-shard answer (our
        ring lost a membership race) is followed to the hinted owner."""
        try:
            return fn(self._owner(task_id))
        except WrongShardError as exc:
            if not exc.owner_url:
                raise
            logger.debug(
                "task %s steered to %s (ring v%d)",
                task_id, exc.owner_id, exc.ring_version,
            )
            return fn(self._client_for(exc.owner_url))

    def for_task(self, task_id: str):
        """The replica owning this task (exposed for tests/debugging)."""
        return self._owner(task_id)

    def backends(self) -> List[str]:
        return sorted(self._clients)

    # -- host-scoped ---------------------------------------------------------

    def announce_host(self, host) -> None:
        # Per-replica isolation: one down replica must not starve the
        # healthy ones of announces (their host-TTL GC would evict this
        # daemon).  Raise only when EVERY replica failed.
        last_exc: Optional[Exception] = None
        ok = 0
        with self._mu:
            clients = list(self._clients.values())
        for c in clients:
            try:
                c.announce_host(host)
                ok += 1
                # Adopt the newest re-published shard ring (§24): the
                # announce fan-out doubles as the peer's ring poll.
                self._adopt_ring(getattr(c, "scheduler_ring", None))
                # Same discipline for the tenant QoS payload (§26).
                qos = getattr(c, "tenant_qos", None)
                if isinstance(qos, dict) and qos:
                    self.tenant_qos = qos
            except Exception as exc:  # noqa: BLE001 — replica outage
                last_exc = exc
        if ok == 0 and last_exc is not None:
            raise last_exc

    def leave_host(self, host) -> None:
        with self._mu:
            clients = list(self._clients.values())
        for c in clients:
            leave = getattr(c, "leave_host", None)
            if leave is None:
                continue
            try:
                leave(host)
            except Exception as exc:  # noqa: BLE001 — best-effort on shutdown
                logger.debug("leave_host on replica failed: %s", exc)

    def sync_probes_start(self, host):
        return self._owner(host.id).sync_probes_start(host)

    def sync_probes_finished(self, host, results) -> None:
        self._owner(host.id).sync_probes_finished(host, results)

    def resolve_host(self, host_id: str):
        last_exc: Optional[Exception] = None
        with self._mu:
            clients = list(self._clients.values())
        for c in clients:
            try:
                return c.resolve_host(host_id)
            except Exception as exc:  # noqa: BLE001 — try the next replica
                last_exc = exc
        raise last_exc if last_exc else KeyError(host_id)

    # -- task-scoped ---------------------------------------------------------

    def register_peer(self, *, host, url, task_id=None, **kw):
        if task_id is None:
            from ..utils import idgen

            task_id = idgen.task_id(url)
        return self._task_call(
            task_id,
            lambda c: c.register_peer(host=host, url=url, task_id=task_id, **kw),
        )

    def _peer_owner(self, peer):
        return self._owner(peer.task.id)

    def set_task_info(self, peer, *a, **kw):
        return self._task_call(
            peer.task.id, lambda c: c.set_task_info(peer, *a, **kw)
        )

    def report_piece_finished(self, peer, *a, **kw):
        return self._task_call(
            peer.task.id, lambda c: c.report_piece_finished(peer, *a, **kw)
        )

    def report_pieces_finished(self, peer, *a, **kw):
        return self._task_call(
            peer.task.id, lambda c: c.report_pieces_finished(peer, *a, **kw)
        )

    def report_piece_failed(self, peer, *a, **kw):
        return self._task_call(
            peer.task.id, lambda c: c.report_piece_failed(peer, *a, **kw)
        )

    def report_peer_finished(self, peer):
        return self._task_call(
            peer.task.id, lambda c: c.report_peer_finished(peer)
        )

    def report_peer_failed(self, peer):
        return self._task_call(
            peer.task.id, lambda c: c.report_peer_failed(peer)
        )

    def set_task_direct_piece(self, peer, data):
        return self._task_call(
            peer.task.id, lambda c: c.set_task_direct_piece(peer, data)
        )

    def mark_back_to_source(self, peer):
        return self._task_call(
            peer.task.id, lambda c: c.mark_back_to_source(peer)
        )

    def leave_peer(self, peer):
        return self._task_call(peer.task.id, lambda c: c.leave_peer(peer))

    def take_pushed_schedule(self, peer):
        """Server-push adoption: only streaming transports have it; a
        mixed ring degrades to None (no push) for the others."""
        take = getattr(self._peer_owner(peer), "take_pushed_schedule", None)
        return take(peer) if take is not None else None

    def close(self) -> None:
        with self._mu:
            clients = list(self._clients.values())
        for c in clients:
            close = getattr(c, "close", None)
            if close is not None:
                close()
