"""Consistent-hash ring (reference: pkg/balancer/consistent_hashing.go).

The reference's gRPC balancer picks the scheduler/seed-peer for a request
by hashing the task id onto a ring of backends, so one task's swarm state
lives on one scheduler.  Same ring here, used by daemons to pick their
scheduler from dynconfig's list.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence

DEFAULT_REPLICAS = 100  # virtual nodes per backend


def _hash(key: str) -> int:
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    def __init__(self, backends: Sequence[str] = (), replicas: int = DEFAULT_REPLICAS):
        self.replicas = replicas
        self._ring: List[int] = []
        self._owners: Dict[int, str] = {}
        self._backends: set = set()
        for b in backends:
            self.add(b)

    def add(self, backend: str) -> None:
        if backend in self._backends:
            return
        self._backends.add(backend)
        for i in range(self.replicas):
            h = _hash(f"{backend}#{i}")
            bisect.insort(self._ring, h)
            self._owners[h] = backend

    def remove(self, backend: str) -> None:
        if backend not in self._backends:
            return
        self._backends.remove(backend)
        for i in range(self.replicas):
            h = _hash(f"{backend}#{i}")
            idx = bisect.bisect_left(self._ring, h)
            if idx < len(self._ring) and self._ring[idx] == h:
                self._ring.pop(idx)
            self._owners.pop(h, None)

    def pick(self, key: str) -> Optional[str]:
        """Backend owning the key; None when the ring is empty."""
        if not self._ring:
            return None
        h = _hash(key)
        idx = bisect.bisect_right(self._ring, h)
        if idx == len(self._ring):
            idx = 0
        return self._owners[self._ring[idx]]

    def backends(self) -> List[str]:
        return sorted(self._backends)


class StickyFailover:
    """Ordered backend list with a sticky cursor: ``current()`` keeps
    answering the last backend that worked; ``advance()`` rotates to the
    next after a failure.  The manager-HA client policy (pkg/balancer's
    pick-first semantics): every client in a process converges on the
    live leader and stays there — no per-call round-robin that would
    split one client's traffic across a leader and a 503ing standby."""

    def __init__(self, backends: Sequence[str]) -> None:
        self._backends: List[str] = [b for b in backends if b]
        if not self._backends:
            raise ValueError("StickyFailover needs at least one backend")
        import threading

        self._mu = threading.Lock()
        self._idx = 0

    def current(self) -> str:
        with self._mu:
            return self._backends[self._idx]

    def advance(self, seen: Optional[str] = None) -> str:
        """Rotate to the next backend.  With ``seen``, only rotate if
        the cursor still points at it — concurrent failures over one
        shared list advance once, not once per caller."""
        with self._mu:
            if seen is None or self._backends[self._idx] == seen:
                self._idx = (self._idx + 1) % len(self._backends)
            return self._backends[self._idx]

    def all(self) -> List[str]:
        """Every backend, current first (the failover try order)."""
        with self._mu:
            return (
                self._backends[self._idx:] + self._backends[:self._idx]
            )

    def __len__(self) -> int:
        return len(self._backends)
