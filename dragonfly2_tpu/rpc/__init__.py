"""Wire transport (reference: pkg/rpc — the distributed communication
backend, SURVEY §5.8).

The reference's fabric is gRPC streams for control + HTTP ranges for piece
data + a consistent-hashing client balancer.  Same split here, stdlib-only:

- ``scheduler_server`` / ``scheduler_client`` — HTTP/JSON control plane
  binding the real SchedulerService; the client maintains local mirrors of
  Host/Task/Peer so the daemon's Conductor runs unchanged against a remote
  scheduler.
- ``piece_transport`` — HTTP piece data plane: a threading server over the
  daemon's UploadManager (GET /pieces/<task>/<n>, Range supported) and the
  matching fetcher.
- ``balancer``  — consistent-hash ring: task-affine scheduler pick
  (pkg/balancer/consistent_hashing.go).
- ``retry``     — exponential backoff for client calls
  (pkg/rpc retry interceptors).
- ``grpc_transport`` — binary gRPC bindings of the SAME adapters
  (scheduler unary RPCs, trainer Train client stream); loaded lazily so
  the JSON transports don't pay grpc's import cost.
"""

from .balancer import HashRing  # noqa: F401
from .piece_transport import (  # noqa: F401
    HTTPPieceFetcher,
    PieceConnectionPool,
    PieceHTTPServer,
)
from .registry_client import RemoteRegistry  # noqa: F401
from .retry import retry_call  # noqa: F401
from .scheduler_client import RemoteScheduler  # noqa: F401
from .scheduler_server import SchedulerHTTPServer  # noqa: F401
from .trainer_transport import RemoteTrainer, TrainerHTTPServer  # noqa: F401

_GRPC_EXPORTS = {
    "SchedulerGRPCServer", "GRPCRemoteScheduler",
    "TrainerGRPCServer", "GRPCTrainerClient",
    "ManagerGRPCServer", "GRPCRemoteRegistry",
}


def __getattr__(name: str):
    if name in _GRPC_EXPORTS:
        from . import grpc_transport

        return getattr(grpc_transport, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
