"""Dynconfig-fed scheduler resolver (reference: pkg/resolver — gRPC
resolvers that watch dynconfig for the live scheduler list and feed the
consistent-hashing balancer, resolver/scheduler_resolver.go).

``SchedulerResolver`` observes a Dynconfig whose payload carries
``schedulers: [{id, url}]``, keeps the hash ring in sync, and answers
``pick(task_id) → url`` — the daemon's scheduler-selection seam.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .balancer import HashRing


class SchedulerResolver:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._ring = HashRing()
        self._urls: Dict[str, str] = {}

    # Dynconfig observer signature (manager/dynconfig.py register()).
    def on_config(self, config: dict) -> None:
        entries = config.get("schedulers", [])
        with self._mu:
            current = set(self._urls)
            # Skip malformed entries rather than raising — an observer
            # exception would take down the dynconfig refresh for everyone.
            incoming = {
                e["id"]: e["url"]
                for e in entries
                if isinstance(e, dict) and e.get("id") and e.get("url")
            }
            for gone in current - set(incoming):
                self._ring.remove(gone)
                del self._urls[gone]
            for sid, url in incoming.items():
                if sid not in self._urls:
                    self._ring.add(sid)
                self._urls[sid] = url

    def pick(self, task_id: str) -> Optional[str]:
        """Scheduler URL owning the task (consistent hashing keeps one
        task's swarm on one scheduler, pkg/balancer semantics)."""
        with self._mu:
            sid = self._ring.pick(task_id)
            return self._urls.get(sid) if sid else None

    def all_urls(self) -> List[str]:
        with self._mu:
            return sorted(self._urls.values())
