"""Dynconfig-fed scheduler resolver + multi-endpoint manager resolver
(reference: pkg/resolver — gRPC resolvers that watch dynconfig for the
live backend lists and feed the balancers, resolver/scheduler_resolver.go).

``SchedulerResolver`` observes a Dynconfig whose payload carries
``schedulers: [{id, url}]``, keeps the hash ring in sync, and answers
``pick(task_id) → url`` — the daemon's scheduler-selection seam.

``ManagerEndpoints`` is the manager-HA half: ONE sticky ordered list of
manager replica URLs shared by every manager-facing client in a process
(cluster keepalive, dynconfig polls, registry/rollout fetches, the job
queue, topology sync).  ``call`` tries the current endpoint and fails
over on connection errors and on 503 (a standby refusing writes), so a
leader bounce moves the whole process to the survivor mid-flight — and
because the list is shared, the FIRST client to fail over moves
everyone.
"""

from __future__ import annotations

import threading
import time
import urllib.error
from typing import Callable, Dict, List, Optional, Sequence, Tuple, TypeVar, Union

from .balancer import HashRing, StickyFailover

T = TypeVar("T")


class SchedulerResolver:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._ring = HashRing()
        self._urls: Dict[str, str] = {}

    # Dynconfig observer signature (manager/dynconfig.py register()).
    def on_config(self, config: dict) -> None:
        entries = config.get("schedulers", [])
        with self._mu:
            current = set(self._urls)
            # Skip malformed entries rather than raising — an observer
            # exception would take down the dynconfig refresh for everyone.
            incoming = {
                e["id"]: e["url"]
                for e in entries
                if isinstance(e, dict) and e.get("id") and e.get("url")
            }
            for gone in current - set(incoming):
                self._ring.remove(gone)
                del self._urls[gone]
            for sid, url in incoming.items():
                if sid not in self._urls:
                    self._ring.add(sid)
                self._urls[sid] = url

    def pick(self, task_id: str) -> Optional[str]:
        """Scheduler URL owning the task (consistent hashing keeps one
        task's swarm on one scheduler, pkg/balancer semantics)."""
        with self._mu:
            sid = self._ring.pick(task_id)
            return self._urls.get(sid) if sid else None

    def all_urls(self) -> List[str]:
        with self._mu:
            return sorted(self._urls.values())


class ShardRouter:
    """Dynconfig-fed sharded-scheduler router (DESIGN.md §24).

    Holds the manager-published ``scheduler_ring`` (version + members)
    and routes task-scoped calls to the owning shard with the bounded-
    load pick.  ``call`` is the steering-aware wrapper the daemon/sim
    uses:

    - a **wrong-shard** answer (HTTP 421 → ``WrongShardError``) means
      the ring moved under us: adopt the answer's owner hint and retry
      there — the server's hint is fresher than our last dynconfig poll;
    - a **saturated** answer (503 + Retry-After →
      ``ShardSaturatedError``) honors the server's pacing through a
      BOUNDED retry budget (``saturation_retries``, decorrelated-jitter
      spaced and capped by the server's Retry-After), then propagates —
      a briefly-saturated shard is a wait, not a hard failure; past the
      budget the CALLER owns the drop-or-degrade decision;
    - a **transport failure** demotes the member locally (the ring loses
      it until a dynconfig refresh re-publishes it) and retries on the
      task's next owner — the client half of task migration.

    Per-shard in-flight counts feed the bounded-load pick, so a shard
    answering slowly sheds new placements to its ring neighbors before
    its admission controller ever 503s.
    """

    def __init__(
        self,
        factory: Optional[Callable[[str], object]] = None,
        *,
        load_factor: float = 1.25,
        saturation_retries: int = 3,
        max_retry_wait_s: float = 2.0,
        backoff_rng=None,
    ) -> None:
        from ..scheduler.sharding import ShardRing

        self._mu = threading.Lock()
        self._ring = ShardRing()
        self._factory = factory
        self.load_factor = load_factor
        # Saturation retry budget: how many 503+Retry-After answers one
        # call absorbs before propagating, each wait the MIN of the
        # server's Retry-After and a decorrelated-jitter draw (seeded
        # rng => reproducible schedules in tests, decorrelated across a
        # fleet seeded differently).
        self.saturation_retries = max(0, int(saturation_retries))
        self.max_retry_wait_s = max_retry_wait_s
        self._backoff_rng = backoff_rng
        self._clients: Dict[str, object] = {}
        self._inflight: Dict[str, int] = {}

    # -- ring adoption (dynconfig observer) ----------------------------------

    def on_config(self, config: dict) -> None:
        payload = config.get("scheduler_ring")
        if not isinstance(payload, dict):
            return
        from ..scheduler.sharding import ShardRing

        try:
            ring = ShardRing.from_payload(payload)
        except (KeyError, TypeError, ValueError):
            return
        with self._mu:
            if ring.version > self._ring.version and len(ring):
                self._ring = ring

    def update_ring(self, ring) -> None:
        with self._mu:
            self._ring = ring

    @property
    def version(self) -> int:
        with self._mu:
            return self._ring.version

    def members(self) -> Dict[str, str]:
        with self._mu:
            return self._ring.members()

    # -- routing -------------------------------------------------------------

    def _load_of(self, sid: str) -> float:
        return float(self._inflight.get(sid, 0))

    def route(self, task_id: str) -> Tuple[str, str]:
        """(shard_id, url) owning ``task_id`` under the bounded-load
        pick; raises ``LookupError`` on an empty ring."""
        with self._mu:
            sid = self._ring.pick(
                task_id, load_of=self._load_of, load_factor=self.load_factor
            )
            if sid is None:
                raise LookupError("shard ring is empty")
            return sid, self._ring.url_of(sid) or ""

    def client_for(self, url: str):
        with self._mu:
            client = self._clients.get(url)
            if client is None:
                from .steering import default_scheduler_factory

                factory = self._factory or default_scheduler_factory
                client = self._clients[url] = factory(url)
            return client

    def _demote(self, sid: str) -> None:
        """Drop a member that failed at the transport level; the next
        dynconfig refresh re-publishes it if the manager still sees it."""
        with self._mu:
            self._ring.remove(sid)

    # -- steering-aware call -------------------------------------------------

    def call(self, task_id: str, fn: Callable[[object], T]) -> T:
        """Run ``fn(client)`` against the owning shard, following wrong-
        shard steering answers and transport-failure re-routes; absorbs
        up to ``saturation_retries`` Retry-After answers (jitter-spaced)
        before propagating the saturation."""
        from ..rpc.retry import DecorrelatedJitterBackoff
        from ..utils import faultinject
        from ..scheduler.sharding import ShardSaturatedError, WrongShardError

        waits = 0
        backoff = DecorrelatedJitterBackoff(
            base=0.01, cap=self.max_retry_wait_s, rng=self._backoff_rng
        )
        last: Optional[BaseException] = None
        # One attempt per member + one slot per steering hop and per
        # budgeted saturation retry: the walk terminates even when every
        # shard answers with an error.
        for _ in range(
            max(2, len(self.members()) + 1) + self.saturation_retries
        ):
            sid, url = self.route(task_id)
            # Chaos seam: route-time drop/delay exercises the same
            # failover path a dying shard does.
            faultinject.fire("shard.route")
            client = self.client_for(url)
            with self._mu:
                self._inflight[sid] = self._inflight.get(sid, 0) + 1
            try:
                return fn(client)
            except WrongShardError as exc:
                last = exc
                if exc.owner_url:
                    # Server-side hint: route THIS task at the hinted
                    # owner without waiting for the next dynconfig poll.
                    with self._mu:
                        self._ring.add(exc.owner_id or exc.owner_url,
                                       exc.owner_url)
                    try:
                        return fn(self.client_for(exc.owner_url))
                    except Exception as exc2:  # noqa: BLE001 — fall through
                        last = exc2
                        break
            except ShardSaturatedError as exc:
                last = exc
                if waits >= self.saturation_retries:
                    # Budget spent: the shard is saturated beyond a
                    # brief wait — the caller owns drop-or-degrade.
                    raise
                waits += 1
                # Honor the server's pacing (never knock sooner than
                # Retry-After), de-synchronized by the growing jitter
                # draw, clamped to the local budget — a shard asking for
                # minutes gets max_retry_wait_s, not a parked caller.
                time.sleep(
                    min(max(exc.retry_after_s, backoff.next()),
                        self.max_retry_wait_s)
                )
            except (ConnectionError, TimeoutError, OSError) as exc:
                last = exc
                self._demote(sid)
            finally:
                with self._mu:
                    self._inflight[sid] = max(
                        0, self._inflight.get(sid, 1) - 1
                    )
        assert last is not None
        raise last


class ManagerEndpoints:
    """Sticky multi-endpoint manager address book (see module doc).

    Accepts a comma-separated spec (``"http://a:80,http://b:80"``), a
    sequence of URLs, or another ``ManagerEndpoints`` (pass-through, so
    compositions can hand ONE shared instance to every client).
    """

    def __init__(self, spec: Union[str, Sequence[str]], *,
                 client: str = "manager") -> None:
        if isinstance(spec, str):
            urls = [u.strip() for u in spec.split(",") if u.strip()]
        else:
            urls = [str(u).rstrip("/") for u in spec if u]
        self._ring = StickyFailover([u.rstrip("/") for u in urls])
        self.client = client

    @classmethod
    def of(
        cls, spec: "Union[str, Sequence[str], ManagerEndpoints]", *,
        client: str = "manager",
    ) -> "ManagerEndpoints":
        if isinstance(spec, ManagerEndpoints):
            return spec
        return cls(spec, client=client)

    def current(self) -> str:
        return self._ring.current()

    def all(self) -> List[str]:
        return self._ring.all()

    def __len__(self) -> int:
        return len(self._ring)

    def failover(self, seen: str) -> str:
        """Rotate past a failed endpoint (idempotent under races) and
        account it on the failover counter."""
        from .metrics import MANAGER_ENDPOINT_FAILOVERS_TOTAL

        MANAGER_ENDPOINT_FAILOVERS_TOTAL.inc(client=self.client)
        return self._ring.advance(seen)

    # Failures that mean "try the next replica": transport errors, plus
    # HTTP 503 — a standby manager refusing writes until promotion.
    @staticmethod
    def _fails_over(exc: BaseException) -> bool:
        if isinstance(exc, urllib.error.HTTPError):
            return exc.code == 503
        return isinstance(exc, (ConnectionError, TimeoutError, OSError))

    def call(self, fn: Callable[[str], T]) -> T:
        """Run ``fn(base_url)`` against the current endpoint, failing
        over through the full list once; the endpoint that answers
        stays current for every sharer of this instance.  Re-raises the
        last error after a full fruitless cycle."""
        last: Optional[BaseException] = None
        url = self.current()
        for _ in range(len(self._ring)):
            try:
                return fn(url)
            except Exception as exc:  # noqa: BLE001 — classified below
                if not self._fails_over(exc):
                    raise
                last = exc
                url = self.failover(url)
        assert last is not None
        raise last
