"""Dynconfig-fed scheduler resolver + multi-endpoint manager resolver
(reference: pkg/resolver — gRPC resolvers that watch dynconfig for the
live backend lists and feed the balancers, resolver/scheduler_resolver.go).

``SchedulerResolver`` observes a Dynconfig whose payload carries
``schedulers: [{id, url}]``, keeps the hash ring in sync, and answers
``pick(task_id) → url`` — the daemon's scheduler-selection seam.

``ManagerEndpoints`` is the manager-HA half: ONE sticky ordered list of
manager replica URLs shared by every manager-facing client in a process
(cluster keepalive, dynconfig polls, registry/rollout fetches, the job
queue, topology sync).  ``call`` tries the current endpoint and fails
over on connection errors and on 503 (a standby refusing writes), so a
leader bounce moves the whole process to the survivor mid-flight — and
because the list is shared, the FIRST client to fail over moves
everyone.
"""

from __future__ import annotations

import threading
import urllib.error
from typing import Callable, Dict, List, Optional, Sequence, TypeVar, Union

from .balancer import HashRing, StickyFailover

T = TypeVar("T")


class SchedulerResolver:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._ring = HashRing()
        self._urls: Dict[str, str] = {}

    # Dynconfig observer signature (manager/dynconfig.py register()).
    def on_config(self, config: dict) -> None:
        entries = config.get("schedulers", [])
        with self._mu:
            current = set(self._urls)
            # Skip malformed entries rather than raising — an observer
            # exception would take down the dynconfig refresh for everyone.
            incoming = {
                e["id"]: e["url"]
                for e in entries
                if isinstance(e, dict) and e.get("id") and e.get("url")
            }
            for gone in current - set(incoming):
                self._ring.remove(gone)
                del self._urls[gone]
            for sid, url in incoming.items():
                if sid not in self._urls:
                    self._ring.add(sid)
                self._urls[sid] = url

    def pick(self, task_id: str) -> Optional[str]:
        """Scheduler URL owning the task (consistent hashing keeps one
        task's swarm on one scheduler, pkg/balancer semantics)."""
        with self._mu:
            sid = self._ring.pick(task_id)
            return self._urls.get(sid) if sid else None

    def all_urls(self) -> List[str]:
        with self._mu:
            return sorted(self._urls.values())


class ManagerEndpoints:
    """Sticky multi-endpoint manager address book (see module doc).

    Accepts a comma-separated spec (``"http://a:80,http://b:80"``), a
    sequence of URLs, or another ``ManagerEndpoints`` (pass-through, so
    compositions can hand ONE shared instance to every client).
    """

    def __init__(self, spec: Union[str, Sequence[str]], *,
                 client: str = "manager") -> None:
        if isinstance(spec, str):
            urls = [u.strip() for u in spec.split(",") if u.strip()]
        else:
            urls = [str(u).rstrip("/") for u in spec if u]
        self._ring = StickyFailover([u.rstrip("/") for u in urls])
        self.client = client

    @classmethod
    def of(
        cls, spec: "Union[str, Sequence[str], ManagerEndpoints]", *,
        client: str = "manager",
    ) -> "ManagerEndpoints":
        if isinstance(spec, ManagerEndpoints):
            return spec
        return cls(spec, client=client)

    def current(self) -> str:
        return self._ring.current()

    def all(self) -> List[str]:
        return self._ring.all()

    def __len__(self) -> int:
        return len(self._ring)

    def failover(self, seen: str) -> str:
        """Rotate past a failed endpoint (idempotent under races) and
        account it on the failover counter."""
        from .metrics import MANAGER_ENDPOINT_FAILOVERS_TOTAL

        MANAGER_ENDPOINT_FAILOVERS_TOTAL.inc(client=self.client)
        return self._ring.advance(seen)

    # Failures that mean "try the next replica": transport errors, plus
    # HTTP 503 — a standby manager refusing writes until promotion.
    @staticmethod
    def _fails_over(exc: BaseException) -> bool:
        if isinstance(exc, urllib.error.HTTPError):
            return exc.code == 503
        return isinstance(exc, (ConnectionError, TimeoutError, OSError))

    def call(self, fn: Callable[[str], T]) -> T:
        """Run ``fn(base_url)`` against the current endpoint, failing
        over through the full list once; the endpoint that answers
        stays current for every sharer of this instance.  Re-raises the
        last error after a full fruitless cycle."""
        last: Optional[BaseException] = None
        url = self.current()
        for _ in range(len(self._ring)):
            try:
                return fn(url)
            except Exception as exc:  # noqa: BLE001 — classified below
                if not self._fails_over(exc):
                    raise
                last = exc
                url = self.failover(url)
        assert last is not None
        raise last
