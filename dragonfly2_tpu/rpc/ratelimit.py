"""Server-side rate limiting (reference: pkg/rpc/interceptor.go:69-128 —
a token-bucket RateLimiterInterceptor on every gRPC server).

``TokenBucket`` is the shared primitive; ``RateLimitInterceptor`` plugs
into grpc servers (RESOURCE_EXHAUSTED when drained) and the HTTP wire
servers check the same bucket (429).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import grpc


class TokenBucket:
    """qps refill, burst capacity; non-blocking take."""

    def __init__(self, qps: float, burst: int) -> None:
        if qps <= 0 or burst <= 0:
            raise ValueError("qps and burst must be positive")
        self.qps = qps
        self.burst = float(burst)
        self._tokens = float(burst)
        # Anchored at the first take, not here: buckets are built on
        # replay paths (qos/accounting.py note_at) where ambient clock
        # reads are DF018-banned, and the first take starts from a full
        # burst either way.
        self._last: Optional[float] = None
        self._mu = threading.Lock()

    def take(self, n: float = 1.0) -> bool:
        """Live edge: samples the monotonic clock and delegates to
        ``take_at`` (the declared clock seam — DESIGN.md §27)."""
        return self.take_at(time.monotonic(), n)

    def take_at(self, now: float, n: float = 1.0) -> bool:
        with self._mu:
            if self._last is not None:
                # Scripted clocks may repeat a timestamp; never refill
                # backwards.
                elapsed = max(0.0, now - self._last)
                self._tokens = min(
                    self.burst, self._tokens + elapsed * self.qps
                )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class RateLimitInterceptor(grpc.ServerInterceptor):
    """Rejects calls with RESOURCE_EXHAUSTED once the bucket drains
    (interceptor.go limit() → resource-exhausted conversion)."""

    def __init__(self, bucket: TokenBucket) -> None:
        self.bucket = bucket

    def intercept_service(self, continuation, handler_call_details):
        if self.bucket.take():
            return continuation(handler_call_details)
        from .metrics import RATE_LIMITED_TOTAL

        RATE_LIMITED_TOTAL.inc(transport="grpc")

        def reject(request, context):
            context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED, "rate limit exceeded"
            )

        return grpc.unary_unary_rpc_method_handler(reject)


def maybe_bucket(qps: Optional[float], burst: Optional[int]) -> Optional[TokenBucket]:
    """Config helper: None/0 qps disables limiting."""
    if not qps:
        return None
    return TokenBucket(qps, burst or max(int(qps), 1))
