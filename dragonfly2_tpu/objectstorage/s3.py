"""S3 and OSS object-storage backends, dependency-free.

Reference: pkg/objectstorage/objectstorage.go:179-212 dispatches the
daemon gateway's backend to s3/oss/obs client packages (aws-sdk /
aliyun-oss-go-sdk).  This build has no SDKs: the S3 backend signs
requests with the repo's own SigV4 implementation (source/sigv4.py — the
same signer the s3:// source client uses) and the OSS backend implements
the public OSS header-signature scheme (HMAC-SHA1 over the canonicalized
request).  Both speak path-style HTTP to any compatible endpoint (AWS,
MinIO, Ceph RGW, Aliyun) and satisfy the ObjectStorageBackend protocol
(backend.py), so the gateway/dfstore select them by config alone.
"""

from __future__ import annotations

import calendar
import email.utils
import hashlib
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import List, Optional

from ..source import sigv4
from .backend import ObjectMetadata


class ObjectStorageError(RuntimeError):
    pass


def _parse_list_xml(body: bytes) -> List[ObjectMetadata]:
    """ListBucketResult → metadata rows (S3 ListObjectsV2 and OSS list
    share the Contents/Key/Size/ETag/LastModified shape)."""
    root = ET.fromstring(body)
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[: root.tag.index("}") + 1]
    out = []
    for contents in root.iter(f"{ns}Contents"):
        key = contents.findtext(f"{ns}Key", "")
        size = int(contents.findtext(f"{ns}Size", "0"))
        etag = contents.findtext(f"{ns}ETag", "").strip('"')
        modified = contents.findtext(f"{ns}LastModified", "")
        try:
            # timegm, not mktime: LastModified is UTC; mktime would shift
            # it by the machine's zone offset (and disagree with
            # head_object's correctly-parsed timestamps).
            ts = float(
                calendar.timegm(time.strptime(modified[:19], "%Y-%m-%dT%H:%M:%S"))
            )
        except ValueError:
            ts = 0.0
        out.append(ObjectMetadata(
            key=key, content_length=size, etag=etag, last_modified=ts,
        ))
    return out


def _parse_bucket_names(body: bytes) -> List[str]:
    """ListAllMyBucketsResult → sorted bucket names (S3 and OSS share
    the Buckets/Bucket/Name shape)."""
    root = ET.fromstring(body)
    ns = root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""
    return sorted(
        b.findtext(f"{ns}Name", "") for b in root.iter(f"{ns}Bucket")
    )


class _HTTPBackendBase:
    """Shared request plumbing: sign → send → translate errors."""

    def __init__(self, endpoint: str, *, timeout: float = 30.0) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout

    def _url(self, bucket: str, key: str = "", query: str = "") -> str:
        path = f"/{bucket}"
        if key:
            path += "/" + urllib.parse.quote(key.strip("/"), safe="/~")
        return self.endpoint + path + (f"?{query}" if query else "")

    def _sign(
        self, method: str, url: str, headers: dict, payload: bytes,
        bucket: str, key: str,
    ) -> dict:
        raise NotImplementedError

    def _request(
        self,
        method: str,
        bucket: str,
        key: str = "",
        *,
        query: str = "",
        payload: bytes = b"",
        extra_headers: Optional[dict] = None,
    ):
        url = self._url(bucket, key, query)
        headers = dict(extra_headers or {})
        if method in ("PUT", "POST"):
            # Sign the Content-Type the server will actually SEE: urllib
            # silently adds application/x-www-form-urlencoded to requests
            # with a body, which would break signature verification on
            # real endpoints (the signature covers Content-Type on OSS).
            headers.setdefault("Content-Type", "application/octet-stream")
        headers = self._sign(method, url, headers, payload, bucket, key)
        req = urllib.request.Request(
            url, data=payload if method in ("PUT", "POST") else None,
            headers=headers, method=method,
        )
        from ..utils import faultinject

        faultinject.fire("objectstorage.request")
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _head_meta(self, bucket: str, key: str) -> ObjectMetadata:
        with self._request("HEAD", bucket, key) as resp:
            modified = resp.headers.get("Last-Modified", "")
            try:
                ts = email.utils.parsedate_to_datetime(modified).timestamp()
            except (TypeError, ValueError):
                ts = 0.0
            return ObjectMetadata(
                key=key,
                content_length=int(resp.headers.get("Content-Length", 0)),
                etag=resp.headers.get("ETag", "").strip('"'),
                last_modified=ts,
            )

    # -- ObjectStorageBackend protocol ---------------------------------------

    def create_bucket(self, bucket: str) -> None:
        try:
            self._request("PUT", bucket).close()
        except urllib.error.HTTPError as exc:
            # 409 BucketAlreadyOwnedByYou → idempotent success.
            if exc.code != 409:
                raise ObjectStorageError(f"create_bucket: HTTP {exc.code}") from exc

    def bucket_exists(self, bucket: str) -> bool:
        try:
            self._request("HEAD", bucket).close()
            return True
        except urllib.error.HTTPError as exc:
            if exc.code in (404, 403):
                return False
            raise

    def list_buckets(self) -> List[str]:
        """Service-level list (GET /): ListAllMyBucketsResult names —
        ONE request path and ONE parser for both protocols (the signers
        get bucket="" and canonicalize the bare "/")."""
        try:
            with self._request("GET", "") as resp:
                return _parse_bucket_names(resp.read())
        except urllib.error.HTTPError as exc:
            raise ObjectStorageError(f"list_buckets: HTTP {exc.code}") from exc

    def delete_bucket(self, bucket: str) -> None:
        """DestroyBucket (handlers/bucket.go); deleting a ghost is
        idempotent."""
        try:
            self._request("DELETE", bucket).close()
        except urllib.error.HTTPError as exc:
            if exc.code != 404:
                raise ObjectStorageError(
                    f"delete_bucket: HTTP {exc.code}"
                ) from exc

    def put_object(self, bucket: str, key: str, data: bytes) -> ObjectMetadata:
        try:
            with self._request("PUT", bucket, key, payload=data) as resp:
                etag = resp.headers.get("ETag", "").strip('"')
        except urllib.error.HTTPError as exc:
            raise ObjectStorageError(f"put_object {key}: HTTP {exc.code}") from exc
        return ObjectMetadata(
            key=key, content_length=len(data),
            etag=etag or hashlib.md5(data).hexdigest(),
            last_modified=time.time(),
        )

    def get_object(self, bucket: str, key: str) -> bytes:
        try:
            with self._request("GET", bucket, key) as resp:
                return resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                raise KeyError(f"{bucket}/{key}") from exc
            raise ObjectStorageError(f"get_object {key}: HTTP {exc.code}") from exc

    def head_object(self, bucket: str, key: str) -> ObjectMetadata:
        try:
            return self._head_meta(bucket, key)
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                raise KeyError(f"{bucket}/{key}") from exc
            raise ObjectStorageError(f"head_object {key}: HTTP {exc.code}") from exc

    def object_exists(self, bucket: str, key: str) -> bool:
        try:
            self.head_object(bucket, key)
            return True
        except KeyError:
            return False

    def delete_object(self, bucket: str, key: str) -> None:
        try:
            self._request("DELETE", bucket, key).close()
        except urllib.error.HTTPError as exc:
            if exc.code != 404:  # deleting a ghost is idempotent
                raise ObjectStorageError(f"delete_object {key}: HTTP {exc.code}") from exc

    def copy_object(self, bucket: str, src: str, dst: str) -> ObjectMetadata:
        # Server-side copy via the copy-source header both protocols use.
        try:
            self._request(
                "PUT", bucket, dst,
                extra_headers={
                    self._copy_header: f"/{bucket}/{src.strip('/')}"
                },
            ).close()
        except urllib.error.HTTPError as exc:
            raise ObjectStorageError(f"copy_object: HTTP {exc.code}") from exc
        return self.head_object(bucket, dst)

    def list_objects(self, bucket: str, prefix: str = "") -> List[ObjectMetadata]:
        query = "list-type=2"
        if prefix:
            query += "&prefix=" + urllib.parse.quote(prefix, safe="~")
        try:
            with self._request("GET", bucket, query=query) as resp:
                return _parse_list_xml(resp.read())
        except urllib.error.HTTPError as exc:
            raise ObjectStorageError(f"list_objects: HTTP {exc.code}") from exc


class S3Backend(_HTTPBackendBase):
    """SigV4-signed path-style S3 (AWS / MinIO / Ceph RGW / any clone)."""

    _copy_header = "x-amz-copy-source"

    def __init__(
        self,
        endpoint: str,
        *,
        access_key: str,
        secret_key: str,
        region: str = "us-east-1",
        timeout: float = 30.0,
    ) -> None:
        super().__init__(endpoint, timeout=timeout)
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def _sign(
        self, method: str, url: str, headers: dict, payload: bytes,
        bucket: str, key: str,
    ) -> dict:
        parsed = urllib.parse.urlsplit(url)
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        payload_sha = hashlib.sha256(payload).hexdigest()
        signed = dict(headers)
        signed["host"] = parsed.netloc
        signed["x-amz-date"] = amz_date
        signed["x-amz-content-sha256"] = payload_sha
        signed["Authorization"] = sigv4.sign_request(
            method, url, signed,
            access_key=self.access_key, secret_key=self.secret_key,
            region=self.region, service="s3", amz_date=amz_date,
            payload_sha256=payload_sha,
        )
        # urllib sets Host itself; it was only needed for the signature.
        signed.pop("host")
        return signed


class OSSBackend(_HTTPBackendBase):
    """Aliyun OSS header-signature backend (public HMAC-SHA1 scheme:
    sign(VERB\\nContent-MD5\\nContent-Type\\nDate\\nCanonicalizedOSSHeaders
    CanonicalizedResource)).  The vendor specifics live in three class
    attributes so OBS (same scheme, different namespace) is attribute
    overrides, not a second copy of the signing flow."""

    _copy_header = "x-oss-copy-source"
    _header_prefix = "x-oss-"
    _auth_label = "OSS"

    def __init__(
        self,
        endpoint: str,
        *,
        access_key: str,
        secret_key: str,
        timeout: float = 30.0,
    ) -> None:
        super().__init__(endpoint, timeout=timeout)
        self.access_key = access_key
        self.secret_key = secret_key

    def _sign(
        self, method: str, url: str, headers: dict, payload: bytes,
        bucket: str, key: str,
    ) -> dict:
        # ONE canonicalization implementation: delegate to the oss://
        # source client's signer (source/oss.py sign_oss) — it signs the
        # raw /{bucket}/{key} resource, which is the scheme real OSS
        # verifies (not the percent-encoded request path).
        from ..source.oss import sign_oss

        date = email.utils.formatdate(usegmt=True)
        signed = dict(headers)
        signed["Date"] = date
        sig = sign_oss(
            self.secret_key, method.upper(), date=date,
            bucket=bucket, key=key.strip("/"),
            content_type=signed.get("Content-Type", ""),
            oss_headers=signed,
            # Service-level requests (list buckets) sign the bare "/".
            resource=None if bucket else "/",
            header_prefix=self._header_prefix,
        )
        signed["Authorization"] = f"{self._auth_label} {self.access_key}:{sig}"
        return signed



class OBSBackend(OSSBackend):
    """Huawei Cloud OBS header-signature backend.  OBS's public auth is
    the SAME HMAC-SHA1 canonical scheme as OSS with the ``x-obs-``
    header namespace and an ``OBS`` authorization prefix — so this IS
    the OSS backend re-parameterized: three attribute overrides, one
    shared signing flow (source/oss.py sign_oss; reference dispatch
    parity: objectstorage.go:179-212 handles s3/oss/obs)."""

    _copy_header = "x-obs-copy-source"
    _header_prefix = "x-obs-"
    _auth_label = "OBS"


def make_backend(kind: str, **kwargs):
    """Config-selected backend (objectstorage.go:179-212 New dispatch):
    kind ∈ {"fs", "s3", "oss", "obs"}."""
    from .backend import FilesystemBackend

    if kind in ("fs", "filesystem"):
        return FilesystemBackend(kwargs["root"])
    if kind == "s3":
        return S3Backend(
            kwargs["endpoint"], access_key=kwargs.get("access_key", ""),
            secret_key=kwargs.get("secret_key", ""),
            region=kwargs.get("region", "us-east-1"),
        )
    if kind == "oss":
        return OSSBackend(
            kwargs["endpoint"], access_key=kwargs.get("access_key", ""),
            secret_key=kwargs.get("secret_key", ""),
        )
    if kind == "obs":
        return OBSBackend(
            kwargs["endpoint"], access_key=kwargs.get("access_key", ""),
            secret_key=kwargs.get("secret_key", ""),
        )
    raise ValueError(f"unknown object-storage backend {kind!r}")
