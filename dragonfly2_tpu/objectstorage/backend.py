"""Backend interface + filesystem implementation.

Operations mirror the reference's ObjectStorage iface
(pkg/objectstorage/objectstorage.go:179-212): bucket CRUD, object
get/put/delete/head/copy/list, and download URLs are replaced by direct
reads (the gateway streams instead of redirecting).
"""

from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Protocol


@dataclass
class ObjectMetadata:
    key: str
    content_length: int
    etag: str
    last_modified: float


class ObjectStorageBackend(Protocol):
    def create_bucket(self, bucket: str) -> None: ...
    def bucket_exists(self, bucket: str) -> bool: ...
    def list_buckets(self) -> List[str]: ...
    def delete_bucket(self, bucket: str) -> None: ...
    def put_object(self, bucket: str, key: str, data: bytes) -> ObjectMetadata: ...
    def get_object(self, bucket: str, key: str) -> bytes: ...
    def head_object(self, bucket: str, key: str) -> ObjectMetadata: ...
    def delete_object(self, bucket: str, key: str) -> None: ...
    def copy_object(self, bucket: str, src: str, dst: str) -> ObjectMetadata: ...
    def list_objects(self, bucket: str, prefix: str = "") -> List[ObjectMetadata]: ...
    def object_exists(self, bucket: str, key: str) -> bool: ...


class FilesystemBackend:
    """Buckets as directories, objects as files (fixture + on-prem backend)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._mu = threading.Lock()

    def _bucket_dir(self, bucket: str) -> str:
        # Empty names are rejected HERE, not just at the REST boundary:
        # os.path.join(root, "") is the root itself, so delete_bucket("")
        # would rmtree the whole store.
        if not bucket or "/" in bucket or bucket in (".", ".."):
            raise ValueError(f"invalid bucket {bucket!r}")
        return os.path.join(self.root, bucket)

    def _path(self, bucket: str, key: str) -> str:
        safe = key.strip("/")
        if not safe or safe == ".":
            # Would resolve to the bucket directory itself.
            raise ValueError(f"invalid object key {key!r}")
        if ".." in safe.split("/"):
            raise ValueError(f"invalid key {key!r}")
        return os.path.join(self._bucket_dir(bucket), safe)

    def create_bucket(self, bucket: str) -> None:
        os.makedirs(self._bucket_dir(bucket), exist_ok=True)

    def bucket_exists(self, bucket: str) -> bool:
        return os.path.isdir(self._bucket_dir(bucket))

    def list_buckets(self) -> List[str]:
        try:
            return sorted(
                d for d in os.listdir(self.root)
                if os.path.isdir(os.path.join(self.root, d))
            )
        except FileNotFoundError:
            return []

    def delete_bucket(self, bucket: str) -> None:
        """Destroy the bucket (handlers/bucket.go DestroyBucket — the
        reference deletes regardless of contents).  Only a MISSING bucket
        is ignored (idempotency); a failed deletion must surface, not
        return success while the bucket still lists."""
        import shutil

        try:
            shutil.rmtree(self._bucket_dir(bucket))
        except FileNotFoundError:
            pass

    def put_object(self, bucket: str, key: str, data: bytes) -> ObjectMetadata:
        if not self.bucket_exists(bucket):
            raise KeyError(f"bucket {bucket} not found")
        path = self._path(bucket, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return self.head_object(bucket, key)

    def get_object(self, bucket: str, key: str) -> bytes:
        try:
            with open(self._path(bucket, key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(f"{bucket}/{key} not found") from None

    def head_object(self, bucket: str, key: str) -> ObjectMetadata:
        path = self._path(bucket, key)
        try:
            st = os.stat(path)
        except FileNotFoundError:
            raise KeyError(f"{bucket}/{key} not found") from None
        with open(path, "rb") as f:
            etag = hashlib.md5(f.read()).hexdigest()
        return ObjectMetadata(
            key=key, content_length=st.st_size, etag=etag, last_modified=st.st_mtime
        )

    def delete_object(self, bucket: str, key: str) -> None:
        try:
            os.remove(self._path(bucket, key))
        except FileNotFoundError:
            pass

    def copy_object(self, bucket: str, src: str, dst: str) -> ObjectMetadata:
        data = self.get_object(bucket, src)
        return self.put_object(bucket, dst, data)

    def list_objects(self, bucket: str, prefix: str = "") -> List[ObjectMetadata]:
        bdir = self._bucket_dir(bucket)
        out: List[ObjectMetadata] = []
        for dirpath, _, files in os.walk(bdir):
            for name in files:
                path = os.path.join(dirpath, name)
                key = os.path.relpath(path, bdir)
                if key.startswith(prefix):
                    out.append(self.head_object(bucket, key))
        return sorted(out, key=lambda m: m.key)

    def object_exists(self, bucket: str, key: str) -> bool:
        return os.path.exists(self._path(bucket, key))


class ObjectStorageRegistry:
    """name → backend (the reference's multi-vendor switch)."""

    def __init__(self) -> None:
        self._backends: Dict[str, ObjectStorageBackend] = {}

    def register(self, name: str, backend: ObjectStorageBackend) -> None:
        self._backends[name] = backend

    def get(self, name: str) -> ObjectStorageBackend:
        if name not in self._backends:
            raise KeyError(f"no object-storage backend {name!r}")
        return self._backends[name]


def default_backends(fs_root: Optional[str] = None) -> ObjectStorageRegistry:
    reg = ObjectStorageRegistry()
    if fs_root:
        reg.register("fs", FilesystemBackend(fs_root))
    return reg
