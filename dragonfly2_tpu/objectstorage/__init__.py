"""Object storage backends (reference: pkg/objectstorage/).

One interface (objectstorage.go:179-212) over pluggable backends,
matching the reference's S3/OSS/OBS dispatch: the filesystem backend is
built in (the e2e fixtures use it), and ``S3Backend``/``OSSBackend``
(s3.py) speak signed path-style HTTP to any compatible endpoint —
selected by config via ``make_backend``.
"""

from .backend import (  # noqa: F401
    FilesystemBackend,
    ObjectMetadata,
    ObjectStorageBackend,
    ObjectStorageRegistry,
    default_backends,
)
from .s3 import (  # noqa: F401
    ObjectStorageError,
    OBSBackend,
    OSSBackend,
    S3Backend,
    make_backend,
)
