"""Object storage backends (reference: pkg/objectstorage/).

One interface (objectstorage.go:179-212) over pluggable backends; the
reference ships S3/OSS/OBS.  Here the filesystem backend is built in (and
is what the e2e fixtures use); cloud backends register into the same
registry at deploy time.
"""

from .backend import (  # noqa: F401
    FilesystemBackend,
    ObjectMetadata,
    ObjectStorageBackend,
    ObjectStorageRegistry,
    default_backends,
)
