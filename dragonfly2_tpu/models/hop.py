"""Hop-feature ranker: scatter-free GNN training via precomputed aggregation.

The measured wall on the GAT ranker is structural: any architecture that
gathers a per-edge [N, K, D] tensor inside the train step pays XLA's
sort-based scatter in the backward (~22 ms per layer at [100k, 16, 128]
on v5e — see BENCHMARKS.md; every scatter-avoidance rewiring measured
worse).  The TPU-native fix is to move aggregation OUT of the step
entirely, SIGN-style (Frasca et al., 2020, "SIGN: Scalable Inception
Graph Neural Networks"): neighbor aggregates of the *input* features
are parameter-independent, so they can be computed once per graph
snapshot — the gradient never flows through a gather wider than the
edge batch.

    precompute:  H = [X, A1·X, A2·(A1·X), deg, rtt-stats]   (once per snapshot)
    train step:  rows = H[src], H[dst]  (narrow endpoint gathers)
                 score = head(enc(rows_s, E[src]), enc(rows_d, E[dst]), qef)

Only the learnable per-node embedding table E still scatters in the
backward — [B, embed] with a 64-byte payload, ~10× cheaper than the
GAT's [B·K, 128] float rows.  The step is pure dense MXU work: measured
~3 ms vs the GAT's ~93 ms at the north-star shape with comparable
validation quality (BENCHMARKS.md "hop ranker" section).

Fills the same seam as models/gnn.py (the reference's stubbed trainGNN,
trainer/training/training.go:82-90); the scheduler-side scorer export
consumes it identically (trainer/export.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .gnn import NeighborTable


@dataclass(frozen=True)
class HopConfig:
    hidden: int = 128
    out_dim: int = 64
    hops: int = 2
    node_embed_dim: int = 32
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16


def precompute_hop_features(
    node_feats: jax.Array,
    table: NeighborTable,
    *,
    hops: int = 2,
) -> jax.Array:
    """[N, D] features + neighbor table → [N, F] hop-augmented features.

    Per hop: masked-mean and inverse-RTT-weighted-mean aggregates of the
    previous hop's representation; plus degree and mean-edge-feature
    columns.  Pure jnp (one-time gathers are fine outside the step); jit
    at the call site when running per-epoch resampled tables.
    """
    x = jnp.asarray(node_feats, jnp.float32)
    return _hop_parts(
        x,
        table.mask,
        table.edge_feats,
        lambda h: jnp.take(h, table.indices, axis=0),
        hops,
    )


# THE cached jit of the replicated precompute (static hops ⇒ one traced
# program per hop count for the whole process).  Construct-per-call
# (`jax.jit(precompute_hop_features)(...)`) throws the compile cache away
# with the wrapper — dflint DF010 flags it; import this instead.
precompute_hop_features_jit = jax.jit(
    precompute_hop_features, static_argnames="hops"
)


def _hop_parts(x, mask, edge_feats, gather, hops: int) -> jax.Array:
    """THE hop-aggregation math, shared between the replicated precompute
    and the node-sharded one (parallel/graph_sharding.py) so the two stay
    numerically identical by construction.  ``gather(h) → [rows, K, D]``
    supplies each row's neighbor representations — a plain global take
    here, a halo-exchange gather in the sharded body.
    """
    m = mask.astype(jnp.float32)[..., None]               # [rows, K, 1]
    denom = jnp.maximum(m.sum(axis=1), 1.0)               # [rows, 1]
    # Inverse-RTT weights from the first edge-feature column (normalized
    # RTT at table build): nearer probes describe the node better.
    rtt = edge_feats[..., :1].astype(jnp.float32)         # [rows, K, 1]
    w = m / (1.0 + jnp.maximum(rtt, 0.0))
    w_denom = jnp.maximum(w.sum(axis=1), 1e-6)

    parts = [x]
    h = x
    for _ in range(hops):
        nbr = gather(h)                                   # [rows, K, D]
        mean_agg = (nbr * m).sum(axis=1) / denom
        wmean_agg = (nbr * w).sum(axis=1) / w_denom
        h = mean_agg
        parts.extend([mean_agg, wmean_agg])
    deg = m.sum(axis=1) / m.shape[1]                      # [rows, 1] norm degree
    mean_rtt = (rtt * m).sum(axis=1) / denom              # [rows, 1]
    parts.extend([deg, mean_rtt])
    return jnp.concatenate(parts, axis=-1)


class HopEncoder(nn.Module):
    """Hop features (+ learned node embedding) → node representation."""

    cfg: HopConfig
    num_nodes: int = 0

    @nn.compact
    def __call__(self, rows: jax.Array, ids: jax.Array, *, train: bool = False):
        cfg = self.cfg
        x = rows.astype(cfg.dtype)
        if cfg.node_embed_dim > 0:
            # Embedding gathers/scatters are [B, embed] — the only
            # non-dense op left in the step, with a narrow payload.
            emb = nn.Embed(
                self.num_nodes, cfg.node_embed_dim, param_dtype=jnp.float32
            )(ids)
            x = jnp.concatenate([x, emb.astype(cfg.dtype)], axis=-1)
        x = nn.gelu(nn.Dense(cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32)(x))
        if cfg.dropout > 0:
            x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        x = nn.gelu(nn.Dense(cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32)(x))
        return nn.Dense(cfg.out_dim, dtype=jnp.float32, param_dtype=jnp.float32)(x)


class HopRanker(nn.Module):
    """Drop-in flagship ranker: same call signature as GATRanker, but
    ``node_feats`` must be the PRECOMPUTED hop features and the table is
    only consulted for its shape (aggregation already happened).

    __call__(hop_feats, table, src, dst, qef) → [B] predicted
    log-bandwidth per queried parent→child edge.
    """

    config: HopConfig

    @nn.compact
    def __call__(
        self,
        hop_feats: jax.Array,
        table: NeighborTable,
        src: jax.Array,
        dst: jax.Array,
        query_edge_feats=None,
        *,
        train: bool = False,
        return_embeddings: bool = False,
    ) -> jax.Array:
        cfg = self.config
        n = hop_feats.shape[0]
        encoder = HopEncoder(cfg, num_nodes=n)
        if return_embeddings:
            # Export path (trainer/export.py GNNScorer): embed every node.
            all_ids = jnp.arange(n, dtype=jnp.int32)
            return encoder(hop_feats, all_ids, train=False)
        s_rows = jnp.take(hop_feats, src, axis=0)
        d_rows = jnp.take(hop_feats, dst, axis=0)
        s = encoder(s_rows, src, train=train)
        d = encoder(d_rows, dst, train=train)
        parts = [s, d, s * d]
        if query_edge_feats is not None:
            parts.append(query_edge_feats)
        x = jnp.concatenate(parts, axis=-1).astype(cfg.dtype)
        x = nn.gelu(nn.Dense(cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32)(x))
        x = nn.gelu(
            nn.Dense(cfg.hidden // 2, dtype=cfg.dtype, param_dtype=jnp.float32)(x)
        )
        return nn.Dense(1, dtype=jnp.float32, param_dtype=jnp.float32)(x)[..., 0]
