"""GNN over the probe graph (the reference's ``gnn`` model type).

The reference planned "train GNN model" over network-topology probe data
(trainer/training/training.go:82-90; dataset production at
scheduler/networktopology/network_topology.go:386-497) and recorded
precision/recall/F1 GNN evaluations in the manager registry
(manager_server_v1.go:874-900), but shipped no model.  This module is the
real thing, designed for XLA rather than for a message-passing framework:

**Static-shape neighbor tables.**  Neighbor aggregation is the classic
XLA-hostility point (ragged degrees ⇒ dynamic shapes ⇒ recompiles).  We
pad/bucket every node to exactly K neighbor slots at ingest time
(``build_neighbor_table``): the model sees dense [N, K] index + mask +
edge-feature tensors, aggregation is one gather + masked mean/softmax —
pure MXU/VPU work, compiled once, trivially shardable over a mesh (node
dim on ``data``).  Degree > K: uniform subsample per epoch (GraphSAGE
semantics); degree < K: masked padding.

Models:
- ``GraphSAGE``  — mean-aggregator SAGE encoder (BASELINE configs[1]).
- ``GATRanker``  — GAT encoder + edge-score head predicting per-edge
  log-bandwidth for parent ranking (configs[2]); the scheduler's ML
  evaluator consumes its exported scores.

bf16 compute, f32 params and softmax/loss reductions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class NeighborTable(NamedTuple):
    """Dense, static-shape adjacency: for each node, K neighbor slots.

    indices   [N, K] int32   — neighbor node ids (0 where padded)
    mask      [N, K] float32 — 1.0 for real neighbors, 0.0 for padding
    edge_feats[N, K, E] float32 — per-edge features (normalized RTT, ...)
    """

    indices: jax.Array
    mask: jax.Array
    edge_feats: jax.Array

    @property
    def num_nodes(self) -> int:
        return self.indices.shape[0]

    @property
    def max_neighbors(self) -> int:
        return self.indices.shape[1]


def build_neighbor_table(
    n_nodes: int,
    src: np.ndarray,
    dst: np.ndarray,
    edge_feats: Optional[np.ndarray] = None,
    *,
    max_neighbors: int = 16,
    rng: Optional[np.random.Generator] = None,
) -> NeighborTable:
    """Host-side: edge lists → padded per-node neighbor slots.

    Edges are directed src→dst; the table lists, for each *dst* node, the
    src nodes probing it (in-neighbors), matching how the probe graph is
    written (prober → probed, network_topology.go Store).  Over-degree
    nodes get a uniform sample (fresh each call ⇒ per-epoch resampling).

    Fully vectorized: a random permutation of the edge list followed by a
    stable sort on dst makes "first max_neighbors per group" a uniform
    without-replacement sample — the previous per-node Python loop with
    rng.choice cost minutes per snapshot at config[5] graph scale (2^20
    nodes × K=32 ≈ 33M edges), where this is seconds.
    """
    rng = rng or np.random.default_rng(0)
    src = np.asarray(src)
    dst = np.asarray(dst)
    if edge_feats is None:
        edge_feats = np.zeros((len(src), 1), dtype=np.float32)
    edge_feats = np.asarray(edge_feats, dtype=np.float32)
    if edge_feats.ndim == 1:
        edge_feats = edge_feats[:, None]
    e_dim = edge_feats.shape[1]

    indices = np.zeros((n_nodes, max_neighbors), dtype=np.int32)
    mask = np.zeros((n_nodes, max_neighbors), dtype=np.float32)
    feats = np.zeros((n_nodes, max_neighbors, e_dim), dtype=np.float32)

    if len(src):
        # Out-of-range dst (stale/hostile ids) drop silently, exactly
        # like the old per-node loop — a negative dst would otherwise
        # python-wraparound into the LAST row as a phantom neighbor.
        in_range = (dst >= 0) & (dst < n_nodes)
        if not in_range.all():
            src, dst, edge_feats = (
                src[in_range], dst[in_range], edge_feats[in_range]
            )
    if len(src):
        perm = rng.permutation(len(src))
        order = perm[np.argsort(dst[perm], kind="stable")]
        dst_s = dst[order]
        boundaries = np.searchsorted(dst_s, np.arange(n_nodes + 1))
        pos = np.arange(len(dst_s)) - boundaries[dst_s]  # rank within group
        keep = pos < max_neighbors
        rows, cols, eid = dst_s[keep], pos[keep], order[keep]
        indices[rows, cols] = src[eid]
        mask[rows, cols] = 1.0
        feats[rows, cols] = edge_feats[eid]
    return NeighborTable(
        indices=jnp.asarray(indices),
        mask=jnp.asarray(mask),
        edge_feats=jnp.asarray(feats),
    )


@dataclass(frozen=True)
class GNNConfig:
    hidden: int = 128
    out_dim: int = 64
    num_layers: int = 2
    num_heads: int = 4          # GAT only
    edge_dim: int = 1
    # Learnable per-node embedding concatenated to the host features.
    # Host stats alone cannot encode *where* a node sits (idc/region are
    # strings the feature vector drops); the embedding learns the latent
    # position from probe-RTT supervision — the factorization that makes
    # edge-RTT/bandwidth prediction possible at all.  0 disables.
    node_embed_dim: int = 32
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    # Optional neighbor-gather override (ops.pallas_segment.
    # make_neighbor_gather): a custom-VJP gather whose backward
    # scatter-add runs on the MXU segment kernel.  Must be built from the
    # SAME [N, K] indices as the NeighborTable passed at call time.
    gather_fn: Optional[Callable] = None


class NodeEmbedding(nn.Module):
    """[N, D] features → [N, D + node_embed_dim] with learned identity."""

    embed_dim: int

    @nn.compact
    def __call__(self, node_feats: jax.Array) -> jax.Array:
        if self.embed_dim <= 0:
            return node_feats
        n = node_feats.shape[0]
        emb = self.param(
            "embedding",
            nn.initializers.normal(stddev=0.1),
            (n, self.embed_dim),
            jnp.float32,
        )
        return jnp.concatenate([node_feats, emb.astype(node_feats.dtype)], axis=-1)


class SAGELayer(nn.Module):
    """h' = act(W_self h ++ W_agg mean_k(h_nbr ++ e))  — one gather + matmuls."""

    width: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, h: jax.Array, table: NeighborTable) -> jax.Array:
        h = h.astype(self.dtype)
        nbr = jnp.take(h, table.indices, axis=0)          # [N, K, D]
        nbr = jnp.concatenate(
            [nbr, table.edge_feats.astype(self.dtype)], axis=-1
        )                                                  # [N, K, D+E]
        m = table.mask.astype(self.dtype)[..., None]       # [N, K, 1]
        denom = jnp.maximum(m.sum(axis=1), 1.0)            # [N, 1]
        agg = (nbr * m).sum(axis=1) / denom                # [N, D+E]
        out = jnp.concatenate(
            [
                nn.Dense(self.width, dtype=self.dtype, param_dtype=jnp.float32)(h),
                nn.Dense(self.width, dtype=self.dtype, param_dtype=jnp.float32)(agg),
            ],
            axis=-1,
        )
        return nn.gelu(
            nn.Dense(self.width, dtype=self.dtype, param_dtype=jnp.float32)(out)
        )


class GraphSAGE(nn.Module):
    """Node features [N, D] + neighbor table → embeddings [N, out_dim]."""

    config: GNNConfig = field(default_factory=GNNConfig)

    @nn.compact
    def __call__(
        self, node_feats: jax.Array, table: NeighborTable, *, train: bool = False
    ) -> jax.Array:
        cfg = self.config
        h = NodeEmbedding(cfg.node_embed_dim)(node_feats)
        for _ in range(cfg.num_layers):
            h = SAGELayer(cfg.hidden, cfg.dtype)(h, table)
            if cfg.dropout > 0:
                h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        return nn.Dense(cfg.out_dim, dtype=jnp.float32, param_dtype=jnp.float32)(h)


class GATLayer(nn.Module):
    """Multi-head attention over the K neighbor slots (masked softmax in f32)."""

    width: int          # per-head width
    num_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    gather_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, h: jax.Array, table: NeighborTable) -> jax.Array:
        H, W = self.num_heads, self.width
        h = h.astype(self.dtype)
        q = nn.Dense(H * W, dtype=self.dtype, param_dtype=jnp.float32)(h)
        N, K = table.indices.shape
        q = q.reshape(N, H, W)
        # Gather the raw neighbor rows ONCE and project k/v AFTER the
        # gather: identical linear algebra, but one [N,K,D] gather (and one
        # backward scatter) instead of two — the gather traffic, not the
        # extra post-gather matmul FLOPs, dominates this layer on TPU
        # (BENCHMARKS.md lever #2; measured ~25 ms per gather+grad at
        # [100k,16,128]).  gather_fn (when set) swaps the backward
        # scatter-add for the MXU segment kernel.
        if self.gather_fn is not None:
            h_n = self.gather_fn(h)                            # [N, K, D]
            if h_n.shape[:2] != table.indices.shape:
                raise ValueError(
                    f"gather_fn output {h_n.shape[:2]} does not match the "
                    f"neighbor table {table.indices.shape} — rebuild it "
                    f"with make_neighbor_gather(table.indices, ...) for "
                    f"THIS graph snapshot"
                )
        else:
            h_n = jnp.take(h, table.indices, axis=0)           # [N, K, D]
        k_n = nn.Dense(H * W, dtype=self.dtype, param_dtype=jnp.float32)(h_n).reshape(
            N, K, H, W
        )
        v_n = nn.Dense(H * W, dtype=self.dtype, param_dtype=jnp.float32)(h_n).reshape(
            N, K, H, W
        )
        # Edge features bias the attention logit per head.
        e_bias = nn.Dense(H, dtype=self.dtype, param_dtype=jnp.float32)(
            table.edge_feats.astype(self.dtype)
        )                                                   # [N, K, H]
        logits = jnp.einsum("nhw,nkhw->nkh", q, k_n) / jnp.sqrt(
            jnp.asarray(W, dtype=self.dtype)
        )
        logits = (logits + e_bias).astype(jnp.float32)
        neg_inf = jnp.finfo(jnp.float32).min
        logits = jnp.where(table.mask[..., None] > 0, logits, neg_inf)
        attn = jax.nn.softmax(logits, axis=1)
        # Fully-padded rows: softmax over all -inf is uniform garbage → zero it.
        attn = attn * table.mask[..., None]
        out = jnp.einsum("nkh,nkhw->nhw", attn.astype(self.dtype), v_n)
        out = out.reshape(N, H * W)
        return nn.gelu(
            nn.Dense(H * W, dtype=self.dtype, param_dtype=jnp.float32)(out) + out
        )


class GATRanker(nn.Module):
    """GAT encoder + edge-score head (the parent-peer ranker).

    __call__(node_feats, table, src, dst, query_edge_feats) → [B] scores:
    predicted log-bandwidth for each queried src→dst (parent→child) edge.
    """

    config: GNNConfig = field(default_factory=GNNConfig)

    @nn.compact
    def __call__(
        self,
        node_feats: jax.Array,
        table: NeighborTable,
        src: jax.Array,           # [B] parent node ids
        dst: jax.Array,           # [B] child node ids
        query_edge_feats: Optional[jax.Array] = None,  # [B, F] transfer feats
        *,
        train: bool = False,
        return_embeddings: bool = False,
    ) -> jax.Array:
        cfg = self.config
        per_head = max(cfg.hidden // cfg.num_heads, 1)
        h = NodeEmbedding(cfg.node_embed_dim)(node_feats)
        for _ in range(cfg.num_layers):
            h = GATLayer(per_head, cfg.num_heads, cfg.dtype, cfg.gather_fn)(h, table)
            if cfg.dropout > 0:
                h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        emb = nn.Dense(cfg.out_dim, dtype=jnp.float32, param_dtype=jnp.float32)(h)
        if return_embeddings:
            # Export path: the scorer artifact stores this table and runs
            # only the head at serve time (trainer/export.py GNNScorer).
            return emb

        s = jnp.take(emb, src, axis=0)                     # [B, out]
        d = jnp.take(emb, dst, axis=0)
        parts = [s, d, s * d]
        if query_edge_feats is not None:
            parts.append(query_edge_feats)
        x = jnp.concatenate(parts, axis=-1).astype(cfg.dtype)
        x = nn.gelu(nn.Dense(cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32)(x))
        x = nn.gelu(nn.Dense(cfg.hidden // 2, dtype=cfg.dtype, param_dtype=jnp.float32)(x))
        return nn.Dense(1, dtype=jnp.float32, param_dtype=jnp.float32)(x)[..., 0]
