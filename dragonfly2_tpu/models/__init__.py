"""Model zoo for the learned scheduling pipeline.

The reference defines exactly two model *types* in its registry —
``mlp`` and ``gnn`` (manager/models/model.go:35-46) — and never implements
either (trainer/training/training.go:82-99 is the stub).  Here:

- ``mlp``  — bandwidth regressor over download-record edge features
             (BASELINE configs[0]).
- ``gnn``  — GraphSAGE encoder over the probe graph (configs[1]) and a
             GAT parent ranker (configs[2]); both use static-shape padded
             neighbor tables so XLA compiles once.

All models compute in bfloat16 on the MXU with float32 params/reductions.
"""

from .mlp import MLPRegressor, MLPConfig  # noqa: F401
from .gnn import (  # noqa: F401
    GATRanker,
    GNNConfig,
    GraphSAGE,
    NeighborTable,
    build_neighbor_table,
)
from .hop import (  # noqa: F401
    HopConfig,
    HopRanker,
    precompute_hop_features,
)
