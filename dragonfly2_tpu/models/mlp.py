"""MLP bandwidth regressor (the reference's ``mlp`` model type).

Implements the model the reference only named: ``trainMLP`` at
trainer/training/training.go:92-99 is a 4-line TODO ("load download,
preprocess dataset, train MLP model, upload model and metadata"), and the
manager's registry stores ``type=mlp`` with MSE/MAE evaluation
(manager/rpcserver/manager_server_v1.go:874-900).

Input: DOWNLOAD_FEATURE_DIM (32) features per parent→child edge
(records/features.py — child host ++ parent host ++ edge/transfer feats).
Target: log1p(bandwidth bytes/s).

TPU notes: feature width 32 and hidden widths are multiples the MXU tiles
cleanly; compute in bf16, params + loss in f32.  The whole model is a few
fused matmuls — the win over the reference design is not this model but
the ingest path feeding it (columnar mmap → device).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..records.features import DOWNLOAD_FEATURE_DIM


@dataclass(frozen=True)
class MLPConfig:
    in_dim: int = DOWNLOAD_FEATURE_DIM
    hidden: Tuple[int, ...] = (256, 256, 128)
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16


class MLPRegressor(nn.Module):
    """feats [B, in_dim] → predicted log-bandwidth [B]."""

    config: MLPConfig = field(default_factory=MLPConfig)

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        cfg = self.config
        x = x.astype(cfg.dtype)
        for width in cfg.hidden:
            x = nn.Dense(width, dtype=cfg.dtype, param_dtype=jnp.float32)(x)
            x = nn.gelu(x)
            if cfg.dropout > 0:
                x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        x = nn.Dense(1, dtype=jnp.float32, param_dtype=jnp.float32)(x)
        return x[..., 0]


def warm_start_output_bias(params: dict, target_mean: float) -> dict:
    """Return params with the OUTPUT layer's bias shifted by target_mean.

    Regression warm start: with Huber's linear tail, a zero-init head that
    is many log-units from the targets spends thousands of steps closing a
    constant offset.  The output layer is the highest-numbered top-level
    Dense submodule (flax auto-naming); streaming and federated trainers
    share this single definition.
    """
    import jax.numpy as jnp

    last = max(
        (k for k in params if k.startswith("Dense_")),
        key=lambda k: int(k.split("_")[1]),
    )
    out = dict(params)
    out[last] = dict(out[last])
    out[last]["bias"] = jnp.asarray(out[last]["bias"]) + float(target_mean)
    return out
