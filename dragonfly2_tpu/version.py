"""Build metadata (reference: version/ — git/go version + platform embedded
in announces and version commands)."""

from __future__ import annotations

import platform
import subprocess
import sys
from dataclasses import dataclass, field


@dataclass(frozen=True)
class BuildInfo:
    version: str
    git_commit: str
    python_version: str
    platform: str

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "git_commit": self.git_commit,
            "python_version": self.python_version,
            "platform": self.platform,
        }


def _git_commit() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=5,
        )
        return out.stdout.strip() if out.returncode == 0 else "unknown"
    except (OSError, subprocess.SubprocessError):
        return "unknown"


def build_info() -> BuildInfo:
    from . import __version__

    return BuildInfo(
        version=__version__,
        git_commit=_git_commit(),
        python_version=sys.version.split()[0],
        platform=f"{platform.system().lower()}/{platform.machine()}",
    )
