"""ssl contexts from CA-issued identities (the mTLS wiring).

``server_context`` requires and verifies client certificates against the
CA (mutual TLS — the reference's auto-issued mTLS between services);
``client_context`` presents the peer identity and verifies the server
against the same CA.  The HTTP services wrap their listening sockets with
these; clients pass theirs to urllib.
"""

from __future__ import annotations

import contextlib
import shutil
import ssl
import tempfile

from .ca import PeerIdentity


@contextlib.contextmanager
def _materialized(identity: PeerIdentity):
    """ssl needs files; load_cert_chain/load_verify_locations read them
    eagerly, so the key material is DELETED the moment the context is
    built — nothing lingers on disk."""
    directory = tempfile.mkdtemp(prefix="df-tls-")
    try:
        yield identity.write(directory)
    finally:
        shutil.rmtree(directory, ignore_errors=True)


def server_context(identity: PeerIdentity) -> ssl.SSLContext:
    with _materialized(identity) as paths:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(paths["cert"], paths["key"])
        ctx.load_verify_locations(paths["ca"])
    ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    return ctx


def reload_context(ctx: ssl.SSLContext, identity: PeerIdentity) -> None:
    """Swap a NEW identity into an existing context in place — live
    listeners/dialers pick the fresh chain up at their next handshake
    (ssl reads the context at handshake time, not at wrap time), which
    is what makes short-TTL auto-issued certs renewable without a
    restart."""
    with _materialized(identity) as paths:
        ctx.load_cert_chain(paths["cert"], paths["key"])
        ctx.load_verify_locations(paths["ca"])


def client_context(
    identity: PeerIdentity, *, check_hostname: bool = False
) -> ssl.SSLContext:
    """Client mTLS context.

    ``check_hostname`` defaults OFF: peers dial each other by announced IP
    and the trust anchor here is CERT_REQUIRED chain verification against
    the private CA (only CA-issued identities connect at all) — hostname
    matching adds value only when server identities embed their IP/DNS
    SANs (PeerIdentity.issue(..., ips=[...]) supports that; turn this on
    then)."""
    with _materialized(identity) as paths:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(paths["cert"], paths["key"])
        ctx.load_verify_locations(paths["ca"])
    ctx.check_hostname = check_hostname
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    return ctx
