"""HMAC bearer tokens with roles (manager PAT / RBAC-lite).

Reference: manager's personal access tokens + casbin RBAC guard the REST
surface.  Here: manager-signed HMAC tokens carrying (subject, role,
expiry); servers verify with the shared secret and enforce a minimum role
per operation.  Token format: base64url(payload).base64url(hmac).
"""

from __future__ import annotations

import base64
import enum
import hmac
import json
import time
from dataclasses import dataclass
from typing import Optional


class Role(enum.IntEnum):
    """Ordered roles: a check passes when token.role >= required."""

    READONLY = 0
    PEER = 1       # daemons/schedulers: announce, register, report
    OPERATOR = 2   # model activation, preheat
    ADMIN = 3


@dataclass
class TokenClaims:
    subject: str
    role: Role
    expires_at: float

    @property
    def expired(self) -> bool:
        return time.time() > self.expires_at


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


class TokenIssuer:
    def __init__(self, secret: bytes) -> None:
        if len(secret) < 16:
            raise ValueError("token secret must be >= 16 bytes")
        self._secret = secret

    def issue(
        self, subject: str, role: Role, *, ttl_s: float = 24 * 3600.0
    ) -> str:
        payload = json.dumps(
            {"sub": subject, "role": int(role), "exp": time.time() + ttl_s},
            separators=(",", ":"),
        ).encode()
        sig = hmac.new(self._secret, payload, "sha256").digest()
        return f"{_b64(payload)}.{_b64(sig)}"


class TokenVerifier:
    def __init__(self, secret: bytes) -> None:
        self._secret = secret

    def verify(self, token: str) -> Optional[TokenClaims]:
        """Claims when the token is authentic and unexpired, else None."""
        try:
            payload_b64, sig_b64 = token.split(".", 1)
            payload = _unb64(payload_b64)
            sig = _unb64(sig_b64)
        except (ValueError, TypeError):
            return None
        expected = hmac.new(self._secret, payload, "sha256").digest()
        if not hmac.compare_digest(sig, expected):
            return None
        try:
            data = json.loads(payload)
            claims = TokenClaims(
                subject=data["sub"],
                role=Role(int(data["role"])),
                expires_at=float(data["exp"]),
            )
        except (KeyError, ValueError, json.JSONDecodeError):
            return None
        return None if claims.expired else claims

    def authorize(self, token: Optional[str], required: Role) -> Optional[TokenClaims]:
        """Claims when the token grants at least ``required``, else None."""
        if token is None:
            return None
        claims = self.verify(token)
        if claims is None or claims.role < required:
            return None
        return claims


def resolve_credential(token, verifier, users):
    """ONE credential-resolution path for every transport (REST + gRPC):
    → (subject, Role, kind) or None.  kind ∈ {"session", "pat"}.

    Session tokens are re-checked against the live user store so a
    disable or demotion takes effect immediately on ALL ports, not at
    token expiry; PATs resolve through the store with their capped role.
    """
    if token is None:
        return None
    if users is not None:
        from ..manager.users import PAT_PREFIX

        if token.startswith(PAT_PREFIX):
            user = users.authenticate_pat(token)
            return None if user is None else (user.id, user.role, "pat")
    if verifier is not None:
        claims = verifier.verify(token)
        if claims is None:
            return None
        role = claims.role
        if users is not None:
            user = users.get(claims.subject)
            if user is not None:
                if user.state != "enabled":
                    return None
                role = min(role, user.role)
        return (claims.subject, role, "session")
    return None
