"""Security: CA-backed mTLS + token auth (reference: pkg/issuer +
pkg/rpc/security — the manager acts as a CA issuing short-lived certs that
schedulers/daemons use for auto-provisioned mTLS, scheduler/scheduler.go:186-222).

- ``ca``     — an EC-P256 certificate authority: self-signed root, CSR
  signing with short validity, SAN support (the certify-integration
  equivalent); peer helpers to generate keys/CSRs and request certs.
- ``tokens`` — HMAC-signed bearer tokens with roles and expiry (the
  manager's personal-access-token / RBAC-lite surface for REST mutations).
- ``tls``    — ssl.SSLContext builders wiring CA-issued identities into
  the HTTP servers/clients for mutual TLS.
"""

from .tokens import Role, TokenIssuer, TokenVerifier  # noqa: F401

try:  # pragma: no cover - environment-dependent
    from .ca import CertificateAuthority, PeerIdentity  # noqa: F401
    from .tls import client_context, server_context  # noqa: F401
except ImportError:  # `cryptography` absent: token auth (and everything
    # that merely imports the manager package) must keep working — only
    # the mTLS/CA surface itself is gated off.  Callers that configure
    # auto-issue get the ImportError at use, not at import of unrelated
    # modules.
    CertificateAuthority = PeerIdentity = None  # type: ignore[assignment]
    client_context = server_context = None  # type: ignore[assignment]
