"""Certificate authority + peer identity (pkg/issuer analog).

The manager hosts the CA; schedulers/daemons generate a key + CSR at boot
and request a short-lived certificate carrying their host identity in the
SAN — the auto-issued-mTLS flow the reference builds on certify.
"""

from __future__ import annotations

import datetime
import ipaddress
from dataclasses import dataclass
from typing import List, Optional

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

DEFAULT_CERT_TTL = datetime.timedelta(hours=24)
# Server-side ceiling on client-requested TTLs: revocation is
# non-renewal, so no caller may mint an effectively permanent cert.
MAX_CERT_TTL = datetime.timedelta(days=7)


def clamp_ttl(ttl_hours: int) -> datetime.timedelta:
    """Requested hours → issued validity: 0/negative → default, anything
    else capped at MAX_CERT_TTL (and immune to timedelta overflow)."""
    if ttl_hours <= 0:
        return DEFAULT_CERT_TTL
    return min(
        datetime.timedelta(hours=min(int(ttl_hours), 24 * 365)), MAX_CERT_TTL
    )


def _name(common_name: str) -> x509.Name:
    return x509.Name(
        [
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, "dragonfly2-tpu"),
            x509.NameAttribute(NameOID.COMMON_NAME, common_name),
        ]
    )


def _san(hostnames: List[str], ips: List[str]) -> x509.SubjectAlternativeName:
    entries: list = [x509.DNSName(h) for h in hostnames]
    for ip in ips:
        entries.append(x509.IPAddress(ipaddress.ip_address(ip)))
    return x509.SubjectAlternativeName(entries)


def _new_key_and_csr(
    common_name: str,
    hostnames: Optional[List[str]],
    ips: Optional[List[str]],
):
    """Fresh EC key + CSR — ONE builder for the in-process and
    over-the-wire issuance paths, so subject/SAN construction can't
    diverge between them."""
    key = ec.generate_private_key(ec.SECP256R1())
    csr = (
        x509.CertificateSigningRequestBuilder()
        .subject_name(_name(common_name))
        .add_extension(
            _san(hostnames or [common_name], ips or []), critical=False
        )
        .sign(key, hashes.SHA256())
    )
    return key, csr


class CertificateAuthority:
    """Self-signed EC-P256 root that signs peer CSRs with short validity."""

    def __init__(self, common_name: str = "dragonfly2-tpu-ca") -> None:
        self._key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        self.certificate = (
            x509.CertificateBuilder()
            .subject_name(_name(common_name))
            .issuer_name(_name(common_name))
            .public_key(self._key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=3650))
            .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
            .add_extension(
                x509.KeyUsage(
                    digital_signature=True, key_cert_sign=True, crl_sign=True,
                    content_commitment=False, key_encipherment=False,
                    data_encipherment=False, key_agreement=False,
                    encipher_only=False, decipher_only=False,
                ),
                critical=True,
            )
            .sign(self._key, hashes.SHA256())
        )

    @property
    def cert_pem(self) -> bytes:
        return self.certificate.public_bytes(serialization.Encoding.PEM)

    @property
    def key_pem(self) -> bytes:
        return self._key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )

    @classmethod
    def from_pem(cls, key_pem: bytes, cert_pem: bytes) -> "CertificateAuthority":
        """Reload a persisted CA — a restarting daemon must keep its trust
        anchor or every already-distributed sni-ca.pem goes stale."""
        ca = cls.__new__(cls)
        ca._key = serialization.load_pem_private_key(key_pem, password=None)
        ca.certificate = x509.load_pem_x509_certificate(cert_pem)
        return ca

    @classmethod
    def persistent(cls, directory: str, common_name: str = "dragonfly2-tpu-ca") -> "CertificateAuthority":
        """Load the CA from `directory`, creating + saving it on first use."""
        import os

        key_path = os.path.join(directory, "ca-key.pem")
        cert_path = os.path.join(directory, "ca-cert.pem")
        if os.path.exists(key_path) and os.path.exists(cert_path):
            with open(key_path, "rb") as f:
                key_pem = f.read()
            with open(cert_path, "rb") as f:
                cert_pem = f.read()
            return cls.from_pem(key_pem, cert_pem)
        ca = cls(common_name)
        os.makedirs(directory, exist_ok=True)
        for path, data in ((key_path, ca.key_pem), (cert_path, ca.cert_pem)):
            # 0600 from the first byte: no default-umask window where
            # another local user could read the signing key.
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(data)
        return ca

    def sign_csr(
        self,
        csr_pem: bytes,
        *,
        ttl: datetime.timedelta = DEFAULT_CERT_TTL,
    ) -> bytes:
        """Issue a peer certificate from a CSR (manager-side issuance).

        The CSR's subject and SAN are honored; validity is capped short so
        revocation is simply non-renewal (the reference's certify flow).
        """
        csr = x509.load_pem_x509_csr(csr_pem)
        if not csr.is_signature_valid:
            raise ValueError("CSR signature invalid")
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (
            x509.CertificateBuilder()
            .subject_name(csr.subject)
            .issuer_name(self.certificate.subject)
            .public_key(csr.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + ttl)
            .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
            .add_extension(
                x509.ExtendedKeyUsage(
                    [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH,
                     x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]
                ),
                critical=False,
            )
        )
        try:
            san = csr.extensions.get_extension_for_class(x509.SubjectAlternativeName)
            builder = builder.add_extension(san.value, critical=False)
        except x509.ExtensionNotFound:
            pass
        cert = builder.sign(self._key, hashes.SHA256())
        return cert.public_bytes(serialization.Encoding.PEM)


class IdentityRenewer:
    """Keeps an auto-issued identity alive past its TTL: re-requests a
    fresh certificate at ``fraction`` of the remaining validity and
    reloads the given ssl contexts IN PLACE (security.tls.reload_context
    — live piece servers/fetchers pick the new chain up at the next
    handshake, no restart).  Issue failures retry on a short backoff
    while the old cert is still valid.

    Scope note: Python ``ssl`` contexts renew live; gRPC channel/server
    credentials are immutable once built — a cluster running mTLS gRPC
    rotates those by service restart within the cert TTL (documented in
    config.SecuritySection).
    """

    def __init__(
        self,
        identity: "PeerIdentity",
        request_fn,
        contexts,
        *,
        fraction: float = 0.5,
        min_interval_s: float = 60.0,
    ) -> None:
        import threading as _threading

        self.identity = identity
        self._request_fn = request_fn
        self._contexts = list(contexts)
        self.fraction = fraction
        self.min_interval_s = min_interval_s
        self.renewals = 0
        self._stop = _threading.Event()
        self._thread: Optional[object] = None

    def start(self) -> "IdentityRenewer":
        import threading as _threading

        self._thread = _threading.Thread(
            target=self._loop, name="mtls-renew", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        from .tls import reload_context

        while not self._stop.is_set():
            wait = max(
                self.identity.seconds_left() * self.fraction,
                self.min_interval_s,
            )
            if self._stop.wait(wait):
                return
            try:
                fresh = self._request_fn()
                for ctx in self._contexts:
                    reload_context(ctx, fresh)
                self.identity = fresh
                self.renewals += 1
            except Exception:  # noqa: BLE001 — old cert still valid; retry soon
                if self._stop.wait(self.min_interval_s):
                    return


@dataclass
class PeerIdentity:
    """A peer's key + CA-issued certificate (daemon/scheduler side)."""

    key_pem: bytes
    cert_pem: bytes
    ca_pem: bytes

    @classmethod
    def issue(
        cls,
        ca: CertificateAuthority,
        *,
        common_name: str,
        hostnames: Optional[List[str]] = None,
        ips: Optional[List[str]] = None,
        ttl: datetime.timedelta = DEFAULT_CERT_TTL,
    ) -> "PeerIdentity":
        """Generate a key, CSR against the CA, receive the signed cert —
        the whole certify bootstrap in one call (in-process CA; over the
        wire the CSR posts to the manager)."""
        key, csr = _new_key_and_csr(common_name, hostnames, ips)
        cert_pem = ca.sign_csr(
            csr.public_bytes(serialization.Encoding.PEM), ttl=ttl
        )
        return cls(
            key_pem=key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            ),
            cert_pem=cert_pem,
            ca_pem=ca.cert_pem,
        )

    @classmethod
    def request_from_manager(
        cls,
        manager_url: str,
        *,
        common_name: str,
        hostnames: Optional[List[str]] = None,
        ips: Optional[List[str]] = None,
        ttl_hours: int = 0,
        token: Optional[str] = None,
        timeout: float = 10.0,
        attempts: int = 5,
    ) -> "PeerIdentity":
        """Self-provision an mTLS identity OVER THE WIRE at boot (the
        reference certify flow, scheduler.go:186-222 / pkg/issuer): the
        private key is generated HERE and never leaves the process —
        only the CSR travels; the manager answers with the signed cert
        and the cluster trust root (POST /api/v1/certs:issue).

        Retries connection failures with backoff — services routinely
        boot before the manager's port listens (compose/systemd restart
        order); an HTTP error (401, 400) is terminal and raises as-is."""
        import json as _json
        import time as _time
        import urllib.error
        import urllib.request

        key, csr = _new_key_and_csr(common_name, hostnames, ips)
        body = _json.dumps({
            "csr_pem": csr.public_bytes(serialization.Encoding.PEM).decode(),
            "ttl_hours": ttl_hours,
        }).encode()
        req = urllib.request.Request(
            manager_url.rstrip("/") + "/api/v1/certs:issue",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        for attempt in range(attempts):
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    reply = _json.loads(resp.read())
                break
            except urllib.error.HTTPError:
                raise  # the manager answered: retrying cannot help
            except (urllib.error.URLError, ConnectionError, TimeoutError, OSError):
                if attempt == attempts - 1:
                    raise
                _time.sleep(min(0.5 * 2 ** attempt, 5.0))
        return cls(
            key_pem=key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            ),
            cert_pem=reply["cert_pem"].encode(),
            ca_pem=reply["ca_pem"].encode(),
        )

    def seconds_left(self) -> float:
        """Validity remaining on this identity's certificate."""
        cert = x509.load_pem_x509_certificate(self.cert_pem)
        now = datetime.datetime.now(datetime.timezone.utc)
        return (cert.not_valid_after_utc - now).total_seconds()

    def write(self, directory: str) -> dict:
        """Materialize to files (ssl contexts need paths); returns paths."""
        import os

        os.makedirs(directory, exist_ok=True)
        paths = {}
        for name, data in (
            ("key.pem", self.key_pem),
            ("cert.pem", self.cert_pem),
            ("ca.pem", self.ca_pem),
        ):
            path = os.path.join(directory, name)
            with open(path, "wb") as f:
                f.write(data)
            os.chmod(path, 0o600)
            paths[name.split(".")[0]] = path
        return paths
