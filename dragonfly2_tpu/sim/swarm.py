"""Swarm simulator: synthetic peers exercising the real scheduler stack.

Each simulated download follows the reference's v1 flow (SURVEY §3.1):
register → schedule → per-piece downloads from assigned parents (piece
cost = piece size / ground-truth bandwidth) → ReportPeerResult → Download
record in storage.  Probe rounds follow §3.3: agents ping ground-truth
RTTs into the topology store; snapshots land in storage.

Because piece costs come from SyntheticCluster's latent bandwidth model,
the records are *learnable* and evaluator quality is *measurable*: rank
parents for a fresh child and compare achieved ground-truth bandwidth.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..records.storage import Storage
from ..records.synthetic import PIECE_SIZE, SyntheticCluster
from ..scheduler import (
    Evaluator,
    NetworkTopology,
    ProbeAgent,
    Resource,
    ScheduleResultKind,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
)
from ..scheduler.resource import Host, Peer, Task
from ..utils.types import HostType


@dataclass
class SwarmConfig:
    num_hosts: int = 48
    seed: int = 0
    pieces_per_download: int = 8
    candidate_parent_limit: int = 4


def host_from_latent(lh) -> Host:
    """SyntheticCluster latent host → scheduler Host (stats populated the
    way announce would)."""
    h = Host(
        id=lh.id,
        hostname=lh.hostname,
        ip=lh.ip,
        port=8002,
        download_port=8001,
        type=HostType.SUPER_SEED if lh.type == "super" else HostType.NORMAL,
        concurrent_upload_limit=lh.upload_limit,
    )
    h.stats.network.idc = lh.idc_name
    h.stats.network.location = lh.location
    h.stats.cpu.percent = lh.cpu_load * 100.0
    h.stats.memory.used_percent = lh.mem_load * 100.0
    h.stats.disk.used_percent = lh.disk_load * 100.0
    h.stats.network.tcp_connection_count = lh.tcp_conns
    h.stats.network.upload_tcp_connection_count = lh.upload_conns
    h.upload_count = lh.upload_count
    h.upload_failed_count = lh.upload_failed
    h.concurrent_upload_count = lh.concurrent_uploads
    return h


def build_announce_swarm(
    num_hosts: int = 1000,
    *,
    seed: int = 0,
    total_piece_count: int = 16,
    max_finished: int = 12,
    served_parents: int = 6,
):
    """Serving-path fixture: ONE task with a Running peer per synthetic
    host, piece costs and parent-attributed child pieces populated, ready
    for ``evaluate_parents`` announce workloads (tools/bench_sched.py and
    the vectorized-vs-scalar property tests).  Returns (task, peers).
    """
    cluster = SyntheticCluster(num_hosts=num_hosts, seed=seed)
    rng = np.random.default_rng(seed)
    task = Task("announce-bench-task", "https://origin.example.com/bench-blob")
    task.content_length = total_piece_count * PIECE_SIZE
    task.total_piece_count = total_piece_count
    task.piece_size = PIECE_SIZE
    peers = []
    for i in range(num_hosts):
        host = host_from_latent(cluster.hosts[i])
        peer = Peer(f"bench-peer-{i}", task, host)
        task.store_peer(peer)
        host.store_peer(peer)
        peer.fsm.event("RegisterNormal")
        peer.fsm.event("Download")
        peer.cost_ns = int(rng.integers(0, 10**10))
        peers.append(peer)
    for i, peer in enumerate(peers):
        n_done = int(rng.integers(0, max_finished + 1))
        # Pieces attributed to a few nearby parents, realistic costs, so
        # featurization's served-piece grouping has real work to do.
        donors = rng.integers(0, num_hosts, size=served_parents)
        for n in range(n_done):
            donor = peers[int(donors[n % served_parents])]
            peer.finish_piece(
                n,
                int(rng.integers(10**6, 10**9)),
                parent_id=donor.id,
                length=PIECE_SIZE,
            )
    return task, peers


class SwarmSimulator:
    def __init__(
        self,
        storage: Storage,
        *,
        config: Optional[SwarmConfig] = None,
        evaluator: Optional[Evaluator] = None,
        cluster: Optional[SyntheticCluster] = None,
    ) -> None:
        self.config = config or SwarmConfig()
        self.cluster = cluster or SyntheticCluster(
            num_hosts=self.config.num_hosts, seed=self.config.seed
        )
        self.rng = np.random.default_rng(self.config.seed)
        self.resource = Resource()
        self.topology = NetworkTopology(self.resource.host_manager)
        self.scheduling = Scheduling(
            evaluator or Evaluator(),
            SchedulingConfig(
                retry_interval=0,
                candidate_parent_limit=self.config.candidate_parent_limit,
            ),
        )
        self.service = SchedulerService(
            self.resource, self.scheduling, storage, self.topology
        )
        self.storage = storage
        self.hosts: List[Host] = [self._register_host(i) for i in range(self.cluster.num_hosts)]
        self._host_index: Dict[str, int] = {h.id: i for i, h in enumerate(self.hosts)}

    def _register_host(self, i: int) -> Host:
        h = host_from_latent(self.cluster.hosts[i])
        self.resource.store_host(h)
        return h

    # -- download simulation -------------------------------------------------

    def simulate_download(
        self, child_idx: Optional[int] = None, url: Optional[str] = None
    ) -> Optional[Peer]:
        """One full download; returns the child peer (None if unschedulable)."""
        r = self.rng
        child_idx = int(r.integers(0, len(self.hosts))) if child_idx is None else child_idx
        child_host = self.hosts[child_idx]
        url = url or f"https://origin.example.com/blob/{int(r.integers(0, 1 << 16))}"

        result = self.service.register_peer(host=child_host, url=url)
        peer = result.peer
        task = peer.task
        if task.content_length < 0:
            # First peer learns the content length from the origin; sizes
            # vary per task so the training corpus spans content lengths.
            pieces = int(r.integers(2, 2 * self.config.pieces_per_download + 1))
            task.content_length = pieces * PIECE_SIZE
            task.total_piece_count = pieces
            task.piece_size = PIECE_SIZE

        if result.schedule is None or result.schedule.kind is not ScheduleResultKind.PARENTS:
            # Back-to-source: origin serves at the child's download capacity.
            bw = float(self.cluster.down_cap[child_idx]) * 0.5
            for n in range(task.total_piece_count):
                cost = int(PIECE_SIZE / bw * 1e9)
                self.service.report_piece_finished(
                    peer, n, parent_id="", length=PIECE_SIZE, cost_ns=cost
                )
            self.service.report_peer_finished(peer)
            return peer

        parents = result.schedule.parents
        # Pieces round-robin over assigned parents with ground-truth costs.
        for n in range(task.total_piece_count):
            parent = parents[n % len(parents)]
            p_idx = self._host_index[parent.host.id]
            bw = self.cluster.bandwidth(p_idx, child_idx)
            cost = int(PIECE_SIZE / max(bw, 1e3) * 1e9)
            self.service.report_piece_finished(
                peer, n, parent_id=parent.id, length=PIECE_SIZE, cost_ns=cost
            )
        self.service.report_peer_finished(peer)
        return peer

    def seed_task(self, url: str, n_seeds: int = 4) -> None:
        """Bootstrap a task: n hosts fetch from origin (become parents)."""
        for _ in range(n_seeds):
            self.simulate_download(
                child_idx=int(self.rng.integers(0, len(self.hosts))), url=url
            )

    def run_downloads(self, n: int, *, tasks: int = 8) -> int:
        """Simulate a workload over a small task catalog; returns records written."""
        urls = [f"https://origin.example.com/blob/{t}" for t in range(tasks)]
        for url in urls:
            self.seed_task(url, n_seeds=2)
        done = 0
        for _ in range(n):
            url = urls[int(self.rng.integers(0, len(urls)))]
            if self.simulate_download(url=url) is not None:
                done += 1
        return done

    # -- probe simulation (§3.3) ---------------------------------------------

    def run_probe_rounds(self, rounds: int = 3) -> None:
        # Agents built once: reconstructing num_hosts ProbeAgents (and
        # their ping closures) per round was pure allocation churn.
        if not hasattr(self, "_probe_agents"):
            self._probe_agents = [
                ProbeAgent(
                    host,
                    self.topology,
                    ping=lambda target, i=i: int(
                        self.cluster.rtt_ns(i, self._host_index[target.id])
                    ),
                )
                for i, host in enumerate(self.hosts)
            ]
        for _ in range(rounds):
            for agent in self._probe_agents:
                agent.sync_probes()

    def snapshot_topology(self) -> int:
        records = self.topology.snapshot()
        for rec in records:
            self.storage.create_network_topology(rec)
        return len(records)

    # -- evaluator quality measurement ---------------------------------------

    def measure_parent_choice_quality(
        self, evaluator: Evaluator, n_trials: int = 50, seed: int = 1234
    ) -> float:
        """Mean ground-truth bandwidth (MB/s) of the evaluator's top-ranked
        parent over fresh (child, candidate-set) draws.  Higher is better;
        the ML-vs-rules comparison metric (BASELINE configs[2] 'beats
        rule-based evaluator')."""
        r = np.random.default_rng(seed)
        total = 0.0
        trials = 0
        # A dedicated task swarm with every host as a potential parent.
        url = "https://origin.example.com/eval-blob"
        reg = self.service.register_peer(host=self.hosts[0], url=url)
        task = reg.peer.task
        if task.content_length < 0:
            task.content_length = 16 * PIECE_SIZE
            task.total_piece_count = 16
            task.piece_size = PIECE_SIZE
        candidates: List[Peer] = []
        for i in range(1, len(self.hosts)):
            res = self.service.register_peer(host=self.hosts[i], url=url)
            p = res.peer
            for n in range(4):
                p.finish_piece(n, int(PIECE_SIZE / 50e6 * 1e9), length=PIECE_SIZE)
            if p.fsm.can("DownloadSucceeded"):
                p.fsm.event("DownloadSucceeded")
            candidates.append(p)
        # Host-index → candidate position, computed ONCE: the per-trial
        # linear scans (`next(c for c in candidates ...)` + a filtered
        # rebuild of the pool) made every trial O(n_hosts).
        cand_host_idx = np.fromiter(
            (self._host_index[c.host.id] for c in candidates),
            dtype=np.int64,
            count=len(candidates),
        )
        peer_by_host_idx = {
            int(idx): c for idx, c in zip(cand_host_idx, candidates)
        }
        for _ in range(n_trials):
            child_i = int(r.integers(0, len(self.hosts)))
            child_peer = peer_by_host_idx.get(child_i)
            pool_positions = np.flatnonzero(cand_host_idx != child_i)
            pool = r.choice(
                pool_positions,
                size=min(8, len(pool_positions)),
                replace=False,
            )
            subset = [candidates[int(j)] for j in pool]
            probe_child = child_peer or reg.peer
            ranked = evaluator.evaluate_parents(subset, probe_child, task.total_piece_count)
            top_idx = self._host_index[ranked[0].host.id]
            total += self.cluster.bandwidth(top_idx, child_i, noise=False)
            trials += 1
        return total / trials / 1e6
