"""Telemetry-plane chaos drills (ISSUE 12 acceptance; DESIGN.md §23).

Two drills, both runnable standalone (``python -m
dragonfly2_tpu.sim.telemetry``) and driven by tier-1
(tests/test_telemetry_chaos.py):

**Kill drill** — N subprocess "daemons" run a synthetic piece-fetch
storm through the REAL telemetry write path: seeded latencies feed
``PieceLatencyTracker.observe`` (the conductor's hot-path sample point →
the ``daemon_piece_fetch_seconds`` sketch) and a REAL ``MetricJournal``
snapshots the default registry.  One child carries a ``crash``
FaultSpec on the ``metrics.journal.write`` seam and SIGKILLs itself at a
deterministic journal write, mid-storm.  The drill then tears the dead
child's tail frame (the mid-``os.write`` power-cut signature a
seam-placed kill cannot produce byte-exactly) and flips one payload
byte in a survivor's mid-file frame (bit rot).  ``fleet_assemble`` must
still produce fleet p50/p99: torn tail tolerated, the digest-bad frame
counted but NEVER admitted, and — because every child also appends each
raw sample to a sidecar before observing it — the merged sketch
quantiles are checked against an EXACT oracle computed from precisely
the samples the admitted frames cover.

**Burn-rate drill** — a latency SLO over a synthetic fetch sketch runs
healthy → overloaded → recovered phases against a live ``SLOEngine``
while a ``MetricJournal`` snapshots alongside every tick.  The alert
must fire within one fast window of the overload, clear after recovery,
and the journal replay (``slo.replay_fleet``) must reconstruct the same
state ``/debug/slo`` served live.
"""

from __future__ import annotations

import json
import math
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

DRILL_SLO = {
    "name": "drill_fetch_p95",
    "objective": "latency",
    "metric": "drill_fetch_seconds",
    "threshold_ms": 100.0,
    "target": 0.95,
    "fast_window_s": 0.6,
    "slow_window_s": 2.4,
    "burn_threshold": 2.0,
}


# ---------------------------------------------------------------------------
# Child body (the kill drill's subprocess workload)
# ---------------------------------------------------------------------------


def child_main(argv: List[str]) -> int:
    """Synthetic daemon: seeded fetch latencies through the real
    tracker → sketch → journal path.  Raw samples are appended (one
    O_APPEND write per line, BEFORE the observe) to a sidecar the parent
    uses as the exact oracle."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--journal", required=True)
    p.add_argument("--raw", required=True)
    p.add_argument("--service", default="dfdaemon")
    p.add_argument("--samples", type=int, default=400)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--snapshot-every", type=int, default=50)
    args = p.parse_args(argv)

    from ..utils import faultinject

    faultinject.install_from_env()

    import random

    from ..daemon.piece_pipeline import PieceLatencyTracker
    from ..utils.metric_journal import MetricJournal

    tracker = PieceLatencyTracker()
    journal = MetricJournal(
        args.journal, service=args.service, interval_s=3600.0,
        run_id=f"run-{args.service}-{args.seed}",
    )
    rng = random.Random(args.seed)
    raw_fd = os.open(args.raw, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    for i in range(args.samples):
        latency = rng.lognormvariate(-3.5, 1.0)
        # Raw sample durable BEFORE the observe: the oracle prefix per
        # admitted snapshot is then exact by construction.
        os.write(raw_fd, f"{latency!r}\n".encode())
        tracker.observe(latency)
        if (i + 1) % args.snapshot_every == 0:
            journal.write_snapshot()
    journal.close()
    os.close(raw_fd)
    print(json.dumps({"ok": True, "samples": args.samples}), flush=True)
    return 0


# ---------------------------------------------------------------------------
# Kill drill
# ---------------------------------------------------------------------------


def run_kill_drill(
    workdir: str,
    *,
    n_children: int = 3,
    samples: int = 400,
    snapshot_every: int = 50,
    kill_at_write: int = 4,
) -> Dict[str, Any]:
    """SIGKILL one of ``n_children`` mid-storm; assemble the fleet view
    from the survivors plus the dead child's torn journal.  Returns the
    drill report (asserted by tests, rendered into TELEMETRY_r*.json)."""
    os.makedirs(workdir, exist_ok=True)
    procs = []
    journals: List[str] = []
    raws: List[str] = []
    for i in range(n_children):
        journal = os.path.join(workdir, f"daemon{i}.dfmj")
        raw = os.path.join(workdir, f"daemon{i}.raw")
        journals.append(journal)
        raws.append(raw)
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "DF_LOCK_WITNESS": "0"}
        if i == 0:
            # The victim: crash (self-SIGKILL) at its Nth journal write.
            env["DF_FAULTINJECT"] = json.dumps({
                "seed": 0,
                "faults": [{
                    "site": "metrics.journal.write", "kind": "crash",
                    "at": [kill_at_write - 1],
                }],
            })
        procs.append(subprocess.Popen(
            [
                sys.executable, "-m", "dragonfly2_tpu.sim.telemetry",
                "--child",
                "--journal", journal, "--raw", raw,
                "--service", f"dfdaemon{i}",
                "--samples", str(samples), "--seed", str(100 + i),
                "--snapshot-every", str(snapshot_every),
            ],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        ))
    outs = []
    for proc in procs:
        out, err = proc.communicate(timeout=120)
        outs.append((proc.returncode, out, err))
    if outs[0][0] != -signal.SIGKILL:
        raise AssertionError(
            f"victim was not SIGKILLed: rc={outs[0][0]} "
            f"out={outs[0][1]!r} err={outs[0][2]!r}"
        )
    for rc, out, err in outs[1:]:
        if rc != 0:
            raise AssertionError(f"survivor failed: {rc} {out!r} {err!r}")

    # The kill left the victim's journal ending at a frame boundary (the
    # crash seam fires before the write).  Tear its tail frame partially
    # — the byte-exact signature of a SIGKILL landing mid-os.write —
    # and flip one payload byte in a survivor's FIRST frame (bit rot);
    # its final cumulative frame is untouched, so the merge loses
    # nothing while the digest check must reject the doctored frame.
    with open(journals[0], "rb") as f:
        victim = f.read()
    assert len(victim) > 40, "victim journal unexpectedly empty"
    with open(journals[0], "wb") as f:
        f.write(victim[:-17])
    with open(journals[1], "rb") as f:
        surv = bytearray(f.read())
    first_payload = surv.find(b'"v"')
    assert first_payload > 0
    surv[first_payload + 1] ^= 0x01
    with open(journals[1], "wb") as f:
        f.write(surv)

    from tools.fleet_assemble import build_report

    report = build_report(journals)

    # -- acceptance: journal-level invariants --------------------------------
    stats = {s["path"]: s for s in report["journals"]}
    if not stats[journals[0]]["torn_tail"]:
        raise AssertionError("victim journal's torn tail not detected")
    if stats[journals[1]]["corrupt"] != 1:
        raise AssertionError("doctored survivor frame not rejected")
    if stats[journals[0]]["corrupt"] != 0:
        raise AssertionError("torn tail must not count as corrupt")
    if len(report["runs"]) != n_children:
        raise AssertionError(f"expected {n_children} runs: {report['runs']}")

    # -- acceptance: merged quantiles vs the exact oracle --------------------
    from dragonfly2_tpu.utils.metric_journal import (
        final_snapshots_by_run,
        replay_metric_journal,
    )

    oracle: List[float] = []
    per_run_counts: Dict[str, int] = {}
    for i, (journal, raw) in enumerate(zip(journals, raws)):
        snaps, _ = replay_metric_journal(journal)
        finals = final_snapshots_by_run(snaps)
        covered = 0
        for snap in finals.values():
            state = snap["metrics"].get("daemon_piece_fetch_seconds")
            if state:
                covered += int(sum(
                    st["total"] for _k, st in state["series"]
                ))
        per_run_counts[f"dfdaemon{i}"] = covered
        with open(raw) as f:
            all_samples = [float(line) for line in f if line.strip()]
        # Cumulative snapshots cover a PREFIX of the raw sample stream.
        oracle.extend(all_samples[:covered])

    fleet = report["quantiles"]["daemon_piece_fetch_seconds"]
    if int(fleet["count"]) != len(oracle):
        raise AssertionError(
            f"merged sketch count {fleet['count']} != oracle {len(oracle)} "
            "— a torn/corrupt frame leaked into the merge"
        )
    oracle.sort()
    alpha = fleet["alpha"]
    checks = {}
    for q in (0.5, 0.99):
        rank = max(int(math.ceil(q * len(oracle))), 1) - 1
        exact = oracle[rank]
        est = fleet[f"p{q * 100:g}"]
        rel = abs(est - exact) / exact
        checks[f"p{q * 100:g}"] = {
            "exact": exact, "estimate": est, "rel_error": rel,
        }
        if rel > alpha * 1.0001 + 1e-12:
            raise AssertionError(
                f"fleet p{q * 100:g} outside the declared bound: "
                f"{est} vs exact {exact} (rel {rel:.5f} > α={alpha})"
            )
    return {
        "ok": True,
        "children": n_children,
        "victim_sigkilled": True,
        "frames_admitted": report["total_frames"],
        "corrupt_rejected": report["total_corrupt"],
        "torn_tail_tolerated": True,
        "oracle_samples": len(oracle),
        "per_run_covered": per_run_counts,
        "alpha": alpha,
        "quantile_checks": checks,
    }


# ---------------------------------------------------------------------------
# Burn-rate drill
# ---------------------------------------------------------------------------


def run_burnrate_drill(
    journal_path: Optional[str] = None,
    *,
    slo: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Synthetic overload: the alert must fire within one fast window,
    clear after recovery, and the journal replay must reconstruct the
    live ``/debug/slo`` state."""
    import tempfile

    from ..utils.metric_journal import MetricJournal, replay_metric_journal
    from ..utils.metrics import Registry
    from ..utils.slo import SLOEngine, replay_fleet

    slo = dict(slo or DRILL_SLO)
    fast = slo["fast_window_s"]
    reg = Registry()
    sketch = reg.sketch(slo["metric"], "drill fetch latency")
    engine = SLOEngine([slo], registry=reg)
    owns_tmp = journal_path is None
    if owns_tmp:
        fd, journal_path = tempfile.mkstemp(suffix=".dfmj")
        os.close(fd)
        os.unlink(journal_path)
    journal = MetricJournal(
        journal_path, registry=reg, service="drill", interval_s=3600.0,
    )

    good_lat = slo["threshold_ms"] / 1e3 * 0.1
    bad_lat = slo["threshold_ms"] / 1e3 * 4.0

    def step(latency: float) -> Dict[str, Any]:
        for _ in range(5):
            sketch.observe(latency)
        state = engine.tick()[slo["name"]]
        journal.write_snapshot()
        time.sleep(0.02)
        return state

    fired_ts = None

    report: Dict[str, Any] = {"ok": True, "slo": slo}
    try:
        # Healthy phase: a full slow window of good traffic.
        deadline = time.monotonic() + slo["slow_window_s"]
        while time.monotonic() < deadline:
            state = step(good_lat)
        if state["breached"]:
            raise AssertionError(f"breached while healthy: {state}")

        # Overload: must flip within ONE fast window (+ scheduling slack).
        t_overload = time.monotonic()
        fired_after = None
        deadline = t_overload + fast * 1.5
        while time.monotonic() < deadline:
            state = step(bad_lat)
            if state["breached"]:
                fired_after = time.monotonic() - t_overload
                fired_ts = time.time()
                break
        if fired_after is None:
            raise AssertionError(
                f"alert did not fire within {fast * 1.5:.1f}s: {state}"
            )
        report["fired_after_s"] = round(fired_after, 3)
        report["fired_within_fast_window"] = fired_after <= fast * 1.25

        # Recovery: good traffic again; must clear.
        t_recover = time.monotonic()
        cleared_after = None
        deadline = t_recover + slo["slow_window_s"] * 2
        while time.monotonic() < deadline:
            state = step(good_lat)
            if not state["breached"]:
                cleared_after = time.monotonic() - t_recover
                break
        if cleared_after is None:
            raise AssertionError("alert never cleared after recovery")
        report["cleared_after_s"] = round(cleared_after, 3)

        # Settle: one more fast window of good traffic so the final
        # burn rates sit away from the threshold boundary (the
        # live-vs-replay comparison is then tight, not boundary-racy).
        deadline = time.monotonic() + fast * 1.2
        while time.monotonic() < deadline:
            step(good_lat)

        # Live /debug/slo state vs journal-replay reconstruction —
        # at the end AND at the moment the alert fired.
        live = engine.state()["slos"][0]
        journal.close()
        snaps, stats = replay_metric_journal(journal_path)
        if stats["corrupt"]:
            raise AssertionError(f"journal corrupt frames: {stats}")
        replayed = replay_fleet(snaps, [slo]).state()["slos"][0]
        if replayed["breached"] != live["breached"]:
            raise AssertionError(
                f"replay disagrees with live: {replayed} vs {live}"
            )
        drift = abs(
            replayed["burn_rate_fast"] - live["burn_rate_fast"]
        )
        if drift > 0.25:
            raise AssertionError(
                f"replay burn rate drifted from live: {drift}"
            )
        at_fire = replay_fleet(
            [s for s in snaps if s["ts"] <= fired_ts + 1e-6], [slo]
        ).state()["slos"][0]
        if not at_fire["breached"]:
            raise AssertionError(
                f"replay at fire time not breached: {at_fire}"
            )
        report["replay_matches_live"] = True
        report["replay_breached_at_fire"] = True
        report["replay_burn_drift"] = round(drift, 6)
        report["journal_frames"] = stats["frames"]
        report["final_state"] = {
            "live": {k: live[k] for k in
                     ("breached", "burn_rate_fast", "burn_rate_slow")},
            "replay": {k: replayed[k] for k in
                       ("breached", "burn_rate_fast", "burn_rate_slow")},
        }
    finally:
        journal.close()
        engine.close()
        if owns_tmp:
            try:
                os.unlink(journal_path)
            except OSError:
                pass
    return report


# ---------------------------------------------------------------------------
# Entry point: full drill round → one TELEMETRY JSON line/file
# ---------------------------------------------------------------------------


def run_round(workdir: str) -> Dict[str, Any]:
    kill = run_kill_drill(os.path.join(workdir, "kill"))
    burn = run_burnrate_drill(os.path.join(workdir, "burn.dfmj"))
    return {
        "ok": kill["ok"] and burn["ok"],
        "metric": "fleet_telemetry_drills",
        "sketch_alpha": kill["alpha"],
        "kill_drill": kill,
        "burnrate_drill": burn,
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--child":
        return child_main(argv[1:])
    import argparse
    import tempfile

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default=None, metavar="FILE",
                   help="write the drill round JSON here (TELEMETRY_r*.json)")
    args = p.parse_args(argv)
    with tempfile.TemporaryDirectory() as workdir:
        try:
            round_data = run_round(workdir)
        except Exception as exc:  # noqa: BLE001 — one parseable line
            round_data = {
                "ok": False,
                "metric": "fleet_telemetry_drills",
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }
    text = json.dumps(round_data, indent=2, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    return 0 if round_data.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
