"""In-process multi-node simulation (SURVEY §4 tier 2).

The reference simulates whole swarms in-process for scheduling tests
(scheduler/scheduling/scheduling_test.go) and fakes Redis for topology
tests; it has no end-to-end data→train loop to simulate (the trainer is a
stub).  This package drives the REAL components — SchedulerService,
NetworkTopology, record Storage, TrainerService, ModelRegistry — against
the SyntheticCluster's ground-truth bandwidth/RTT model, closing the loop
the reference never closed, deterministically and without sockets.
"""

from .swarm import SwarmSimulator, SwarmConfig  # noqa: F401
from .fleet import (  # noqa: F401
    ColumnarPopulation,
    FleetConfig,
    FleetSwarmDriver,
    ShardedFleet,
)
from .lifecycle import (  # noqa: F401
    LifecycleDrillConfig,
    run_lifecycle_drill,
)
from .chaos import (  # noqa: F401
    ChaosProcess,
    ChaosScenario,
    crash_at,
    drop_storm,
    replay_history,
    sha256_hex,
    task_digest,
    wait_until,
)
