"""Zero-human lifecycle drill (DESIGN.md §29).

The acceptance question for the self-driving lifecycle plane: does the
train→export→register→rollout loop reach ACTIVE **with zero human
steps**, does an injected regression auto-roll back to the last good
ACTIVE, and does a manager bounce mid-promotion RESUME the loop instead
of restarting it?  This module builds the smallest REAL composition that
can answer all three on one box:

- one ``ModelRegistry`` + ``RolloutController`` + ``LocalRolloutClient``
  over a shared ``MemoryBackend`` (the manager side, minus sockets);
- one ``LifecycleDaemon`` with real ``StreamingTrainer`` arms;
- a synthetic linear ground truth ``target = 3 + masked_feats · w``:
  fed records train the MLP against it, and the drill's replay source
  scores REAL exported scorer blobs (loaded back through the registry's
  digest-checked artifact path) against fresh draws from the same
  truth — so promotion and rollback verdicts come from the honest
  regret@k/inversion math in rollout/evaluation.py, never from scripted
  reports.

Stages (``run_lifecycle_drill``):

1. **unattended promotion** — feed one epoch of records, then only call
   ``daemon.step()``: epoch cut → scorer exported (drift baseline
   stamped) → CANDIDATE registered → SHADOW → CANARY → ACTIVE.
2. **injected regression** — the ``export_transform`` chaos hook negates
   the next export's output head; evaluation sees the anti-correlated
   ranking and the controller rolls the candidate back, keeping stage
   1's model ACTIVE (last-good).
3. **bounce resume** — a fresh registry/controller/daemon composition
   over the SAME backend mid-promotion: the lifecycle store hands back
   the watermark and in-flight candidate, the controller reconciles its
   rollout row, and the resumed daemon walks the candidate to ACTIVE —
   exactly one ACTIVE row, artifact digest intact.

``seed`` is the drill's single entropy source (a declared rng injection
seam in records/determinism_contracts.py): every verdict downstream is a
pure function of it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..lifecycle import LifecycleConfig, LifecycleDaemon, regional_model_name
from ..manager.registry import KVBlobStore, ModelRegistry
from ..manager.state import MemoryBackend
from ..records.features import (
    DOWNLOAD_COLUMNS,
    DOWNLOAD_FEATURE_DIM,
    mask_post_hoc,
)
from ..rollout import LocalRolloutClient, RolloutController, RolloutGuardrails
from ..rollout.shadow import SHADOW_COLUMNS
from ..trainer.export import load_scorer

_COL = {name: i for i, name in enumerate(SHADOW_COLUMNS)}


@dataclass
class LifecycleDrillConfig:
    seed: int = 11
    model_name: str = "parent-bandwidth-mlp"
    scheduler_id: str = "scheduler-sim"
    epoch_records: int = 512
    batch_size: int = 64
    max_steps_per_epoch: int = 40
    announces: int = 80           # shadow announce groups per pump
    parents: int = 6              # candidate edges per announce
    min_shadow_samples: int = 200
    min_canary_samples: int = 200
    canary_percent: int = 25
    max_pumps: int = 12           # step() budget per stage


class _World:
    """The synthetic data plane: one linear ground truth shared by the
    training records and the replay evaluations."""

    def __init__(self, cfg: LifecycleDrillConfig) -> None:
        self.cfg = cfg
        self.rng = np.random.default_rng(cfg.seed)
        w = self.rng.standard_normal(DOWNLOAD_FEATURE_DIM) * 0.5
        # Ground truth lives on the serving-visible features only:
        # mask_post_hoc zeroes outcome columns at train AND serve time,
        # so truth on masked columns would be unlearnable by design.
        self.truth_w = mask_post_hoc(w[None, :].astype(np.float32))[0]
        self._pair = 0

    def record_rows(self, n: int) -> np.ndarray:
        """n download records in DOWNLOAD_COLUMNS layout drawn from the
        ground truth (the daemon's training feed)."""
        feats = self.rng.standard_normal(
            (n, DOWNLOAD_FEATURE_DIM)
        ).astype(np.float32)
        rows = np.zeros((n, len(DOWNLOAD_COLUMNS)), np.float32)
        rows[:, 2:2 + DOWNLOAD_FEATURE_DIM] = feats
        rows[:, -1] = 3.0 + mask_post_hoc(feats) @ self.truth_w
        return rows

    def shadow_batch(
        self, cand_scorer, cand_version: int, active_scorer, active_version: int,
    ) -> Tuple[np.ndarray, np.ndarray, int]:
        """One pump's worth of announce groups: fresh feature draws,
        both arms scored with the REAL blobs, per-announce ranks, and
        the realized download rows that the evaluation joins back on
        unique (src, dst) bucket pairs."""
        cfg = self.cfg
        n = cfg.announces * cfg.parents
        feats = self.rng.standard_normal(
            (n, DOWNLOAD_FEATURE_DIM)
        ).astype(np.float32)
        masked = mask_post_hoc(feats)
        target = 3.0 + masked @ self.truth_w
        cand_scores = np.asarray(cand_scorer.score(masked), np.float64)
        if active_scorer is not None:
            act_scores = np.asarray(active_scorer.score(masked), np.float64)
        else:
            # No ACTIVE yet (first rollout): the incumbent arm is the
            # heuristic scheduler — rank-agnostic for this drill.
            act_scores = self.rng.standard_normal(n)
        shadow = np.zeros((n, len(SHADOW_COLUMNS)), np.float32)
        seq0 = self._pair  # announce seq survives across pumps
        shadow[:, _COL["announce_seq"]] = seq0 + np.repeat(
            np.arange(cfg.announces), cfg.parents
        )
        self._pair = seq0 + cfg.announces
        shadow[:, _COL["candidate_version"]] = cand_version
        shadow[:, _COL["active_version"]] = active_version
        # Unique bucket pair per edge → the outcome join is exact.
        idx = np.arange(n) + seq0 * cfg.parents
        shadow[:, _COL["src_bucket"]] = idx % 997
        shadow[:, _COL["dst_bucket"]] = idx // 997 + 1
        for arm, scores in (("candidate", cand_scores), ("active", act_scores)):
            grouped = scores.reshape(cfg.announces, cfg.parents)
            order = np.argsort(-grouped, axis=1)
            ranks = np.argsort(order, axis=1)
            shadow[:, _COL[f"{arm}_score"]] = scores
            shadow[:, _COL[f"{arm}_rank"]] = ranks.reshape(-1)
        dl = np.zeros((n, len(DOWNLOAD_COLUMNS)), np.float32)
        dl[:, 0] = shadow[:, _COL["src_bucket"]]
        dl[:, 1] = shadow[:, _COL["dst_bucket"]]
        dl[:, -1] = target
        return shadow, dl, n


def _build_plane(cfg: LifecycleDrillConfig, backend, world, invert_flag):
    """One manager+daemon composition over ``backend`` (stage 3 builds a
    second one over the same backend to model the bounce)."""
    registry = ModelRegistry(KVBlobStore(backend), backend=backend)
    controller = RolloutController(
        registry,
        backend=backend,
        guardrails=RolloutGuardrails(
            min_shadow_samples=cfg.min_shadow_samples,
            min_canary_samples=cfg.min_canary_samples,
            canary_percent=cfg.canary_percent,
        ),
    )
    client = LocalRolloutClient(controller)

    # Per-candidate-version shadow accumulator: the controller demands
    # NEW samples past each phase baseline, so each pump extends the
    # current candidate's log (and a version flip starts a fresh log,
    # like ShadowScorer's install reset).
    acc: Dict[str, dict] = {}

    def replay_source(key: str):
        name = regional_model_name(cfg.model_name, key)
        cand = registry.candidate_model(cfg.scheduler_id, name)
        if cand is None:
            return None
        active = registry.active_model(cfg.scheduler_id, name)
        cand_scorer = load_scorer(registry.load_artifact(cand))
        active_scorer = (
            load_scorer(registry.load_artifact(active)) if active else None
        )
        shadow, dl, _ = world.shadow_batch(
            cand_scorer, cand.version, active_scorer,
            active.version if active else 0,
        )
        slot = acc.get(key)
        if slot is None or slot["version"] != cand.version:
            slot = {"version": cand.version, "shadow": [], "dl": []}
            acc[key] = slot
        slot["shadow"].append(shadow)
        slot["dl"].append(dl)
        return (
            np.concatenate(slot["shadow"], axis=0),
            np.concatenate(slot["dl"], axis=0),
        )

    def export_transform(scorer, key, epoch):
        if invert_flag["invert"]:
            w, b = scorer.weights[-1]
            scorer.weights[-1] = (-w, -b)
        return scorer

    def trainer_factory(key: str):
        from ..trainer.streaming import StreamingConfig, StreamingTrainer

        return StreamingTrainer(
            StreamingConfig(
                batch_size=cfg.batch_size,
                warmup_steps=4,
                learning_rate=3e-3,
                snapshot_rows=512,
                seed=cfg.seed,
            )
        )

    daemon = LifecycleDaemon(
        registry,
        client,
        config=LifecycleConfig(
            scheduler_id=cfg.scheduler_id,
            model_name=cfg.model_name,
            epoch_records=cfg.epoch_records,
            max_steps_per_epoch=cfg.max_steps_per_epoch,
            min_joined=cfg.min_shadow_samples // 4,
            canary_percent=cfg.canary_percent,
        ),
        backend=backend,
        trainer_factory=trainer_factory,
        replay_source=replay_source,
        export_transform=export_transform,
    )
    return registry, controller, daemon


def _pump_until(daemon, registry, cfg, done) -> int:
    """step() until ``done(registry)`` or the pump budget runs out;
    returns the number of steps taken."""
    for i in range(cfg.max_pumps):
        daemon.step()
        if done():
            return i + 1
    return cfg.max_pumps


def run_lifecycle_drill(
    cfg: Optional[LifecycleDrillConfig] = None,
) -> Dict[str, object]:
    cfg = cfg or LifecycleDrillConfig()
    world = _World(cfg)
    backend = MemoryBackend()
    invert = {"invert": False}
    registry, controller, daemon = _build_plane(cfg, backend, world, invert)
    name = cfg.model_name
    sid = cfg.scheduler_id

    def active_version() -> int:
        m = registry.active_model(sid, name)
        return m.version if m else 0

    # -- stage 1: unattended train → export → register → ACTIVE --------------
    t0 = time.perf_counter()
    daemon.feed(world.record_rows(cfg.epoch_records + cfg.batch_size))
    pumps1 = _pump_until(daemon, registry, cfg, lambda: active_version() == 1)
    stage1 = {
        "active_version": active_version(),
        "pumps": pumps1,
        "epoch": int(daemon.store.row("global")["epoch"]),
        "candidate_clear": daemon.store.candidate("global") is None,
        "wall_s": round(time.perf_counter() - t0, 4),
    }

    # -- stage 2: injected regression auto-rolls back ------------------------
    invert["invert"] = True
    t0 = time.perf_counter()
    daemon.feed(world.record_rows(cfg.epoch_records + cfg.batch_size))

    def rolled_back() -> bool:
        r = controller.get(sid, name)
        return r is not None and r.phase == "rolled_back"

    pumps2 = _pump_until(daemon, registry, cfg, rolled_back)
    invert["invert"] = False
    row2 = controller.get(sid, name)
    stage2 = {
        "rolled_back": rolled_back(),
        "rollback_reason": row2.reason if row2 else "",
        "active_version": active_version(),  # stage 1's model stays ACTIVE
        "pumps": pumps2,
        "wall_s": round(time.perf_counter() - t0, 4),
    }

    # -- stage 3: bounce mid-promotion, resumed plane finishes the walk ------
    t0 = time.perf_counter()
    daemon.feed(world.record_rows(cfg.epoch_records + cfg.batch_size))
    daemon.step()  # cut the epoch: candidate v3 registered, SHADOW begun
    in_flight = daemon.store.candidate("global")
    pre_bounce_epoch = int(daemon.store.row("global")["epoch"])
    # The bounce: every in-memory object is dropped; only the backend
    # (the replicated state in a real deployment) survives.
    registry2, controller2, daemon2 = _build_plane(cfg, backend, world, invert)

    def active_is_resumed_candidate() -> bool:
        m = registry2.active_model(sid, name)
        return m is not None and in_flight is not None and m.id == in_flight

    pumps3 = _pump_until(
        daemon2, registry2, cfg, active_is_resumed_candidate
    )
    from ..manager import ModelState

    actives = registry2.list(
        scheduler_id=sid, name=name, state=ModelState.ACTIVE
    )
    stage3 = {
        "had_in_flight": in_flight is not None,
        "resumed_watermark": int(daemon2.store.row("global")["watermark"]),
        "resumed_epoch": int(daemon2.store.row("global")["epoch"]),
        "pre_bounce_epoch": pre_bounce_epoch,
        "promoted_resumed_candidate": active_is_resumed_candidate(),
        "active_count": len(actives),
        "artifact_ok": bool(
            actives and registry2.load_artifact(actives[0]) is not None
        ),
        "pumps": pumps3,
        "wall_s": round(time.perf_counter() - t0, 4),
    }

    history: List[dict] = list(daemon2.store.row("global")["history"])
    return {
        "config": {
            "seed": cfg.seed,
            "epoch_records": cfg.epoch_records,
            "announces": cfg.announces,
            "parents": cfg.parents,
        },
        "stage1": stage1,
        "stage2": stage2,
        "stage3": stage3,
        "events": [h["event"] for h in history],
        "ok": bool(
            stage1["active_version"] == 1
            and stage2["rolled_back"]
            and stage2["active_version"] == 1
            and stage3["promoted_resumed_candidate"]
            and stage3["active_count"] == 1
            and stage3["artifact_ok"]
        ),
    }
