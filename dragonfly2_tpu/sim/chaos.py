"""Chaos drill harness: scenario schedules + process-kill orchestration.

The reference proves compatibility and resilience with e2e drills
(test/e2e inside kind, Makefile:358-366) rather than policy text.  This
module is the equivalent for FAILURE: it packages the deterministic
fault-injection layer (utils/faultinject.py) into replayable scenarios
and gives tests the process plumbing to SIGKILL real service binaries
at controlled points.

Two ways to kill a process:

- ``ChaosProcess.sigkill()`` — the external kill, for "the box died"
  drills where the victim's position in its work doesn't matter;
- a ``crash`` FaultSpec in the scenario handed to the child via
  ``DF_FAULTINJECT`` — the child SIGKILLs ITSELF at an exact call index
  of an exact seam (e.g. ``trainer.dispatch`` #3), which makes
  "mid-upload"/"mid-ingest" deterministic instead of a sleep race.

Every drill's end state is digest-checked (``sha256_hex`` /
``task_digest``): surviving a fault with corrupt bytes is a FAILED
drill, whatever the status codes said.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.faultinject import ENV_VAR, FaultInjector, FaultSpec


@dataclass
class ChaosScenario:
    """A named, seeded fault schedule — the replayable unit of chaos.

    ``injector()`` builds the in-process executor; ``env()`` serializes
    the schedule for a child process (installed by every CLI binary at
    boot via ``faultinject.install_from_env``).
    """

    seed: int = 0
    faults: List[FaultSpec] = field(default_factory=list)
    name: str = ""

    def injector(self, **kwargs) -> FaultInjector:
        return FaultInjector(list(self.faults), seed=self.seed, **kwargs)

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "name": self.name,
            "faults": [f.to_dict() for f in self.faults],
        })

    @classmethod
    def from_json(cls, data: str) -> "ChaosScenario":
        d = json.loads(data)
        return cls(
            seed=int(d.get("seed", 0)),
            name=d.get("name", ""),
            faults=[FaultSpec.from_dict(f) for f in d.get("faults", [])],
        )

    def env(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        out = dict(base if base is not None else os.environ)
        out[ENV_VAR] = self.to_json()
        return out


def drop_storm(
    seed: int, site: str = "rpc.client.*", probability: float = 0.2,
    **spec_kw,
) -> ChaosScenario:
    """Seed-derived random drops on a site family — the background-noise
    scenario soak runs layer under a workload."""
    return ChaosScenario(
        seed=seed, name=f"drop-storm:{site}",
        faults=[FaultSpec(site=site, kind="drop", probability=probability,
                          **spec_kw)],
    )


def crash_at(site: str, index: int, *, seed: int = 0) -> ChaosScenario:
    """SIGKILL the process at call `index` of `site` — the deterministic
    mid-flight kill used by the subprocess drills."""
    return ChaosScenario(
        seed=seed, name=f"crash:{site}#{index}",
        faults=[FaultSpec(site=site, kind="crash", at=(index,))],
    )


def replay_history(scenario: ChaosScenario, drive) -> List[tuple]:
    """Run ``drive(injector)`` under a fresh injector and return the
    injection history keys — calling this twice with the same scenario
    and the same drive MUST yield identical histories (the determinism
    contract tests assert)."""
    from ..utils import faultinject

    inj = scenario.injector()
    with faultinject.installed(inj):
        drive(inj)
    return inj.history_keys()


# ---------------------------------------------------------------------------
# Digest verification
# ---------------------------------------------------------------------------


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def task_digest(storage, task_id: str) -> str:
    """End-to-end digest of a completed task's assembled bytes (piece
    reads go through the store's crc verification)."""
    return sha256_hex(storage.read_task_bytes(task_id))


# ---------------------------------------------------------------------------
# Process orchestration
# ---------------------------------------------------------------------------


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ChaosProcess:
    """A service binary under drill control: spawn with an optional fault
    scenario in its environment, wait for ready lines on stdout, SIGKILL
    or await its (self-inflicted) death.

    ``ready_prefixes``: stdout line prefixes that must all appear before
    ``wait_ready`` returns; matched lines are kept (ports ride in them).
    """

    def __init__(
        self,
        argv: Sequence[str],
        *,
        scenario: Optional[ChaosScenario] = None,
        ready_prefixes: Sequence[str] = (),
        env: Optional[Dict[str, str]] = None,
        python: bool = True,
    ) -> None:
        self.argv = ([sys.executable, *argv] if python else list(argv))
        self.scenario = scenario
        self.ready_prefixes = tuple(ready_prefixes)
        base = dict(env if env is not None else os.environ)
        base.setdefault("PYTHONPATH", os.getcwd())
        base.setdefault("JAX_PLATFORMS", "cpu")
        self.env = scenario.env(base) if scenario is not None else base
        self.proc: Optional[subprocess.Popen] = None
        self.lines: List[str] = []
        self.ready_lines: Dict[str, str] = {}
        self._ready = threading.Event()
        self._pump: Optional[threading.Thread] = None

    def start(self) -> "ChaosProcess":
        self.proc = subprocess.Popen(
            self.argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=self.env,
        )

        def pump() -> None:
            for line in self.proc.stdout:
                line = line.rstrip("\n")
                self.lines.append(line)
                for p in self.ready_prefixes:
                    if line.startswith(p):
                        self.ready_lines.setdefault(p, line)
                if len(self.ready_lines) == len(self.ready_prefixes):
                    self._ready.set()

        self._pump = threading.Thread(target=pump, daemon=True)
        self._pump.start()
        if not self.ready_prefixes:
            self._ready.set()
        return self

    def wait_ready(self, timeout: float = 60.0) -> Dict[str, str]:
        if not self._ready.wait(timeout):
            raise AssertionError(
                f"{self.argv}: never ready; last output: {self.lines[-12:]}"
            )
        return dict(self.ready_lines)

    def sigkill(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def wait_dead(self, timeout: float = 60.0) -> int:
        """Await a self-inflicted (crash-fault) or natural exit; returns
        the return code (-9 for SIGKILL)."""
        return self.proc.wait(timeout=timeout)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def stop(self) -> None:
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


def wait_until(fn, *, timeout: float = 30.0, interval: float = 0.05, desc=""):
    """Poll ``fn`` until truthy; raises AssertionError on timeout.  The
    drills' convergence helper (wait_for in deploy/e2e_loop.py, minus the
    SystemExit)."""
    deadline = time.monotonic() + timeout
    last: object = None
    while time.monotonic() < deadline:
        try:
            out = fn()
            if out:
                return out
            last = "falsy"
        except Exception as exc:  # noqa: BLE001 — converging system
            last = exc
        time.sleep(interval)
    raise AssertionError(f"chaos: timeout waiting for {desc or fn}: {last!r}")
