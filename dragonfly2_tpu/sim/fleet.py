"""Columnar million-peer swarm population + sharded fleet driver.

``sim/swarm.py`` drives the real scheduler stack peer-by-peer: one
``Peer`` object, one FSM walk, one Python call per piece — honest, and
walled around 1k hosts.  This module rebuilds the peer *population* on
the §18 columnar technique so ONE process can replay 100k–1M peers
against N real ``SchedulerService`` shards (DESIGN.md §24):

- ``ColumnarPopulation`` — synthetic peer state lives in preallocated
  slot columns (state, idc class, latent capacities/loads), and every
  discrete-event tick draws the join/leave/fail/announce event sets as
  vectorized bernoulli masks per idc churn class.  No per-peer Python
  runs until an event actually targets a peer.
- ``ShardedFleet`` — N in-process scheduler shards (each a REAL
  ``SchedulerService`` with its own Resource + columnar host store +
  ``ShardGuard``) behind one ``ShardRing``.  Task-scoped traffic routes
  by ring ownership; host announces pin to the host id's ring owner
  (task registration carries announce-time stats, so task owners never
  need a fan-out).  ``kill()`` removes a member, bumps the ring and
  runs every survivor's handoff sweep — the membership-change protocol
  the chaos drill exercises over the wire.
- ``FleetSwarmDriver`` — applies each tick's event arrays to the fleet
  through the real entry points: ``announce_host`` for joins and
  re-announces, ``register_peer`` → batched ``report_pieces_finished``
  → ``report_peer_finished`` for the download slice, steering
  (``WrongShardError``) followed like a client would.

The measured product (tools/bench_swarm.py) is **aggregate
announces/sec across shards** — the fleet-scale serving signal the
ROADMAP asks for.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..records.synthetic import IDC_NAMES, PIECE_SIZE, REGIONS
from ..scheduler import (
    AdmissionController,
    Evaluator,
    HostFeatureCache,
    Resource,
    ScheduleResultKind,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
    ShardGuard,
    ShardRing,
    ShardSaturatedError,
    WrongShardError,
)
from ..scheduler.resource import Host
from ..utils import idgen
from ..utils.types import HostType

# -- churn classes ------------------------------------------------------------

# (name, population share, join/tick, leave/tick, fail/tick): stable
# datacenter cores, churny edge boxes, and mobile-grade peers that
# appear and vanish.  Rates are per ONLINE (leave/fail) or OFFLINE
# (join) peer per tick.
IDC_CLASSES: Tuple[Tuple[str, float, float, float, float], ...] = (
    ("core",   0.50, 0.60, 0.002, 0.0005),
    ("edge",   0.30, 0.30, 0.020, 0.005),
    ("mobile", 0.20, 0.15, 0.080, 0.020),
)

_OFFLINE = np.uint8(0)
_ONLINE = np.uint8(1)


@dataclass
class FleetConfig:
    num_peers: int = 100_000
    seed: int = 0
    # Fraction of ONLINE peers that re-announce each tick (the keepalive
    # cadence scaled to tick time).
    announce_rate: float = 0.5
    # Fraction of ONLINE peers that start a download each tick.
    download_rate: float = 0.002
    pieces_per_download: int = 4
    task_catalog: int = 64
    candidate_parent_limit: int = 4


@dataclass
class TickEvents:
    """One tick's event sets, as index arrays into the population."""

    tick: int
    joins: np.ndarray
    leaves: np.ndarray
    fails: np.ndarray
    announcers: np.ndarray
    downloaders: np.ndarray

    @property
    def total(self) -> int:
        return (
            len(self.joins) + len(self.leaves) + len(self.fails)
            + len(self.announcers) + len(self.downloaders)
        )


class ColumnarPopulation:
    """Slot-matrix synthetic peer population (§18 technique applied to
    the *simulator*): peer state is struct-of-arrays, tick event sets
    are drawn with whole-array bernoulli masks, and per-peer Python
    (Host materialization) runs only for peers an event touched."""

    def __init__(self, config: Optional[FleetConfig] = None) -> None:
        self.config = config or FleetConfig()
        n = self.config.num_peers
        self.rng = np.random.default_rng(self.config.seed)
        r = self.rng
        shares = np.array([c[1] for c in IDC_CLASSES])
        self.idc_class = r.choice(
            len(IDC_CLASSES), size=n, p=shares / shares.sum()
        ).astype(np.uint8)
        self.state = np.full(n, _OFFLINE, dtype=np.uint8)
        # Latent host attributes, columnar (no LatentHost objects).
        self.idc = r.integers(0, len(IDC_NAMES), n).astype(np.int16)
        self.region = r.integers(0, len(REGIONS), n).astype(np.int8)
        self.zone = r.integers(0, 4, n).astype(np.int8)
        self.up_cap = np.exp(r.normal(math.log(60e6), 0.7, n)).astype(np.float32)
        self.cpu_load = np.clip(r.beta(2, 5, n), 0, 1).astype(np.float32)
        self.mem_load = np.clip(r.beta(2, 4, n), 0, 1).astype(np.float32)
        self.upload_count = r.integers(10, 5000, n).astype(np.int64)
        self.upload_failed = (
            self.upload_count * np.clip(r.beta(1, 12, n), 0, 1)
        ).astype(np.int64)
        # Per-class rate columns, broadcast once.
        joins = np.array([c[2] for c in IDC_CLASSES])
        leaves = np.array([c[3] for c in IDC_CLASSES])
        fails = np.array([c[4] for c in IDC_CLASSES])
        self._join_rate = joins[self.idc_class]
        self._leave_rate = leaves[self.idc_class]
        self._fail_rate = fails[self.idc_class]
        self._hosts: Dict[int, Host] = {}
        self.tick_count = 0

    # -- vectorized event draws ----------------------------------------------

    def tick(self) -> TickEvents:
        """Draw one discrete-event tick: state transitions applied
        columnar, event index arrays returned for the driver."""
        r = self.rng
        n = self.config.num_peers
        u = r.random(n)
        offline = self.state == _OFFLINE
        online = ~offline
        joins = np.flatnonzero(offline & (u < self._join_rate))
        # Independent draw for departures; a peer that joined this tick
        # stays for at least one tick (real daemons outlive one announce).
        v = r.random(n)
        leaves = np.flatnonzero(online & (v < self._leave_rate))
        fails = np.flatnonzero(
            online & (v >= self._leave_rate)
            & (v < self._leave_rate + self._fail_rate)
        )
        w = r.random(n)
        announcers = np.flatnonzero(online & (w < self.config.announce_rate))
        d = r.random(n)
        downloaders = np.flatnonzero(online & (d < self.config.download_rate))
        # Apply transitions columnar.
        self.state[joins] = _ONLINE
        self.state[leaves] = _OFFLINE
        self.state[fails] = _OFFLINE
        self.tick_count += 1
        return TickEvents(
            tick=self.tick_count,
            joins=joins,
            leaves=leaves,
            fails=fails,
            announcers=announcers,
            downloaders=downloaders,
        )

    def online_count(self) -> int:
        return int((self.state == _ONLINE).sum())

    # -- lazy Host materialization -------------------------------------------

    def host(self, i: int) -> Host:
        """The peer's scheduler Host view, built once on first touch —
        1M cold slots cost nothing until an event reaches one."""
        h = self._hosts.get(i)
        if h is None:
            h = Host(
                id=f"fleet-host-{i}",
                hostname=f"fleet-{i}",
                ip=f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}",
                port=8002,
                download_port=8001,
                type=HostType.NORMAL,
                concurrent_upload_limit=50,
            )
            h.stats.network.idc = IDC_NAMES[self.idc[i]]
            h.stats.network.location = (
                f"{REGIONS[self.region[i]]}|zone-{self.zone[i]}"
                f"|rack-{i % 8}"
            )
            h.upload_count = int(self.upload_count[i])
            h.upload_failed_count = int(self.upload_failed[i])
            self._hosts[i] = h
        # Announce-time stats refresh from the latent columns (cheap
        # scalar reads; the service's adopt/touch does the column write).
        h.stats.cpu.percent = float(self.cpu_load[i]) * 100.0
        h.stats.memory.used_percent = float(self.mem_load[i]) * 100.0
        return h

    def forget(self, i: int) -> None:
        """Drop a departed peer's Host view (its next join rebuilds)."""
        self._hosts.pop(i, None)


# -- the sharded fleet --------------------------------------------------------


@dataclass
class _Shard:
    shard_id: str
    service: SchedulerService
    guard: ShardGuard
    cache: HostFeatureCache
    announces: int = 0
    registers: int = 0
    redirects_followed: int = 0


class ShardedFleet:
    """N real in-process scheduler shards behind one ShardRing."""

    def __init__(
        self,
        n_shards: int,
        *,
        feature_cache_hosts: int = 65536,
        candidate_parent_limit: int = 4,
        admission: bool = False,
        storage=None,
    ) -> None:
        self._feature_cache_hosts = feature_cache_hosts
        self._candidate_parent_limit = candidate_parent_limit
        self._admission = admission
        self._storage = storage
        self.shards: Dict[str, _Shard] = {}
        members: Dict[str, str] = {}
        for i in range(n_shards):
            sid = f"shard-{i}"
            self.shards[sid] = self._make_shard(sid)
            members[sid] = f"inproc://{sid}"
        self.ring = ShardRing(members, version=1)
        for shard in self.shards.values():
            shard.guard.update_ring(self.ring)

    def _make_shard(self, sid: str) -> _Shard:
        cache = HostFeatureCache(max_hosts=self._feature_cache_hosts)
        ctl = AdmissionController() if self._admission else None
        guard = ShardGuard(sid, admission=ctl)
        service = SchedulerService(
            Resource(),
            Scheduling(
                Evaluator(feature_cache=cache),
                SchedulingConfig(
                    retry_interval=0,
                    candidate_parent_limit=self._candidate_parent_limit,
                ),
            ),
            self._storage,
            None,
            shard_guard=guard,
        )
        return _Shard(sid, service, guard, cache)

    # -- routing -------------------------------------------------------------

    def owner_of(self, key: str) -> _Shard:
        sid = self.ring.owner(key)
        if sid is None:
            raise LookupError("fleet has no live shards")
        return self.shards[sid]

    def live(self) -> List[_Shard]:
        return [self.shards[sid] for sid in self.ring.members()]

    # -- membership change ---------------------------------------------------

    def kill(self, shard_id: str) -> Dict[str, int]:
        """Remove a member: bump the ring, push it to every survivor
        (their guards run the handoff sweep).  Returns per-survivor
        handed-off task counts — the migration evidence."""
        dead = self.shards.pop(shard_id, None)
        if dead is None:
            raise KeyError(shard_id)
        members = self.ring.members()
        members.pop(shard_id, None)
        self.ring = ShardRing(
            members, replicas=self.ring.replicas,
            version=self.ring.version + 1,
        )
        moved: Dict[str, int] = {}
        for shard in self.shards.values():
            moved[shard.shard_id] = len(shard.guard.update_ring(self.ring))
        return moved

    def add_shard(self, shard_id: Optional[str] = None) -> Dict[str, int]:
        """Scale-out: a new member joins, the ring bumps, and every
        EXISTING shard's handoff sweep marks the tasks the newcomer now
        owns — their peers get steered there on their next call (the
        consistent-hash add moves only ≈K/(N+1) keys, all TO the
        newcomer; the property tests pin the bound).  Returns
        per-survivor handed-off task counts."""
        sid = shard_id or f"shard-{len(self.shards)}-r{self.ring.version}"
        if sid in self.shards:
            raise KeyError(f"shard {sid} already exists")
        members = self.ring.members()
        members[sid] = f"inproc://{sid}"
        self.ring = ShardRing(
            members, replicas=self.ring.replicas,
            version=self.ring.version + 1,
        )
        moved: Dict[str, int] = {}
        for shard in self.shards.values():
            moved[shard.shard_id] = len(shard.guard.update_ring(self.ring))
        newcomer = self._make_shard(sid)
        self.shards[sid] = newcomer
        newcomer.guard.update_ring(self.ring)
        return moved

    # -- aggregate stats -----------------------------------------------------

    def stats(self) -> Dict[str, object]:
        per = {
            s.shard_id: {
                "announces": s.announces,
                "registers": s.registers,
                "hosts": len(s.service.resource.host_manager),
                "tasks": len(s.service.resource.task_manager),
                "cache_hits": s.cache.hits,
                "cache_misses": s.cache.misses,
            }
            for s in self.shards.values()
        }
        hits = sum(p["cache_hits"] for p in per.values())
        misses = sum(p["cache_misses"] for p in per.values())
        return {
            "shards": per,
            "announces": sum(p["announces"] for p in per.values()),
            "registers": sum(p["registers"] for p in per.values()),
            "cache_hit_rate": hits / max(1, hits + misses),
        }


class FleetSwarmDriver:
    """Applies population ticks to the fleet through the real service
    entry points, following steering answers like a wire client."""

    def __init__(
        self,
        population: ColumnarPopulation,
        fleet: ShardedFleet,
    ) -> None:
        self.population = population
        self.fleet = fleet
        # The driver routes through its OWN ring snapshot, like a wire
        # client between dynconfig polls: a membership change leaves it
        # stale until a dead member or a steering answer forces the
        # refresh — so the REDIRECT protocol is exercised by the sim,
        # not bypassed by omniscience.
        self._ring = fleet.ring
        cfg = population.config
        self._urls = [
            f"https://origin.example.com/fleet-blob/{t}"
            for t in range(cfg.task_catalog)
        ]
        self._task_ids = [idgen.task_id(u) for u in self._urls]
        self.downloads_ok = 0
        self.downloads_failed = 0
        self.sheds = 0
        self.announce_seconds = 0.0
        self.rehomed_tasks = 0

    # -- client-side routing (stale-ring semantics) ---------------------------

    def _route(self, key: str) -> _Shard:
        """Route via the driver's ring snapshot; a dead member (the
        connection-refused analog) triggers the snapshot refresh and one
        re-route — the client half of kill-migration."""
        sid = self._ring.owner(key)
        shard = self.fleet.shards.get(sid) if sid is not None else None
        if shard is None:
            self._ring = self.fleet.ring
            sid = self._ring.owner(key)
            shard = self.fleet.shards.get(sid) if sid is not None else None
            if shard is None:
                raise LookupError("fleet has no live shards")
        return shard

    # -- per-event application ----------------------------------------------

    def _announce(self, i: int) -> None:
        host = self.population.host(i)
        shard = self._route(host.id)
        t0 = time.perf_counter()
        try:
            shard.service.announce_host(host)
        except ShardSaturatedError:
            self.sheds += 1
            return
        finally:
            self.announce_seconds += time.perf_counter() - t0
        shard.announces += 1

    def _download(self, i: int) -> None:
        """One synthetic download through the task's ring owner: register
        → batched piece reports → finished.  Wrong-shard steering is
        followed once, like the wire router."""
        pop = self.population
        cfg = pop.config
        t = int(pop.rng.integers(0, len(self._urls)))
        url, tid = self._urls[t], self._task_ids[t]
        host = pop.host(i)
        shard = self._route(tid)
        try:
            try:
                result = shard.service.register_peer(
                    host=host, url=url, task_id=tid
                )
            except WrongShardError as exc:
                # Stale routing (ring moved): follow the steering answer
                # and adopt the fresher ring it implies.
                owner = self.fleet.shards.get(exc.owner_id)
                self._ring = self.fleet.ring
                if owner is None:
                    self.downloads_failed += 1
                    return
                shard = owner
                shard.redirects_followed += 1
                result = shard.service.register_peer(
                    host=host, url=url, task_id=tid
                )
        except ShardSaturatedError:
            self.sheds += 1
            return
        shard.registers += 1
        peer = result.peer
        task = peer.task
        if task.content_length < 0:
            task.content_length = cfg.pieces_per_download * PIECE_SIZE
            task.total_piece_count = cfg.pieces_per_download
            task.piece_size = PIECE_SIZE
        schedule = result.schedule
        parents = (
            schedule.parents
            if schedule is not None
            and schedule.kind is ScheduleResultKind.PARENTS
            else []
        )
        bw = max(float(pop.up_cap[i]), 1e3)
        pieces = [
            {
                "number": n,
                "parent_id": parents[n % len(parents)].id if parents else "",
                "length": PIECE_SIZE,
                "cost_ns": int(PIECE_SIZE / bw * 1e9),
            }
            for n in range(task.total_piece_count)
        ]
        try:
            shard.service.report_pieces_finished(peer, pieces)
            shard.service.report_peer_finished(peer)
        except WrongShardError:
            # Task handed off mid-download: the client re-registers on
            # the new owner and the download restarts there.
            new_owner = self.fleet.owner_of(tid)
            self.rehomed_tasks += 1
            try:
                res2 = new_owner.service.register_peer(
                    host=host, url=url, task_id=tid
                )
                new_owner.registers += 1
                p2 = res2.peer
                if p2.task.content_length < 0:
                    p2.task.content_length = task.content_length
                    p2.task.total_piece_count = task.total_piece_count
                    p2.task.piece_size = task.piece_size
                new_owner.service.report_pieces_finished(p2, pieces)
                new_owner.service.report_peer_finished(p2)
            except (WrongShardError, ShardSaturatedError):
                self.downloads_failed += 1
                return
        self.downloads_ok += 1

    # -- tick application ----------------------------------------------------

    def apply(self, events: TickEvents) -> None:
        pop = self.population
        for i in events.joins:
            self._announce(int(i))
        for i in events.announcers:
            self._announce(int(i))
        for i in events.downloaders:
            self._download(int(i))
        for i in events.leaves:
            host = pop._hosts.get(int(i))
            if host is not None:
                try:
                    self._route(host.id).service.leave_host(host)
                except LookupError:
                    pass
            pop.forget(int(i))
        # Fails: the box died — no leave reaches the scheduler; the
        # host ages out of the TTL GC exactly like a real power loss.
        for i in events.fails:
            pop.forget(int(i))

    def run(self, ticks: int) -> Dict[str, object]:
        """Drive ``ticks`` ticks; returns the aggregate workload report
        (the bench's measured unit)."""
        t0 = time.perf_counter()
        totals = {"joins": 0, "leaves": 0, "fails": 0, "announces": 0,
                  "downloads": 0}
        for _ in range(ticks):
            ev = self.population.tick()
            totals["joins"] += len(ev.joins)
            totals["leaves"] += len(ev.leaves)
            totals["fails"] += len(ev.fails)
            totals["announces"] += len(ev.joins) + len(ev.announcers)
            totals["downloads"] += len(ev.downloaders)
            self.apply(ev)
        wall = time.perf_counter() - t0
        stats = self.fleet.stats()
        announces = int(stats["announces"])
        return {
            **totals,
            "wall_s": wall,
            "announce_wall_s": self.announce_seconds,
            "announces_served": announces,
            "announces_per_sec": (
                announces / self.announce_seconds
                if self.announce_seconds > 0 else 0.0
            ),
            "downloads_ok": self.downloads_ok,
            "downloads_failed": self.downloads_failed,
            "sheds": self.sheds,
            "rehomed_tasks": self.rehomed_tasks,
            "online": self.population.online_count(),
            "unique_hosts": sum(
                s["hosts"] for s in stats["shards"].values()  # type: ignore[index]
            ),
            "cache_hit_rate": stats["cache_hit_rate"],
            "shards": stats["shards"],
        }


# -- seed-sweep reproducibility (DESIGN.md §27) ------------------------------

# Keys of the run report that measure WALL TIME rather than simulated
# behavior.  Everything else is a pure function of (FleetConfig, ticks):
# the population draws from a seeded numpy Generator and the fleet is
# driven synchronously, so two runs with the same seed — even under
# different PYTHONHASHSEED values — must agree byte-for-byte on the
# projection below (tests/test_sim_determinism.py gates this in
# subprocesses).
TIMING_KEYS = ("wall_s", "announce_wall_s", "announces_per_sec")


def deterministic_summary(report: Dict[str, object]) -> Dict[str, object]:
    """The seed-reproducible core of ``FleetSwarmDriver.run``'s report:
    the full report minus the wall-clock measurements."""
    return {k: v for k, v in report.items() if k not in TIMING_KEYS}
