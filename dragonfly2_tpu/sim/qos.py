"""Multi-tenant QoS overload drill (DESIGN.md §26).

The headline question the ROADMAP asks: **does a 10× burst from tenant
B move tenant A's announce p99 and download TTLB?**  This module builds
the smallest REAL composition that can answer it on one box:

- one ``SchedulerService`` (columnar host store + rule evaluator)
  behind a ``ShardGuard`` + ``AdmissionController``;
- one seed ``Daemon`` holding every task's pieces (its UploadManager is
  the upload-path chokepoint);
- a tenant-A client daemon running REAL downloads (register → parents →
  piece fetch off the seed → batched reports) plus a measured announce
  loop;
- tenant-B flood threads driving announces and piece pulls flat-out.

Arms differ in ONE thing — whether the QoS plane is installed:

- ``shaped``   — the tenant_qos policy is live: B runs at the
  background class with an announce-rate cap and an upload-bandwidth
  cap; admission carries ``TenantAccounting`` so B's over-quota flood
  sheds first, and refusals carry Retry-After which B's drive loop
  HONORS (sleep-backoff — the real client protocol; shedding works
  because refusals are cheap AND pace the flood);
- ``unshaped`` — same traffic, tenant-blind admission, no caps: B's
  requests all pay full per-request cost and A contends head-on.

Per arm the drill reports tenant A's announce p50/p99 and download
TTLB, B's offered/shed/capped counts, and the seed's per-tenant byte
accounting.  ``run_isolation_drill`` runs baseline (A alone) + burst
arms and computes the MOVEMENT of A's metrics under burst — the <10%
shaped bar is tools/bench_qos.py's regression-guarded headline.
"""

from __future__ import annotations

import shutil
import tempfile
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..daemon.daemon import Daemon
from ..daemon.upload import UploadBusy
from ..qos import QoSPolicy, TenantAccounting
from ..scheduler import (
    AdmissionController,
    Evaluator,
    HostFeatureCache,
    Resource,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
    ShardGuard,
    ShardSaturatedError,
)
from ..scheduler.resource import Host
from ..utils.types import Priority

TENANT_A = "t-a"
TENANT_B = "t-b"


@dataclass
class QoSDrillConfig:
    a_announces: int = 1000        # measured tenant-A announce loop
    a_downloads: int = 6          # real downloads measured for TTLB
    pieces_per_task: int = 8
    piece_size: int = 64 * 1024
    b_threads: int = 2            # tenant-B flood threads
    burst_multiplier: int = 10    # offered B:A announce ratio target
    b_announce_qps: float = 50.0    # shaped: B's announce cap
    b_upload_rate: float = 1e6      # shaped: B-task upload cap (bytes/s)
    b_backoff_s: float = 0.15     # B's Retry-After honor cap (the drill
                                  # clamps the server's 1 s so arms finish)
    max_inflight: int = 256
    p99_budget_ms: float = 20.0
    seed: int = 7

    def policy(self) -> QoSPolicy:
        return QoSPolicy.from_payload({
            TENANT_A: {
                "tenant_class": "gold", "weight": 4.0, "priority": 0,
            },
            TENANT_B: {
                "tenant_class": "background", "weight": 1.0, "priority": 6,
                "announce_qps": self.b_announce_qps,
                "announce_burst": max(int(self.b_announce_qps / 4), 1),
                "upload_rate_bytes_s": self.b_upload_rate,
            },
        })


def _host(name: str, i: int) -> Host:
    h = Host(
        id=f"{name}-{i}", hostname=f"{name}-{i}",
        ip=f"10.9.{i >> 8 & 255}.{i & 255}", port=8002, download_port=8001,
        concurrent_upload_limit=64,
    )
    h.stats.network.idc = "idc-qos"
    return h


class _Origin:
    """Deterministic piece-addressable origin content."""

    def __init__(self, piece_size: int) -> None:
        self.piece_size = piece_size

    def fetch(self, url: str, number: int, piece_size: int) -> bytes:
        # crc32, not builtin hash(): str hashing is salted by
        # PYTHONHASHSEED, which made "deterministic" origin content
        # differ between processes (DESIGN.md §27 seed-sweep gate).
        seed = (zlib.crc32(url.encode()) ^ number) & 0xFF
        return bytes((seed + i) % 256 for i in range(self.piece_size))


@dataclass
class _ArmState:
    service: SchedulerService
    admission: AdmissionController
    seed: Daemon
    client_a: Daemon
    registry: Dict[str, Daemon]
    workdir: str
    a_urls: List[str] = field(default_factory=list)
    b_urls: List[str] = field(default_factory=list)
    warm_url: str = "https://origin.qos/warm"


def _build(cfg: QoSDrillConfig, *, shaped: bool, workdir: str) -> _ArmState:
    policy = cfg.policy() if shaped else None
    accounting = TenantAccounting(policy) if shaped else None
    admission = AdmissionController(
        max_inflight=cfg.max_inflight,
        p99_budget_s=cfg.p99_budget_ms / 1e3,
        accounting=accounting,
    )
    guard = ShardGuard("qos-shard", admission=admission)
    cache = HostFeatureCache(max_hosts=4096)
    service = SchedulerService(
        Resource(),
        Scheduling(
            Evaluator(feature_cache=cache), SchedulingConfig(retry_interval=0)
        ),
        None,
        None,
        shard_guard=guard,
    )
    if shaped:
        service.set_qos_policy(policy)
    registry: Dict[str, Daemon] = {}
    origin = _Origin(cfg.piece_size)
    seed = Daemon(
        _host("qos-seed", 0), service, storage_root=f"{workdir}/seed",
        daemon_registry=registry, source_fetcher=origin,
    )
    if shaped:
        seed.set_qos_policy(policy)
    client_a = Daemon(
        _host("qos-a", 0), service, storage_root=f"{workdir}/a",
        daemon_registry=registry, tenant=TENANT_A,
    )
    state = _ArmState(
        service=service, admission=admission, seed=seed, client_a=client_a,
        registry=registry, workdir=workdir,
    )
    # Seed every task's content ahead of the measured window; stamp task
    # ownership on the seed's upload gate (production: the requesting
    # tenant's job/daemon stamps it) so serves account — and, shaped,
    # throttle — against the OWNING tenant.
    content = cfg.pieces_per_task * cfg.piece_size
    for i in range(cfg.a_downloads):
        url = f"https://origin.qos/a-{i}"
        state.a_urls.append(url)
    for i in range(max(2, cfg.a_downloads)):
        url = f"https://origin.qos/b-{i}"
        state.b_urls.append(url)
    from ..utils import idgen

    for url in state.a_urls + state.b_urls + [state.warm_url]:
        r = seed.download(
            url, piece_size=cfg.piece_size, content_length=content
        )
        if not r.ok:
            raise RuntimeError(f"seeding {url} failed")
    for url in state.a_urls:
        seed.upload.register_task_tenant(idgen.task_id(url), TENANT_A)
    for url in state.b_urls:
        seed.upload.register_task_tenant(idgen.task_id(url), TENANT_B)
    return state


def _percentile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, int(q * (len(s) - 1) + 0.5))
    return s[idx]


def _run_arm(
    cfg: QoSDrillConfig, *, shaped: bool, burst: bool
) -> Dict[str, object]:
    """One arm: measured tenant-A workload, optional tenant-B flood."""
    workdir = tempfile.mkdtemp(prefix="qos-drill-")
    try:
        state = _build(cfg, shaped=shaped, workdir=workdir)
        service, seed = state.service, state.seed
        from ..utils import idgen

        stop = threading.Event()
        b_stats = {"announces": 0, "sheds": 0, "pulls": 0, "throttled": 0}
        b_mu = threading.Lock()

        def b_flood(tid: int) -> None:
            """Tenant-B flood: announces + piece pulls every iteration,
            honoring Retry-After/backoff when BOTH are refused (the real
            client protocol — shedding protects tenant A because
            refusals are cheap AND pace the flood)."""
            hosts = [
                _host("qos-b", tid * 64 + i) for i in range(8)
            ]
            b_task = idgen.task_id(state.b_urls[tid % len(state.b_urls)])
            i = 0
            while not stop.is_set():
                i += 1
                refused = 0
                retry_after = cfg.b_backoff_s
                try:
                    service.announce_host(
                        hosts[i % len(hosts)], tenant=TENANT_B
                    )
                    with b_mu:
                        b_stats["announces"] += 1
                except ShardSaturatedError as exc:
                    refused += 1
                    retry_after = min(exc.retry_after_s, cfg.b_backoff_s)
                    with b_mu:
                        b_stats["sheds"] += 1
                try:
                    seed.upload.serve_piece(
                        b_task, i % cfg.pieces_per_task
                    )
                    with b_mu:
                        b_stats["pulls"] += 1
                except UploadBusy:
                    refused += 1
                    with b_mu:
                        b_stats["throttled"] += 1
                if refused == 2:
                    stop.wait(retry_after)

        threads = [
            threading.Thread(target=b_flood, args=(t,), daemon=True)
            for t in range(cfg.b_threads)
        ]
        if burst:
            for t in threads:
                t.start()

        # Measured tenant-A workload: the announce loop + real downloads.
        host_a = state.client_a.host
        announce_walls: List[float] = []
        a_sheds = 0
        download_walls: List[float] = []
        dl_every = max(1, cfg.a_announces // max(cfg.a_downloads, 1))
        content = cfg.pieces_per_task * cfg.piece_size
        # Unmeasured warmup: cold-path costs (first announce's column
        # bind, conductor thread spin-up) land outside the percentiles.
        for _ in range(min(50, cfg.a_announces // 4)):
            try:
                service.announce_host(host_a, tenant=TENANT_A)
            except ShardSaturatedError:
                pass
        state.client_a.download(
            state.warm_url, piece_size=cfg.piece_size, content_length=content,
            priority=Priority.LEVEL0,
        )
        for i in range(cfg.a_announces):
            t0 = time.perf_counter()
            try:
                service.announce_host(host_a, tenant=TENANT_A)
            except ShardSaturatedError:
                a_sheds += 1
            announce_walls.append(time.perf_counter() - t0)
            if i % dl_every == 0 and len(download_walls) < cfg.a_downloads:
                url = state.a_urls[len(download_walls)]
                t0 = time.perf_counter()
                r = state.client_a.download(
                    url, piece_size=cfg.piece_size, content_length=content,
                    priority=Priority.LEVEL0,
                )
                wall = time.perf_counter() - t0
                if r.ok:
                    download_walls.append(wall)
        stop.set()
        for t in threads:
            while t.is_alive():
                t.join(5.0)

        acct = state.admission.accounting
        out: Dict[str, object] = {
            "shaped": shaped,
            "burst": burst,
            "a_announce_p50_ms": round(
                _percentile(announce_walls, 0.50) * 1e3, 4
            ),
            "a_announce_p99_ms": round(
                _percentile(announce_walls, 0.99) * 1e3, 4
            ),
            "a_announces": len(announce_walls),
            "a_sheds": a_sheds,
            "a_downloads_ok": len(download_walls),
            # Median TTLB: robust to the conductor's piece-poll hiccups
            # (a single 50 ms poll sleep would wreck a small-N mean).
            "a_ttlb_ms": round(_percentile(download_walls, 0.50) * 1e3, 3),
            "a_ttlb_p90_ms": round(_percentile(download_walls, 0.90) * 1e3, 3),
            "b_offered": b_stats["announces"] + b_stats["sheds"],
            "b_announces": b_stats["announces"],
            "b_sheds": b_stats["sheds"],
            "b_pulls": b_stats["pulls"],
            "b_throttled": b_stats["throttled"],
            "seed_tenant_bytes": dict(seed.upload.tenant_bytes),
            "seed_throttled": seed.upload.throttled_count,
            "tenant_accounting": acct.snapshot() if acct is not None else {},
        }
        return out
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def run_isolation_drill(
    cfg: Optional[QoSDrillConfig] = None,
) -> Dict[str, object]:
    """baseline (A alone) → unshaped burst → shaped burst; movements of
    A's announce p99 and TTLB vs baseline per arm.  The shaped bar the
    bench guards: both movements < 10%."""
    cfg = cfg or QoSDrillConfig()
    baseline = _run_arm(cfg, shaped=False, burst=False)
    unshaped = _run_arm(cfg, shaped=False, burst=True)
    shaped = _run_arm(cfg, shaped=True, burst=True)

    def movement(arm: Dict[str, object], key: str) -> float:
        base = float(baseline[key]) or 1e-9
        return round((float(arm[key]) - base) / base * 100.0, 2)

    return {
        "config": {
            "a_announces": cfg.a_announces,
            "a_downloads": cfg.a_downloads,
            "pieces_per_task": cfg.pieces_per_task,
            "piece_size": cfg.piece_size,
            "b_threads": cfg.b_threads,
            "burst_multiplier": cfg.burst_multiplier,
            "seed": cfg.seed,
        },
        "baseline": baseline,
        "unshaped": unshaped,
        "shaped": shaped,
        "movement": {
            "unshaped_announce_p99_pct": movement(
                unshaped, "a_announce_p99_ms"
            ),
            "unshaped_ttlb_pct": movement(unshaped, "a_ttlb_ms"),
            "shaped_announce_p99_pct": movement(shaped, "a_announce_p99_ms"),
            "shaped_ttlb_pct": movement(shaped, "a_ttlb_ms"),
        },
    }


# -- seed-sweep reproducibility (DESIGN.md §27) ------------------------------

# Arm-report keys that COUNT simulated behavior rather than measure wall
# time.  Latency percentiles, TTLB and byte-rate movements are honest
# wall measurements and legitimately vary run to run; the counts below
# are a pure function of the drill script once the origin content is
# hash-seed-independent (the crc32 fix in ``_Origin.fetch``), so a
# baseline arm replayed under a different PYTHONHASHSEED must agree
# byte-for-byte (tests/test_sim_determinism.py gates this in
# subprocesses).
COUNT_KEYS = (
    "shaped", "burst", "a_announces", "a_sheds", "a_downloads_ok",
    "b_offered", "b_announces", "b_pulls", "seed_tenant_bytes",
)


def deterministic_summary(arm_report: Dict[str, object]) -> Dict[str, object]:
    """The seed-reproducible core of one ``_run_arm`` report."""
    return {k: arm_report[k] for k in COUNT_KEYS if k in arm_report}
