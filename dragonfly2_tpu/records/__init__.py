"""Training-record layer: schemas, storage, columnar TPU ingest format, synthesis.

Mirrors the reference's scheduler/storage (record schemas + rotating files,
scheduler/storage/types.go, storage.go) but replaces the CSV bottleneck with
a fixed-width columnar binary format that feeds the TPU input pipeline
directly (SURVEY.md §2.1 rebuild target for scheduler/storage).
"""

from .schema import (  # noqa: F401
    Download,
    DownloadError,
    HostRecord,
    NetworkTopologyRecord,
    Parent,
    Piece,
    ProbeStats,
    TaskRecord,
)
