"""Native ABI contracts: declared once, checked twice (DESIGN.md §30).

The native data plane crosses a C ABI: `native/src/native.cpp` exports
~43 `extern "C"` symbols that the hand-maintained ctypes table in
`native/__init__.py` binds, plus packed records (the 24-byte FetchDone
completion, the piece-store metadata/header layouts) and shared
constants (batch caps, status codes, wire magics) that BOTH sides
restate.  Drift on either side compiles clean and corrupts memory at
runtime — a widened parameter, a reordered field, a constant changed on
one side.  This registry is the single declaration of that boundary:

- ``tools/dflint/checkers/df020_abi.py`` reads it with
  ``ast.literal_eval`` (never imported — dflint stays stdlib-only) and
  enforces **DF020**: a declaration extractor over native.cpp's
  ``extern "C"`` blocks / ``constexpr`` constants / ``pack(1)`` structs
  and an AST pass over the ctypes bindings are BOTH cross-checked
  against this registry, so drift in either direction fails tier-1 by
  symbol/field/constant name (exported-but-unbound, bound-but-
  unexported, and stale registry entries all fail too); and **DF021**:
  every ``extern "C"`` function body and every ``std::thread`` entry
  carries a top-level catch-all (an escaping exception would
  ``std::terminate`` the embedding daemon).
- ``dragonfly2_tpu/utils/dfabi.py`` imports it at runtime (the witness
  side): the compiled library's ``df_abi_manifest()`` export emits a
  self-description generated from an X-macro table inside native.cpp —
  prototype strings, compiler-computed ``sizeof``/``offsetof`` of every
  declared record, constant values — and ``tests/test_zz_abiwitness.py``
  requires it to byte-match the canonical JSON rendered from this
  registry, so a compiler/padding surprise fails even when both source
  texts agree.

Canonical type vocabulary (shared by this registry, the C++ alias table
inside native.cpp's manifest section, and both extractor sides; `const`
is dropped on both sides before comparison):

    void  i32(int/int32_t)  i64  u16  u32  f64(double)  cstr(char*)
    u8p  f32p  i32p  i64p  f64p

Keep ``ABI_CONTRACTS`` a PURE LITERAL: one dict, no computed entries.
DF020 emits a finding if ``ast.literal_eval`` stops working on it.  The
accessor helpers below the dict exist for runtime consumers (the ctypes
bindings derive their struct formats and shared constants from here
instead of restating literals — the dedup DF020 pins).
"""

from __future__ import annotations

ABI_CONTRACTS = {
    # -- library geography ---------------------------------------------------
    "library": {
        "source": "dragonfly2_tpu/native/src/native.cpp",
        "bindings": "dragonfly2_tpu/native/__init__.py",
    },
    # -- exported symbols ----------------------------------------------------
    # symbol -> [return, *params] in the canonical type vocabulary.  The
    # C side must define exactly these prototypes inside `extern "C"`
    # blocks; the ctypes side must declare exactly these restype/argtypes.
    "exports": {
        # record engine (DFC1 columnar append)
        "re_open": ["i64", "cstr", "cstr", "u32"],
        "re_append": ["i64", "i64", "f32p", "i64"],
        "re_flush": ["i32", "i64"],
        "re_rows": ["i64", "i64"],
        "re_close": ["i32", "i64"],
        # piece store (per-task {meta,data} pairs, crash reload)
        "ps_open": ["i64", "cstr"],
        "ps_create_task": ["i32", "i64", "cstr", "u32", "i64"],
        "ps_load_task": ["i32", "i64", "cstr"],
        "ps_write_piece": ["i64", "i64", "cstr", "u32", "u8p", "u32"],
        "ps_read_piece": ["i64", "i64", "cstr", "u32", "u8p", "u32", "i32"],
        "ps_piece_count": ["i64", "i64", "cstr"],
        "ps_piece_bitmap": ["i32", "i64", "cstr", "u8p", "u32"],
        "ps_task_bytes": ["i64", "i64", "cstr"],
        "ps_content_length": ["i64", "i64", "cstr"],
        "ps_piece_size": ["i64", "i64", "cstr"],
        "ps_delete_task": ["i32", "i64", "cstr"],
        # in-engine HTTP piece server
        "ps_serve": ["i64", "i64", "cstr", "u16", "i32"],
        "ps_serve_stop": ["i32", "i64"],
        "ps_serve_stats2": ["i32", "i64", "i64p", "i64p", "i64p", "i64p"],
        "ps_leak_stats": ["i32", "i64p", "i64p"],
        "ps_close": ["i32", "i64"],
        # in-engine piece fetch loop (client half)
        "pf_open": ["i64", "i64", "i32", "cstr"],
        "pf_parent": ["i32", "i64", "i32", "cstr", "u16"],
        "pf_submit": ["i32", "i64", "cstr", "i32", "u32", "u32"],
        "pf_complete": ["i32", "i64", "u8p", "i32", "i32"],
        "pf_pending": ["i64", "i64"],
        "pf_close": ["i32", "i64"],
        # online ingest engine (wire -> trainer hot path)
        "oi_create": ["i64", "i32", "i64", "i32", "i32", "f64", "i64"],
        "oi_feed_download_rows": ["i64", "i64", "f32p", "i64", "f64", "i32"],
        "oi_map_buckets": ["i32", "i64", "f32p", "i64", "f64", "i32p"],
        "oi_lookup": ["i32", "i64", "f32p", "i64", "i32p"],
        "oi_take_edges": ["i64", "i64", "i64", "i32p", "i32p", "f32p", "i64"],
        "oi_eof": ["void", "i64"],
        "oi_node_features": ["i32", "i64", "f32p"],
        "oi_take_recycled": ["i64", "i64", "i32p", "i64"],
        "oi_pending_recycled": ["i64", "i64"],
        "oi_stats": ["i32", "i64", "i64p", "i64p", "i64p", "i64p"],
        "oi_export_state": [
            "i64", "i64", "i32p", "i64p", "f64p", "i32p", "i64",
            "f32p", "f32p", "i64p",
        ],
        "oi_import_state": [
            "i32", "i64", "i32p", "i64p", "f64p", "i32p", "i64",
            "f32p", "f32p", "i64", "i64", "i64",
        ],
        "oi_destroy": ["i32", "i64"],
        # ABI witness probes (DESIGN.md §30)
        "df_abi_manifest": ["cstr"],
        "df_abi_probe_fetchdone": ["i32", "u8p", "u32"],
    },
    # -- packed records crossing the boundary --------------------------------
    # Every struct inside a `#pragma pack(push, 1)` region in native.cpp
    # must appear here with its exact field order; offsets/total size are
    # derived (pack(1) => no padding) and cross-checked against the
    # compiler's sizeof/offsetof through the manifest witness.  A
    # `py_struct` entry pins the ctypes-side mirror: the named class
    # attributes must be derived via record_format()/record_size() below.
    "records": {
        "FetchDone": {
            "fields": [
                ["number", "u32"],
                ["status", "i32"],
                ["length", "u32"],
                ["slot", "i32"],
                ["cost_ns", "i64"],
            ],
            "size": 24,
            "py_struct": {
                "qual": "NativePieceFetcher",
                "fmt_attr": "RECORD",
                "size_attr": "RECORD_SIZE",
            },
        },
        "PieceMeta": {
            "fields": [
                ["number", "u32"],
                ["length", "u32"],
                ["offset", "i64"],
                ["crc", "u32"],
                ["flags", "u32"],
            ],
            "size": 24,
        },
        "TaskHeader": {
            "fields": [
                ["magic", "char4"],
                ["piece_size", "u32"],
                ["content_length", "i64"],
            ],
            "size": 16,
        },
    },
    # -- shared constants ----------------------------------------------------
    # name -> value.  The C side must declare `constexpr <int> kName = v`
    # (or `constexpr char kName[] = "v"` for the wire magics) at
    # namespace scope with exactly this value; the manifest witness
    # re-emits the compiled values.
    "constants": {
        # batched submission / pipelining caps (server burst + client window)
        "kBatchMax": 16,
        "kBatchBytesMax": 524288,
        "kFetchBurstMax": 8,
        "kMaxFetchBody": 67108864,
        # worker / slot / serving caps
        "kFetchWorkersDefault": 4,
        "kFetchWorkersMax": 64,
        "kParentSlotMax": 255,
        "kServeLimitDefault": 64,
        "kLongPollMaxMs": 30000,
        # FetchDone.status codes (0 ok, >0 HTTP passthrough, negatives below)
        "kFetchStatusOk": 0,
        "kFetchStatusConn": -1,
        "kFetchStatusProto": -2,
        "kFetchStatusCommit": -3,
        # catch-all containment sentinel: any extern "C" accessor that
        # swallows an exception returns this (DF021's exactly-once story)
        "kAbiTrap": -125,
        # PieceMeta.flags bits
        "kPieceFlagCommitted": 1,
        "kPieceFlagVerified": 2,
        # wire magics
        "kMagic": "DFC1",
        "kTaskMagic": "DFPS",
    },
    # -- Python-side constant mirrors ----------------------------------------
    # Module-level attributes that restate a shared constant.  DF020
    # requires each to be derived through constant() below (or to be a
    # literal equal to the registry value) — and fails stale mirrors whose
    # attribute no longer exists.
    "constant_mirrors": [
        {
            "constant": "kMagic",
            "file": "dragonfly2_tpu/records/columnar.py",
            "attr": "MAGIC",
            "kind": "bytes",
        },
        {
            "constant": "kLongPollMaxMs",
            "file": "dragonfly2_tpu/rpc/piece_transport.py",
            "attr": "LONG_POLL_MAX_MS",
            "kind": "int",
        },
        {
            "constant": "kBatchBytesMax",
            "file": "dragonfly2_tpu/native/__init__.py",
            "attr": "BATCH_BYTES_MAX",
            "kind": "int",
        },
        {
            "constant": "kBatchMax",
            "file": "dragonfly2_tpu/native/__init__.py",
            "attr": "BATCH_MAX",
            "kind": "int",
        },
        {
            "constant": "kFetchBurstMax",
            "file": "dragonfly2_tpu/native/__init__.py",
            "attr": "FETCH_BURST_MAX",
            "kind": "int",
        },
        {
            "constant": "kMaxFetchBody",
            "file": "dragonfly2_tpu/native/__init__.py",
            "attr": "MAX_FETCH_BODY",
            "kind": "int",
        },
    ],
    # -- out-pointer stats field order ---------------------------------------
    # Multi-out-pointer stats exports: the declared field order IS the
    # ABI.  DF020 checks the arity against the export's i64p parameter
    # count and, when `py_builder` names a bindings method, that the dict
    # literal it returns carries exactly these keys in this order; the
    # witness round-trips distinguishable values through each field.
    "stats_fields": {
        "ps_serve_stats2": {
            "fields": ["pieces", "bytes", "batched", "conns"],
            "py_builder": "NativePieceStore.serve_stats_full",
        },
        "oi_stats": {
            "fields": ["overflow_edges", "evicted_nodes", "next_id", "rows_in"],
            "py_builder": "NativeOnlineIngest.stats",
        },
        "ps_leak_stats": {
            "fields": ["servers", "conns"],
        },
    },
    # -- handle-lifetime discipline ------------------------------------------
    # Which export families hold their objects through the shared_ptr
    # registry pattern (a caller blocked inside the object keeps it alive
    # across a concurrent close) vs raw pointers with explicit
    # leak-on-wedge accounting.  DF020 checks the registry map
    # declarations in native.cpp match.
    "handle_families": {
        "re_": {"registry": "g_records", "lifetime": "shared_ptr"},
        "ps_": {"registry": "g_stores", "lifetime": "raw"},
        "pf_": {"registry": "g_fetchers", "lifetime": "shared_ptr"},
        "oi_": {"registry": "g_oi", "lifetime": "shared_ptr"},
        "df_": {"registry": None, "lifetime": "stateless"},
    },
}

# ---------------------------------------------------------------------------
# Accessor helpers (runtime consumers only — dflint never imports this
# module).  The ctypes bindings and wire-constant mirrors read through
# these instead of restating literals, so DF020 has a single value to pin.
# ---------------------------------------------------------------------------

_FIELD_SIZES = {
    "u8": 1, "i8": 1, "u16": 2, "i16": 2, "u32": 4, "i32": 4,
    "u64": 8, "i64": 8, "f32": 4, "f64": 8, "char4": 4,
}

_FIELD_FMT = {
    "u8": "B", "i8": "b", "u16": "H", "i16": "h", "u32": "I", "i32": "i",
    "u64": "Q", "i64": "q", "f32": "f", "f64": "d", "char4": "4s",
}


def constant(name: str):
    """Shared-constant value by C-side name (e.g. ``kBatchBytesMax``)."""
    return ABI_CONTRACTS["constants"][name]


def record_fields(name: str):
    """[(field, ctype, offset, size), ...] for a declared packed record."""
    out = []
    offset = 0
    for fname, ctype in ABI_CONTRACTS["records"][name]["fields"]:
        size = _FIELD_SIZES[ctype]
        out.append((fname, ctype, offset, size))
        offset += size
    return out


def record_size(name: str) -> int:
    """Declared total size of a packed record (cross-checked: the field
    sizes must sum to it — the witness asserts the compiler agrees)."""
    return ABI_CONTRACTS["records"][name]["size"]


def record_format(name: str) -> str:
    """``struct`` format string (little-endian, packed) for a record."""
    return "<" + "".join(
        _FIELD_FMT[ctype] for _, ctype in ABI_CONTRACTS["records"][name]["fields"]
    )


def expected_manifest(contracts=None) -> dict:
    """The manifest ``df_abi_manifest()`` must emit, as a Python object.

    Shape (mirrored by the X-macro emission in native.cpp):
    ``{"constants": {...}, "exports": {name: [ret, *args]},
    "records": {name: {"fields": [[fname, offset, size], ...],
    "size": N}}, "version": 1}``.
    """
    c = ABI_CONTRACTS if contracts is None else contracts
    records = {}
    for rname, spec in c["records"].items():
        fields = []
        offset = 0
        for fname, ctype in spec["fields"]:
            size = _FIELD_SIZES[ctype]
            fields.append([fname, offset, size])
            offset += size
        records[rname] = {"fields": fields, "size": spec["size"]}
    return {
        "constants": dict(c["constants"]),
        "exports": {k: list(v) for k, v in c["exports"].items()},
        "records": records,
        "version": 1,
    }


def manifest_json(contracts=None) -> str:
    """Canonical JSON bytes of :func:`expected_manifest` — the exact
    string ``df_abi_manifest()`` must return (sorted keys, compact
    separators; field lists stay in declaration order)."""
    import json

    return json.dumps(
        expected_manifest(contracts), sort_keys=True, separators=(",", ":")
    )
