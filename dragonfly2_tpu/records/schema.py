"""Training-record schemas (reference: scheduler/storage/types.go).

Field-for-field parity with the reference's record types so the training
data carries the same signal:

- ``Download``        — one finished (or failed) peer download, with the
                        task, the child host's full machine stats, and up to
                        MAX_PARENTS parents each with up to MAX_PIECES piece
                        cost samples (types.go:189-221, Parent :143-173,
                        Piece :131-138, Host :59-126).
- ``NetworkTopologyRecord`` — one probe-graph snapshot row: a source host and
                        up to MAX_DEST_HOSTS destinations with EMA RTT
                        (types.go:285-297, SrcHost/DestHost :240-283).

Timestamps are nanoseconds since epoch (the reference stores nanosecond
int64s).  Records serialize to/from plain dicts (JSONL storage) and to
fixed-width feature rows (columnar TPU ingest — see features.py).
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from typing import Any, List, get_args, get_origin

from ..utils.hostinfo import BuildInfo, CPUStat, DiskStat, MemoryStat, NetworkStat

# Array caps from the reference's csv[] tags (types.go:168 pieces=10,
# :215 parents=20, :295 destHosts=5). Fixed caps are what make the records
# convertible to static-shape tensors.
MAX_PIECES_PER_PARENT = 10
MAX_PARENTS_PER_DOWNLOAD = 20
MAX_DEST_HOSTS = 5


def now_ns() -> int:
    return time.time_ns()


@dataclass
class TaskRecord:
    id: str = ""
    url: str = ""
    type: str = ""
    content_length: int = -1
    total_piece_count: int = 0
    back_to_source_limit: int = 0
    back_to_source_peer_count: int = 0
    state: str = ""
    created_at: int = 0
    updated_at: int = 0


@dataclass
class HostRecord:
    id: str = ""
    type: str = "normal"
    hostname: str = ""
    ip: str = ""
    port: int = 0
    download_port: int = 0
    os: str = ""
    platform: str = ""
    platform_family: str = ""
    platform_version: str = ""
    kernel_version: str = ""
    concurrent_upload_limit: int = 0
    concurrent_upload_count: int = 0
    upload_count: int = 0
    upload_failed_count: int = 0
    cpu: CPUStat = field(default_factory=CPUStat)
    memory: MemoryStat = field(default_factory=MemoryStat)
    network: NetworkStat = field(default_factory=NetworkStat)
    disk: DiskStat = field(default_factory=DiskStat)
    build: BuildInfo = field(default_factory=BuildInfo)
    scheduler_cluster_id: int = 0
    created_at: int = 0
    updated_at: int = 0


@dataclass
class Piece:
    length: int = 0
    cost: int = 0  # nanoseconds
    created_at: int = 0


@dataclass
class Parent:
    id: str = ""
    tag: str = ""
    application: str = ""
    state: str = ""
    cost: int = 0  # task download duration, nanoseconds
    upload_piece_count: int = 0
    finished_piece_count: int = 0
    host: HostRecord = field(default_factory=HostRecord)
    pieces: List[Piece] = field(default_factory=list)
    created_at: int = 0
    updated_at: int = 0

    def observed_bandwidth(self) -> float:
        """Bytes/sec actually achieved from this parent (the training target)."""
        total_bytes = sum(p.length for p in self.pieces)
        total_ns = sum(p.cost for p in self.pieces)
        if total_ns <= 0:
            return 0.0
        return total_bytes / (total_ns / 1e9)


@dataclass
class DownloadError:
    code: str = ""
    message: str = ""


@dataclass
class Download:
    id: str = ""
    tag: str = ""
    application: str = ""
    state: str = ""
    error: DownloadError = field(default_factory=DownloadError)
    cost: int = 0  # nanoseconds
    finished_piece_count: int = 0
    task: TaskRecord = field(default_factory=TaskRecord)
    host: HostRecord = field(default_factory=HostRecord)
    parents: List[Parent] = field(default_factory=list)
    created_at: int = 0
    updated_at: int = 0


@dataclass
class ProbeStats:
    average_rtt: int = 0  # nanoseconds (EMA — see networktopology store)
    created_at: int = 0
    updated_at: int = 0


@dataclass
class TopoHost:
    """Source/destination host in a topology snapshot (types.go SrcHost/DestHost)."""

    id: str = ""
    type: str = "normal"
    hostname: str = ""
    ip: str = ""
    port: int = 0
    network: NetworkStat = field(default_factory=NetworkStat)
    probes: ProbeStats = field(default_factory=ProbeStats)


@dataclass
class NetworkTopologyRecord:
    id: str = ""
    host: TopoHost = field(default_factory=TopoHost)
    dest_hosts: List[TopoHost] = field(default_factory=list)
    created_at: int = 0


# ---------------------------------------------------------------------------
# dict <-> dataclass (JSONL storage codec)
# ---------------------------------------------------------------------------


def to_dict(record: Any) -> dict:
    return dataclasses.asdict(record)


def _build(cls: type, data: Any) -> Any:
    if dataclasses.is_dataclass(cls) and isinstance(data, dict):
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name not in data:
                continue
            val = data[f.name]
            ftype = f.type if not isinstance(f.type, str) else _resolve(f.name, cls)
            kwargs[f.name] = _convert(ftype, val)
        return cls(**kwargs)
    return data


def _resolve(field_name: str, cls: type) -> type:
    import typing

    hints = typing.get_type_hints(cls)
    return hints[field_name]


def _convert(ftype: Any, val: Any) -> Any:
    origin = get_origin(ftype)
    if origin in (list, List):
        (inner,) = get_args(ftype)
        return [_convert(inner, v) for v in val]
    if dataclasses.is_dataclass(ftype):
        return _build(ftype, val)
    return val


def from_dict(cls: type, data: dict) -> Any:
    return _build(cls, data)
