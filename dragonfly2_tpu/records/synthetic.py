"""Synthetic swarm / probe-graph generator with ground-truth bandwidth.

The reference's test strategy builds multi-peer swarms in-process
(scheduler/scheduling/scheduling_test.go) but has no data generator for the
trainer (nothing to train).  The TPU build needs one: a latent cluster
model whose download records and probe graphs are *learnable* — per-edge
bandwidth is a deterministic function of latent host capacities, load, and
topology plus noise — so training can be verified (MAE falls, learned
ranking beats the rule-based evaluator) and benchmarked at any scale.

Two paths:
- record-level: full Download / NetworkTopologyRecord dataclasses, for
  end-to-end system tests (scheduler storage → announcer → trainer ingest);
- vectorized: numpy row batches in DOWNLOAD_COLUMNS layout at millions of
  rows/sec, for the scale benches (1B-record configs).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..utils import idgen
from ..utils.hostinfo import CPUStat, DiskStat, MemoryStat, NetworkStat
from .schema import (
    Download,
    HostRecord,
    NetworkTopologyRecord,
    Parent,
    Piece,
    ProbeStats,
    TaskRecord,
    TopoHost,
    now_ns,
)

IDC_NAMES = ("idc-a", "idc-b", "idc-c", "idc-d")
REGIONS = ("region-1", "region-2")
PIECE_SIZE = 4 << 20  # 4 MiB default piece size (reference daemon default)


@dataclass
class LatentHost:
    index: int
    id: str
    hostname: str
    ip: str
    type: str            # normal | super | strong | weak
    idc: int
    region: int
    zone: int
    up_capacity: float   # bytes/sec
    down_capacity: float
    cpu_load: float      # [0,1]
    mem_load: float
    disk_load: float
    tcp_conns: int
    upload_conns: int
    concurrent_uploads: int
    upload_limit: int
    upload_count: int
    upload_failed: int

    @property
    def location(self) -> str:
        return f"{REGIONS[self.region]}|zone-{self.zone}|rack-{self.index % 8}"

    @property
    def idc_name(self) -> str:
        return IDC_NAMES[self.idc]


class SyntheticCluster:
    """A latent cluster whose edge bandwidth is ground truth.

    bandwidth(parent→child) =
        min(parent_up / (1 + a·uploads), child_down)
        · idc/region affinity factor · cpu-load factor · lognormal noise
    rtt(src→dst) = base(region, idc, zone) + load jitter.
    """

    def __init__(self, num_hosts: int = 64, seed: int = 0, seed_peer_fraction: float = 0.06):
        self.rng = np.random.default_rng(seed)
        self.num_hosts = num_hosts
        r = self.rng
        n = num_hosts
        self.idc = r.integers(0, len(IDC_NAMES), n)
        self.region = r.integers(0, len(REGIONS), n)
        self.zone = r.integers(0, 4, n)
        # capacities: lognormal around 60 MB/s up, 120 MB/s down; seeds beefier
        self.up_cap = np.exp(r.normal(math.log(60e6), 0.7, n))
        self.down_cap = np.exp(r.normal(math.log(120e6), 0.5, n))
        is_seed = r.random(n) < seed_peer_fraction
        self.host_type = np.where(is_seed, 1, 0)  # 1 => super seed
        self.up_cap[is_seed] *= 4.0
        self.cpu_load = np.clip(r.beta(2, 5, n), 0, 1)
        self.mem_load = np.clip(r.beta(2, 4, n), 0, 1)
        self.disk_load = np.clip(r.beta(2, 6, n), 0, 1)
        self.tcp_conns = r.integers(4, 400, n)
        self.upload_conns = r.integers(0, 60, n)
        self.upload_limit = np.full(n, 50)
        self.concurrent_uploads = r.integers(0, 30, n)
        self.upload_count = r.integers(10, 5000, n)
        self.upload_failed = (self.upload_count * np.clip(r.beta(1, 12, n), 0, 1)).astype(np.int64)
        self.hosts: List[LatentHost] = [self._make_host(i) for i in range(n)]

    def _make_host(self, i: int) -> LatentHost:
        ip = f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"
        hostname = f"host-{i}"
        htype = "super" if self.host_type[i] == 1 else "normal"
        # Identity never changes across drift() rebuilds — cache the hash
        # (drift replay at soak scale would otherwise re-hash 100k ids
        # per epoch).
        if not hasattr(self, "_host_id_cache"):
            self._host_id_cache = {}
        hid = self._host_id_cache.get(i)
        if hid is None:
            hid = idgen.host_id_v2(ip, hostname, seed_peer=htype != "normal")
            self._host_id_cache[i] = hid
        return LatentHost(
            index=i,
            id=hid,
            hostname=hostname,
            ip=ip,
            type=htype,
            idc=int(self.idc[i]),
            region=int(self.region[i]),
            zone=int(self.zone[i]),
            up_capacity=float(self.up_cap[i]),
            down_capacity=float(self.down_cap[i]),
            cpu_load=float(self.cpu_load[i]),
            mem_load=float(self.mem_load[i]),
            disk_load=float(self.disk_load[i]),
            tcp_conns=int(self.tcp_conns[i]),
            upload_conns=int(self.upload_conns[i]),
            concurrent_uploads=int(self.concurrent_uploads[i]),
            upload_limit=int(self.upload_limit[i]),
            upload_count=int(self.upload_count[i]),
            upload_failed=int(self.upload_failed[i]),
        )

    # -- ground truth --------------------------------------------------------

    def bandwidth(self, parent: int, child: int, noise: bool = True) -> float:
        return float(self._bandwidth_vec(np.array([parent]), np.array([child]), noise)[0])

    def _bandwidth_vec(
        self,
        parent: np.ndarray,
        child: np.ndarray,
        noise: bool = True,
        rng: Optional[np.random.Generator] = None,
    ) -> np.ndarray:
        """``rng`` overrides the cluster's SHARED generator for the
        measurement noise — position-deterministic streams (the 1B soak's
        resumable ingest) must not depend on how many draws happened
        before; the noise model itself (σ=0.12 lognormal, 1 KB/s floor
        AFTER noise) lives only here."""
        up = self.up_cap[parent] / (1.0 + 0.15 * self.concurrent_uploads[parent])
        eff = np.minimum(up, self.down_cap[child])
        same_idc = self.idc[parent] == self.idc[child]
        same_region = self.region[parent] == self.region[child]
        factor = np.where(same_idc, 1.0, np.where(same_region, 0.55, 0.25))
        cpu_factor = 1.0 - 0.5 * self.cpu_load[parent] ** 2
        bw = eff * factor * cpu_factor
        if noise:
            bw = bw * np.exp((rng or self.rng).normal(0.0, 0.12, bw.shape))
        return np.maximum(bw, 1e3)

    def rtt_ns(self, src: int, dst: int, noise: bool = True) -> float:
        return float(self._rtt_vec(np.array([src]), np.array([dst]), noise)[0])

    def _rtt_vec(
        self,
        src: np.ndarray,
        dst: np.ndarray,
        noise: bool = True,
        rng: Optional[np.random.Generator] = None,
    ) -> np.ndarray:
        """``rng`` overrides the shared generator for the jitter, like
        ``_bandwidth_vec`` — position-deterministic topology streams (the
        online soak's resumable probe feed) need it."""
        base = np.where(
            self.idc[src] == self.idc[dst],
            0.3e6,  # 0.3 ms intra-idc
            np.where(self.region[src] == self.region[dst], 2e6, 30e6),
        ).astype(np.float64)
        base = base * (1.0 + (self.zone[src] != self.zone[dst]) * 0.5)
        base = base + 0.5e6 * self.cpu_load[dst]
        if noise:
            base = base * np.exp((rng or self.rng).normal(0.0, 0.08, base.shape))
        return base

    # -- record-level generation --------------------------------------------

    def host_record(self, i: int, now: Optional[int] = None) -> HostRecord:
        h = self.hosts[i]
        now = now or now_ns()
        return HostRecord(
            id=h.id,
            type=h.type,
            hostname=h.hostname,
            ip=h.ip,
            port=8002,
            download_port=8001,
            os="linux",
            platform="linux",
            concurrent_upload_limit=h.upload_limit,
            concurrent_upload_count=h.concurrent_uploads,
            upload_count=h.upload_count,
            upload_failed_count=h.upload_failed,
            cpu=CPUStat(logical_count=16, percent=h.cpu_load * 100.0),
            memory=MemoryStat(total=64 << 30, used_percent=h.mem_load * 100.0),
            network=NetworkStat(
                tcp_connection_count=h.tcp_conns,
                upload_tcp_connection_count=h.upload_conns,
                location=h.location,
                idc=h.idc_name,
            ),
            disk=DiskStat(total=1 << 40, used_percent=h.disk_load * 100.0),
            created_at=now,
            updated_at=now,
        )

    def generate_download(self, rng: Optional[np.random.Generator] = None) -> Download:
        r = rng or self.rng
        child = int(r.integers(0, self.num_hosts))
        n_parents = int(r.integers(1, 5))
        parents_idx = r.choice(self.num_hosts, size=n_parents, replace=False)
        parents_idx = parents_idx[parents_idx != child]
        content_length = int(np.exp(r.normal(math.log(256e6), 1.0)))
        total_pieces = max(1, content_length // PIECE_SIZE)
        now = now_ns()
        task = TaskRecord(
            id=idgen.task_id(f"https://example.com/blob/{int(r.integers(0, 1 << 30))}"),
            url="https://example.com/blob",
            type="standard",
            content_length=content_length,
            total_piece_count=int(total_pieces),
            back_to_source_limit=3,
            state="Succeeded",
            created_at=now,
            updated_at=now,
        )
        parents: List[Parent] = []
        for p in parents_idx:
            p = int(p)
            bw = self.bandwidth(p, child)
            n_pieces = int(min(r.integers(1, 11), total_pieces))
            pieces = []
            for _ in range(n_pieces):
                length = int(min(PIECE_SIZE, content_length))
                cost_ns = int(length / bw * 1e9 * float(np.exp(r.normal(0, 0.05))))
                pieces.append(Piece(length=length, cost=max(cost_ns, 1000), created_at=now))
            total_cost = sum(pc.cost for pc in pieces)
            parents.append(
                Parent(
                    id=idgen.peer_id(self.hosts[p].ip, self.hosts[p].hostname),
                    state="Succeeded",
                    cost=total_cost,
                    upload_piece_count=n_pieces,
                    finished_piece_count=n_pieces,
                    host=self.host_record(p, now),
                    pieces=pieces,
                    created_at=now,
                    updated_at=now,
                )
            )
        total_cost = max((p.cost for p in parents), default=0)
        return Download(
            id=idgen.peer_id(self.hosts[child].ip, self.hosts[child].hostname),
            state="Succeeded",
            cost=total_cost,
            finished_piece_count=sum(p.finished_piece_count for p in parents),
            task=task,
            host=self.host_record(child, now),
            parents=parents,
            created_at=now,
            updated_at=now,
        )

    def generate_downloads(self, n: int) -> List[Download]:
        return [self.generate_download() for _ in range(n)]

    def topo_host(self, i: int, avg_rtt: int = 0, now: Optional[int] = None) -> TopoHost:
        h = self.hosts[i]
        now = now or now_ns()
        return TopoHost(
            id=h.id,
            type=h.type,
            hostname=h.hostname,
            ip=h.ip,
            port=8002,
            network=NetworkStat(
                tcp_connection_count=h.tcp_conns,
                upload_tcp_connection_count=h.upload_conns,
                location=h.location,
                idc=h.idc_name,
            ),
            probes=ProbeStats(average_rtt=avg_rtt, created_at=now, updated_at=now),
        )

    def generate_topology_record(self, src: Optional[int] = None) -> NetworkTopologyRecord:
        r = self.rng
        if src is None:
            src = int(r.integers(0, self.num_hosts))
        n_dst = int(min(5, self.num_hosts - 1))
        dsts = r.choice(self.num_hosts, size=n_dst + 1, replace=False)
        dsts = [int(d) for d in dsts if int(d) != src][:n_dst]
        now = now_ns()
        return NetworkTopologyRecord(
            id=f"networktopology-{src}-{int(r.integers(0, 1 << 30))}",
            host=self.topo_host(src, now=now),
            dest_hosts=[self.topo_host(d, avg_rtt=int(self.rtt_ns(src, d)), now=now) for d in dsts],
            created_at=now,
        )

    def generate_topology_records(self, n: int) -> List[NetworkTopologyRecord]:
        return [self.generate_topology_record() for _ in range(n)]

    def drift(self, rng: np.random.Generator) -> None:
        """Evolve the cluster's LOAD state in place (the online-trainer
        story, BASELINE configs[5]): concurrent uploads churn, CPU/mem
        load random-walks, upload tallies grow.  Ground-truth bandwidth
        and RTT both depend on these, so after a drift the topology a
        model was trained on is STALE — the mid-training snapshot
        refresh exists to chase exactly this.  Capacities and placement
        (idc/region/zone) stay fixed: machines don't move racks.

        Takes an explicit rng so a position-seeded caller (the resumable
        1B soak) replays the identical drift sequence.
        """
        n = self.num_hosts
        self.concurrent_uploads = np.clip(
            self.concurrent_uploads + rng.integers(-6, 7, n), 0, 60
        )
        self.cpu_load = np.clip(
            self.cpu_load + rng.normal(0.0, 0.12, n), 0.0, 1.0
        )
        self.mem_load = np.clip(
            self.mem_load + rng.normal(0.0, 0.08, n), 0.0, 1.0
        )
        grown = rng.integers(0, 50, n)
        self.upload_count = self.upload_count + grown
        self.upload_failed = self.upload_failed + (
            grown * np.clip(rng.beta(1, 12, n), 0, 1)
        ).astype(np.int64)
        self.upload_conns = np.clip(
            self.upload_conns + rng.integers(-4, 5, n), 0, 120
        )
        # Record-level views (host_record / hosts[i]) must see the same
        # drifted state as the vectorized path.
        self.hosts = [self._make_host(i) for i in range(n)]

    # -- vectorized generation (bench scale) ---------------------------------

    def _host_feature_matrix(self) -> np.ndarray:
        """[num_hosts, HOST_FEATURE_DIM] matching features.host_features()."""
        n = self.num_hosts
        out = np.zeros((n, 12), dtype=np.float32)
        out[:, 0] = self.cpu_load
        out[:, 1] = self.mem_load
        out[:, 2] = self.disk_load
        out[:, 3] = np.log1p(self.tcp_conns)
        out[:, 4] = np.log1p(self.upload_conns)
        out[:, 5] = np.minimum(self.concurrent_uploads / np.maximum(self.upload_limit, 1), 4.0)
        out[:, 6] = 1.0 - np.minimum(self.upload_failed / np.maximum(self.upload_count, 1), 1.0)
        out[:, 7] = np.log1p(self.upload_count)
        out[:, 8] = (self.host_type == 0).astype(np.float32)
        out[:, 9] = (self.host_type == 1).astype(np.float32)
        return out

    def _bucket_table(self) -> np.ndarray:
        """crc32 hash buckets per host — the SAME node keys as the
        record-level path (features.host_bucket), so vectorized bench data
        and record-level data index one node space."""
        if not hasattr(self, "_bucket_cache"):
            from .features import host_bucket

            self._bucket_cache = np.array(
                [host_bucket(h.id) for h in self.hosts], dtype=np.float32
            )
        return self._bucket_cache

    def _location_affinity_vec(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        # location = region|zone|rack (3 segments)
        same_region = (self.region[a] == self.region[b]).astype(np.float32)
        same_zone = same_region * (self.zone[a] == self.zone[b]).astype(np.float32)
        same_rack = same_zone * ((a % 8) == (b % 8)).astype(np.float32)
        return (same_region + same_zone + same_rack) / 3.0

    def generate_feature_rows(self, n_rows: int, seed: Optional[int] = None) -> np.ndarray:
        """Vectorized batch of training rows in DOWNLOAD_COLUMNS layout."""
        r = np.random.default_rng(seed) if seed is not None else self.rng
        host_f = self._host_feature_matrix()
        parent = r.integers(0, self.num_hosts, n_rows)
        child = r.integers(0, self.num_hosts, n_rows)
        bump = (parent == child).astype(np.int64)
        child = (child + bump) % self.num_hosts

        bw = self._bandwidth_vec(parent, child)
        n_pieces = r.integers(1, 11, n_rows)
        piece_len = np.full(n_rows, PIECE_SIZE, dtype=np.float64)
        content_length = np.exp(r.normal(math.log(256e6), 1.0, n_rows))
        total_pieces = np.maximum(content_length // PIECE_SIZE, 1)
        parent_cost_s = n_pieces * piece_len / bw

        edge = np.zeros((n_rows, 8), dtype=np.float32)
        edge[:, 0] = (self.idc[parent] == self.idc[child]).astype(np.float32)
        edge[:, 1] = self._location_affinity_vec(child, parent)
        edge[:, 2] = np.log1p(n_pieces)
        edge[:, 3] = np.log1p(piece_len)
        edge[:, 4] = np.log1p(content_length)
        edge[:, 5] = np.minimum(n_pieces / total_pieces, 1.0)
        edge[:, 6] = np.log1p(parent_cost_s)
        edge[:, 7] = np.log1p(n_pieces)

        target = np.log1p(bw).astype(np.float32)[:, None]
        buckets = self._bucket_table()
        src_b = buckets[parent][:, None]
        dst_b = buckets[child][:, None]
        return np.concatenate(
            [src_b, dst_b, host_f[child], host_f[parent], edge, target], axis=1
        ).astype(np.float32)

    def probe_edges(self, density: float = 0.1, seed: int = 0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Random directed probe edges: (senders, receivers, rtt_ns). No self loops."""
        r = np.random.default_rng(seed)
        n_edges = int(self.num_hosts * max(self.num_hosts - 1, 1) * density)
        n_edges = max(n_edges, self.num_hosts)
        src = r.integers(0, self.num_hosts, n_edges)
        dst = r.integers(0, self.num_hosts, n_edges)
        keep = src != dst
        src, dst = src[keep], dst[keep]
        return src, dst, self._rtt_vec(src, dst)
