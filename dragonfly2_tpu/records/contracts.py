"""DF012 columnar dtype/shape contract registry — declared ONCE, checked twice.

Every columnar surface the TPU loop depends on (DFC1 record files, the
HostFeatureCache slot matrix, scorer blob arrays, the pallas kernel
outputs) declares its dtype contract here, in one literal dict:

- **statically**, ``tools/dflint/tracerules.py`` (rule DF012) parses this
  file's AST (``ast.literal_eval`` — no import, dflint stays stdlib-only)
  and checks every producer/consumer seam named below: creation-site
  dtype pins for slot columns, constructor/param defaults, float64 leaks
  (x64 is off — a float64 request silently truncates under jit, and on
  host it doubles DFC1 row width), and implicit-float64 array
  constructors (``np.zeros(n)`` defaults to float64).
- **dynamically**, tests import this module and assert the live objects
  agree: ``records.features.DOWNLOAD_COLUMNS`` must equal the declared
  column list, kernel outputs must come back in the declared dtype for
  empty/single/bf16 inputs (tests/test_ops.py), so kernel and contract
  cannot drift apart.

Because dflint evaluates ``CONTRACTS`` with ``ast.literal_eval``, the
dict MUST stay a pure literal: no names, calls, or comprehensions.

Entry shapes (all fields optional except the key):

- ``file``      — repo-relative path the entry's code lives in;
- ``columns``   — the declared column-name list (runtime-asserted);
- ``dtype``     — the contract dtype for produced arrays;
- ``allow``     — extra dtype names reviewed as legitimate in these
                  functions (documented widened intermediate math, e.g.
                  float64 accumulation that rounds once on assignment);
- ``functions`` — producer/consumer functions scanned for dtype leaks;
- ``attrs``     — ``"Class.attr" -> dtype`` creation-site pins;
- ``defaults``  — ``"Class.field"`` / ``"Class.fn.param"`` -> required
                  literal default.
"""

from __future__ import annotations

CONTRACTS = {
    # -- DFC1 download rows (records/features.py + records/columnar.py) ----
    "dfc1.download": {
        "file": "dragonfly2_tpu/records/features.py",
        "dtype": "float32",
        # STRICT: the reviewed float64 intermediates in
        # edge_features_batch carry inline `# dflint: disable=DF012`
        # pragmas instead of a blanket allow, so widening any OTHER
        # construction to float64 still fails by contract name.
        "functions": [
            "download_to_rows",
            "host_features",
            "edge_features",
            "edge_features_batch",
            "mask_post_hoc",
        ],
        "columns": [
            "src_bucket", "dst_bucket",
            "child_cpu_percent", "child_mem_used_percent",
            "child_disk_used_percent", "child_tcp_conn_log",
            "child_upload_tcp_conn_log", "child_upload_load",
            "child_upload_success_ratio", "child_upload_count_log",
            "child_type_normal", "child_type_super", "child_type_strong",
            "child_type_weak",
            "parent_cpu_percent", "parent_mem_used_percent",
            "parent_disk_used_percent", "parent_tcp_conn_log",
            "parent_upload_tcp_conn_log", "parent_upload_load",
            "parent_upload_success_ratio", "parent_upload_count_log",
            "parent_type_normal", "parent_type_super", "parent_type_strong",
            "parent_type_weak",
            "same_idc", "location_affinity", "piece_count_log",
            "mean_piece_size_log", "content_length_log",
            "finished_piece_ratio", "parent_cost_log_s",
            "parent_upload_pieces_log",
            "target_log_bw",
        ],
    },
    "dfc1.topology": {
        "file": "dragonfly2_tpu/records/features.py",
        "dtype": "float32",
        "functions": ["topology_to_rows"],
        "columns": [
            "src_bucket", "dst_bucket", "avg_rtt_norm", "src_tcp_conn_log",
            "dst_tcp_conn_log", "same_idc", "location_affinity", "freshness",
        ],
    },
    "dfc1.file": {
        "file": "dragonfly2_tpu/records/columnar.py",
        "dtype": "float32",
        "defaults": {
            "ColumnarHeader.dtype": "float32",
            "ColumnarWriter.__init__.dtype": "float32",
        },
    },
    # -- HostFeatureCache slot matrix (scheduler/featcache.py) -------------
    "featcache.slots": {
        "file": "dragonfly2_tpu/scheduler/featcache.py",
        "attrs": {
            "HostFeatureCache._matrix": "float32",
            "HostFeatureCache._bucket_col": "int64",
            "HostFeatureCache._idc_col": "int64",
            "HostFeatureCache._loc_col": "int64",
        },
    },
    # -- scorer blob arrays (trainer/export.py) ----------------------------
    "scorer.mlp": {
        "file": "dragonfly2_tpu/trainer/export.py",
        "dtype": "float32",
        # STRICT: feature_snapshot_stats' float64 binning carries inline
        # pragmas (rounds once on return) — see dfc1.download.
        "functions": [
            "_flatten_mlp_params",
            "export_mlp_scorer",
            "export_from_state",
            "feature_snapshot_stats",
            "_pack",
            "load_scorer",
            "MLPScorer.score",
            "MLPScorer._serving_weights",
        ],
    },
    "scorer.gnn": {
        "file": "dragonfly2_tpu/trainer/export.py",
        "dtype": "float32",
        "functions": [
            "export_gnn_scorer",
            "gnn_scorer_to_bytes",
            "GNNScorer.score",
            "GNNScorer._lookup",
            "GNNScorer.__post_init__",
        ],
    },
    # -- TPU kernels (ops/) -------------------------------------------------
    "ops.segment_sum": {
        "file": "dragonfly2_tpu/ops/pallas_segment.py",
        "dtype": "float32",
        # exact=False runs native bf16 MXU passes with f32 accumulate.
        "allow": ["bfloat16"],
        "functions": [
            "bucket_edges_by_block",
            "_segment_kernel",
            "segment_sum_pallas",
            "_segment_sum_bucketed",
            "make_neighbor_gather",
        ],
    },
    "ops.transpose_gather": {
        "file": "dragonfly2_tpu/ops/transpose_gather.py",
        "dtype": "float32",
        "functions": ["build_transpose_table", "make_transpose_gather"],
    },
}
