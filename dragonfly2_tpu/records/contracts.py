"""DF012 columnar dtype/shape contract registry — declared ONCE, checked twice.

Every columnar surface the TPU loop depends on (DFC1 record files, the
HostFeatureCache slot matrix, scorer blob arrays, the pallas kernel
outputs) declares its dtype contract here, in one literal dict:

- **statically**, ``tools/dflint/tracerules.py`` (rule DF012) parses this
  file's AST (``ast.literal_eval`` — no import, dflint stays stdlib-only)
  and checks every producer/consumer seam named below: creation-site
  dtype pins for slot columns, constructor/param defaults, float64 leaks
  (x64 is off — a float64 request silently truncates under jit, and on
  host it doubles DFC1 row width), and implicit-float64 array
  constructors (``np.zeros(n)`` defaults to float64).
- **dynamically**, tests import this module and assert the live objects
  agree: ``records.features.DOWNLOAD_COLUMNS`` must equal the declared
  column list, kernel outputs must come back in the declared dtype for
  empty/single/bf16 inputs (tests/test_ops.py), so kernel and contract
  cannot drift apart.

Because dflint evaluates ``CONTRACTS`` with ``ast.literal_eval``, the
dict MUST stay a pure literal: no names, calls, or comprehensions.

Entry shapes (all fields optional except the key):

- ``file``      — repo-relative path the entry's code lives in;
- ``columns``   — the declared column-name list (runtime-asserted);
- ``dtype``     — the contract dtype for produced arrays;
- ``allow``     — extra dtype names reviewed as legitimate in these
                  functions (documented widened intermediate math, e.g.
                  float64 accumulation that rounds once on assignment);
- ``functions`` — producer/consumer functions scanned for dtype leaks;
- ``attrs``     — ``"Class.attr" -> dtype`` creation-site pins;
- ``defaults``  — ``"Class.field"`` / ``"Class.fn.param"`` -> required
                  literal default.
"""

from __future__ import annotations

CONTRACTS = {
    # -- DFC1 download rows (records/features.py + records/columnar.py) ----
    "dfc1.download": {
        "file": "dragonfly2_tpu/records/features.py",
        "dtype": "float32",
        # STRICT: the reviewed float64 intermediates in
        # edge_features_batch carry inline `# dflint: disable=DF012`
        # pragmas instead of a blanket allow, so widening any OTHER
        # construction to float64 still fails by contract name.
        "functions": [
            "download_to_rows",
            "host_features",
            "edge_features",
            "edge_features_batch",
            "mask_post_hoc",
        ],
        "columns": [
            "src_bucket", "dst_bucket",
            "child_cpu_percent", "child_mem_used_percent",
            "child_disk_used_percent", "child_tcp_conn_log",
            "child_upload_tcp_conn_log", "child_upload_load",
            "child_upload_success_ratio", "child_upload_count_log",
            "child_type_normal", "child_type_super", "child_type_strong",
            "child_type_weak",
            "parent_cpu_percent", "parent_mem_used_percent",
            "parent_disk_used_percent", "parent_tcp_conn_log",
            "parent_upload_tcp_conn_log", "parent_upload_load",
            "parent_upload_success_ratio", "parent_upload_count_log",
            "parent_type_normal", "parent_type_super", "parent_type_strong",
            "parent_type_weak",
            "same_idc", "location_affinity", "piece_count_log",
            "mean_piece_size_log", "content_length_log",
            "finished_piece_ratio", "parent_cost_log_s",
            "parent_upload_pieces_log",
            "target_log_bw",
        ],
    },
    "dfc1.topology": {
        "file": "dragonfly2_tpu/records/features.py",
        "dtype": "float32",
        "functions": ["topology_to_rows"],
        "columns": [
            "src_bucket", "dst_bucket", "avg_rtt_norm", "src_tcp_conn_log",
            "dst_tcp_conn_log", "same_idc", "location_affinity", "freshness",
        ],
    },
    "dfc1.file": {
        "file": "dragonfly2_tpu/records/columnar.py",
        "dtype": "float32",
        "defaults": {
            "ColumnarHeader.dtype": "float32",
            "ColumnarWriter.__init__.dtype": "float32",
        },
    },
    # -- Columnar host store (scheduler/featcache.py, DESIGN.md §18) -------
    # The slot matrix is the SOURCE OF TRUTH for host serving state:
    # every column is creation-site pinned, so widening any of them (or
    # adding an unpinned float64 construction to a producer) fails lint
    # by contract name.  float64 is DELIBERATE for the timestamp and the
    # pre-scaled rule-score columns: they must reproduce the scalar
    # oracle's python-double math bit-for-bit (host code, never traced).
    "featcache.slots": {
        "file": "dragonfly2_tpu/scheduler/featcache.py",
        "dtype": "float32",
        "allow": ["float64"],
        "attrs": {
            "HostFeatureCache._matrix": "float32",
            "HostFeatureCache._bucket_col": "int64",
            "HostFeatureCache._idc_col": "int64",
            "HostFeatureCache._idc_ci_col": "int64",
            "HostFeatureCache._loc_col": "int64",
            "HostFeatureCache._upload_count_col": "int64",
            "HostFeatureCache._upload_failed_col": "int64",
            "HostFeatureCache._concurrent_upload_col": "int64",
            "HostFeatureCache._upload_limit_col": "int64",
            "HostFeatureCache._peer_count_col": "int64",
            "HostFeatureCache._updated_at_col": "float64",
            "HostFeatureCache._rule_w_cols": "float64",
            "HostFeatureCache._pair_col": "int64",
            "HostFeatureCache._type_normal_col": "int8",
            "HostFeatureCache._stamp_col": "int64",
        },
        "functions": [
            "HostFeatureCache.serve",
            "HostFeatureCache.rule_serve",
            "HostFeatureCache.rule_scores",
            "HostFeatureCache.gather_with_buckets",
            "HostFeatureCache._fill_slot_locked",
            "HostFeatureCache._derive_upload_cells",
            "HostFeatureCache.write_upload_state",
            "HostFeatureCache._serve_uncached",
            "HostFeatureCache._rule_serve_uncached",
            "HostFeatureCache._aff_row_locked",
            "HostFeatureCache._pair_row_locked",
        ],
    },
    # -- scorer blob arrays (trainer/export.py) ----------------------------
    "scorer.mlp": {
        "file": "dragonfly2_tpu/trainer/export.py",
        "dtype": "float32",
        # STRICT: feature_snapshot_stats' float64 binning carries inline
        # pragmas (rounds once on return) — see dfc1.download.
        "functions": [
            "_flatten_mlp_params",
            "export_mlp_scorer",
            "export_from_state",
            "feature_snapshot_stats",
            "_pack",
            "load_scorer",
            "MLPScorer.score",
            "MLPScorer._serving_weights",
        ],
    },
    # int8/bf16 post-training-quantized serving variant: the blob packs
    # quantized payloads + per-channel scales next to the drift
    # histograms; scoring runs the float32 DEQUANTIZED weights, so every
    # producer below must stay float32-out (int8/uint16 payloads are the
    # storage form, not a compute dtype).
    "scorer.quantized": {
        "file": "dragonfly2_tpu/trainer/export.py",
        "dtype": "float32",
        "functions": [
            "quantize_scorer",
            "_int8_quantize",
            "_bf16_round",
            "_dequantize_layers",
        ],
    },
    "scorer.gnn": {
        "file": "dragonfly2_tpu/trainer/export.py",
        "dtype": "float32",
        "functions": [
            "export_gnn_scorer",
            "gnn_scorer_to_bytes",
            "GNNScorer.score",
            "GNNScorer._lookup",
            "GNNScorer.__post_init__",
        ],
    },
    # -- TPU kernels (ops/) -------------------------------------------------
    "ops.segment_sum": {
        "file": "dragonfly2_tpu/ops/pallas_segment.py",
        "dtype": "float32",
        # exact=False runs native bf16 MXU passes with f32 accumulate.
        "allow": ["bfloat16"],
        "functions": [
            "bucket_edges_by_block",
            "_segment_kernel",
            "segment_sum_pallas",
            "_segment_sum_bucketed",
            "make_neighbor_gather",
        ],
    },
    "ops.transpose_gather": {
        "file": "dragonfly2_tpu/ops/transpose_gather.py",
        "dtype": "float32",
        "functions": ["build_transpose_table", "make_transpose_gather"],
    },
    # Fused slot-row gather + mask-folded MLP scoring kernel over the
    # columnar host store's slot matrix (DESIGN.md §18): everything is
    # float32 end to end (slot ids int32 are the storage/index form).
    "ops.fused_score": {
        "file": "dragonfly2_tpu/ops/pallas_score.py",
        "dtype": "float32",
        "functions": [
            "fold_post_hoc_weights",
            "split_first_layer",
            "_fused_score_kernel",
            "_fused_score_call",
            "FusedMLPScorer.score",
            "FusedMLPScorer.score_rows",
            "FusedMLPScorer._sync_mirror",
            "_rule_sum_kernel",
            "_rule_sum_call",
            "rule_weighted_sum",
        ],
    },
}
